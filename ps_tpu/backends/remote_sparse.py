"""Cross-process sparse PS: embedding tables served over the van.

The reference's classic async deployment is Wide&Deep: workers push
(row_ids, row_grads) to the sparse servers owning those rows and pull the
rows they need next (SURVEY.md §4c composed with §4d — range-sharded
tables, per-row optimizer state server-side, workers hold only gathered
rows). The in-process :class:`~ps_tpu.kv.sparse.SparseEmbedding` maps this
to mesh shards + ``all_to_all``; THIS module is the cross-process form —
separate, unsynchronized OS processes exchanging framed row messages over
the native van's TCP layer:

- each SERVER process owns a contiguous row range of each table
  (:func:`row_range` — the reference's "range-sharded rows") as a local
  :class:`SparseEmbedding` (its own devices, its own per-row optimizer
  state) and serves ROW_PULL / ROW_PUSH / ROW_PUSH_PULL frames
  (:class:`SparsePSService`). One service can own several named tables
  (Wide&Deep: "deep" [V,D] + "wide" [V,1]) so a worker cycle is one round
  trip per server, not per table;
- each WORKER process runs :class:`RemoteSparseWorker`: route global ids to
  owners by range, fan the per-server requests out concurrently, scatter the
  pulled rows back into id order. Pushes apply immediately server-side
  (async semantics; a per-table version counts applies). A dead server
  surfaces as a typed :class:`ServerFailureError`.

Parity contract (tests/test_remote_sparse.py): each server records its
apply order; replaying that exact (worker, cycle) push sequence — routed by
the same range split — through an in-process ``SparseEmbedding`` of the
server's local size yields a bit-identical table: the wire and the range
partition change nothing about the math.
"""

from __future__ import annotations

import math
import threading
import time as _ptime
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ps_tpu import obs
from ps_tpu.obs import freshness
from ps_tpu.backends.common import (
    DRAIN_TO_TIMEOUT_S,
    BucketedTransportMixin,
    BucketPlan,
    ServerFailureError,
    parse_replica_uri,
    payload_nbytes,
    request_payload,
)
from ps_tpu.backends.remote_async import (
    CheckpointRoundError,
    CheckpointRoundsMixin,
    PendingCycle,
)
from ps_tpu.backends.van_service import (
    VanService,
    log_tail,
    make_history_log,
    resolve_ckpt_dir,
)
from ps_tpu.compress import decode_tree, resolve_spec
from ps_tpu.control import tensor_van as tv


def row_range(shard: int, num_shards: int, total_rows: int) -> Tuple[int, int]:
    """The contiguous global row range ``[lo, hi)`` server ``shard`` of
    ``num_shards`` owns in a ``total_rows``-row table (even ceil split; the
    last shard takes whatever remains — possibly fewer rows than the
    others, or none — the reference's range partition)."""
    if not (0 <= shard < num_shards):
        raise ValueError(f"shard {shard} out of range for {num_shards}")
    per = math.ceil(total_rows / num_shards)
    lo = min(shard * per, total_rows)
    return lo, min(lo + per, total_rows)


#: per-key read-cache invalidation (README "Native observability" /
#: ROADMAP PR-12 follow-up): cached READ entries are tagged with one u64
#: per (table, GLOBAL row id) they cover, and a sparse row apply
#: invalidates only the intersecting entries — untouched hot id-sets keep
#: serving natively. Over these caps the path degrades to the old
#: conservative behavior (an untagged publish drops on any invalidation;
#: an over-cap apply drops everything) rather than burning CPU on tag
#: arithmetic for huge batches.
READ_TAG_CAP = 128
APPLY_TAG_CAP = 512


def _table_hash(name: str) -> int:
    """Stable 64-bit seed per table name (process-local use only — tags
    never cross a process boundary)."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(name.encode(), digest_size=8).digest(), "little")


def _row_tags(table_hash: int, ids: np.ndarray) -> set:
    """One mix-hashed u64 tag per (table, global row id)."""
    mask = (1 << 64) - 1
    return {(table_hash ^ ((int(i) + 0x9E3779B97F4A7C15)
                          * 0xBF58476D1CE4E5B9)) & mask
            for i in np.asarray(ids).ravel().tolist()}


def dedupe_rows_np(ids: np.ndarray, grads: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Worker-side pre-push merge (SURVEY.md §4c: "dedupe/sum duplicate
    rows"): sum duplicate ids' grads so each unique row travels once.
    Host-side (numpy) twin of the in-process ``_dedupe_rows``; summation in
    f32, rounded once back to the wire dtype. Deterministic."""
    if ids.size == 0:
        return ids, grads
    uniq, inv = np.unique(ids, return_inverse=True)
    summed = np.zeros((uniq.size, grads.shape[1]), np.float32)
    np.add.at(summed, inv, grads.astype(np.float32))
    return uniq.astype(ids.dtype), summed.astype(grads.dtype)


class SparsePSService(VanService):
    """Serve named :class:`SparseEmbedding` tables to remote workers.

    Accept/serve/drain machinery (and the stop() guarantees) live in
    :class:`~ps_tpu.backends.van_service.VanService`; this class is the
    protocol: HELLO/ROW_PULL/ROW_PUSH/ROW_PUSH_PULL/STATS over the tables.

    Args:
      tables: ``{name: initialized SparseEmbedding}`` — in sharded mode each
        holds only this server's row range (``row_range`` rows of the
        table's global size).
      port/bind: as :class:`~ps_tpu.backends.remote_async.AsyncPSService`
        (loopback by default; the endpoint is unauthenticated).
      shard/num_shards: position in an N-server row partition (None = one
        server owns every row).
      total_rows: sharded mode only — ``{name: global table rows}``; each
        local table's ``num_rows`` is validated against its
        :func:`row_range` slice so a mis-sliced topology fails loudly at
        construction, and the worker validates coverage at connect time.
    """

    def __init__(self, tables: Dict[str, Any], port: int = 0,
                 bind: str = "127.0.0.1", shard: Optional[int] = None,
                 num_shards: Optional[int] = None,
                 total_rows: Optional[Dict[str, int]] = None,
                 ckpt_root: Optional[str] = None,
                 writev: Optional[bool] = None,
                 shm: Optional[bool] = None,
                 backup: bool = False,
                 record_full_history: bool = False,
                 history: int = 4096,
                 coordinator=None,
                 advertise_host: str = "127.0.0.1",
                 native_loop: Optional[bool] = None,
                 loop_threads: Optional[int] = None):
        if not tables:
            raise ValueError("no tables to serve")
        if (shard is None) != (num_shards is None):
            raise ValueError("pass shard and num_shards together")
        self.shard, self.num_shards = shard, num_shards
        self._tables = dict(tables)
        self._meta: Dict[str, dict] = {}
        for name, emb in self._tables.items():
            if num_shards is None:
                lo, hi = 0, emb.num_rows
                total = emb.num_rows
            else:
                if total_rows is None or name not in total_rows:
                    raise ValueError(
                        f"sharded mode needs total_rows[{name!r}]"
                    )
                total = int(total_rows[name])
                lo, hi = row_range(shard, num_shards, total)
                if emb.num_rows != hi - lo:
                    raise ValueError(
                        f"table {name!r} holds {emb.num_rows} rows but "
                        f"shard {shard}/{num_shards} of {total} owns "
                        f"[{lo}, {hi}) = {hi - lo} rows — init it with "
                        f"row_range(shard, num_shards, total)"
                    )
            self._meta[name] = {
                "total_rows": total, "lo": lo, "hi": hi, "dim": emb.dim,
                "dtype": np.dtype(emb.dtype).str,
            }
        # one lock: a multi-table push applies atomically, and pulls never
        # observe a half-swapped (table, state) pair
        self._lock = threading.Lock()
        self._draining = False
        # checkpoint pause (see AsyncPSService._checkpoint): pushes BLOCK
        # while a coordinated cross-shard snapshot is in flight. Pause
        # hands out an ownership token; later phases must present it
        # (concurrent coordinators serialize instead of tearing snapshots;
        # token bookkeeping lives in VanService).
        self._paused = False
        self._pause_cond = threading.Condition(self._lock)
        self._ckpt_root = ckpt_root
        # seeded from the tables' own (checkpoint-restored) counters, so a
        # server restarted from SparseEmbedding.restore resumes its version
        # stream instead of resetting to 0 (coordinated-checkpoint story)
        self.versions: Dict[str, int] = {
            n: int(emb.push_count) for n, emb in self._tables.items()
        }
        self.rows_applied: Dict[str, int] = {
            n: int(emb.rows_pushed) for n, emb in self._tables.items()
        }
        # freshness plane (README "Online serving & freshness"): one
        # birth stamp per table — the wall/monotonic moment its current
        # version committed (per-table because the staleness a reader of
        # table A feels is A's, not the shard's hottest table's). Rides
        # READ replies as committed state, exactly like the dense
        # service's per-shard stamp. A never-applied table has NO birth:
        # its age is undefined, and two services constructed over the
        # same state must encode byte-identical replies.
        self._births: Dict[str, dict] = {}
        # sparse fused apply (README "Sparse apply"): which tier each
        # table's scatter-apply runs (resolved at SparseEmbedding
        # construction from PS_FUSED_APPLY / the backend), plus the
        # fleet-visible row counter — ps_top's tier/rows columns and the
        # ps_sparse_rows_applied_total family both ride these
        self.fused_tiers: Dict[str, str] = {
            n: getattr(emb, "fused_tier", "off")
            for n, emb in self._tables.items()
        }
        self._rows_counter = obs.default_registry().counter(
            "ps_sparse_rows_applied_total",
            "raw sparse row updates applied (server side)")
        # exactly-once under failover replay + the checkpoint drain round:
        # worker -> (nonce, cycle seq, fanout) of the last applied push.
        # The seq dedups replays; the fanout set tells the coordinator
        # which shards that cycle addressed (sparse cycles route to a
        # SUBSET of shards, so bare counts are not comparable — the seq
        # and fanout make them so).
        self._applied_pseq: Dict[int, tuple] = {}
        self._drain_targets: Dict[int, tuple] = {}
        self._log_lock = threading.Lock()
        # worker id per applied push message — bounded ring unless the
        # replay-parity tests opt into full history
        self.apply_log = make_history_log(record_full_history, history)
        # elastic membership (ps_tpu/elastic): a sparse shard JOINS the
        # coordinator (membership, liveness, load reports, topology
        # discovery for workers) but its row ranges do not live-migrate —
        # a range move would resize live SparseEmbedding tables, which
        # stays checkpoint-restart territory (SURVEY §6). The coordinator
        # refuses to plan moves against kind="sparse" members.
        self._coordinator = coordinator
        self._coord_member = None
        # starts accepting: state ready
        super().__init__(port=port, bind=bind, writev=writev, shm=shm,
                         backup=backup, native_loop=native_loop,
                         loop_threads=loop_threads)
        if coordinator is not None and not backup:
            self._join_coordinator(advertise_host)

    def _join_coordinator(self, advertise_host: str) -> None:
        import time as _time

        from ps_tpu.elastic.member import CoordinatorMember

        # one registry entry per (table, row range) — unique across the
        # range partition, so the coordinator's ownership check holds
        key_bytes = {
            f"{name}@{m['lo']}:{m['hi']}":
                (m["hi"] - m["lo"]) * m["dim"] * np.dtype(m["dtype"]).itemsize
            for name, m in self._meta.items()
        }
        last = {"t": _time.monotonic(), "applies": self.apply_log.total}

        def report_extra() -> dict:
            now = _time.monotonic()
            applies = self.apply_log.total
            dt = max(now - last["t"], 1e-6)
            push_qps = (applies - last["applies"]) / dt
            last.update(t=now, applies=applies)
            return {
                "keys": len(self._meta),
                "nbytes": sum(key_bytes.values()),
                "push_qps": round(push_qps, 2),
                "pull_qps": None,  # reads don't advance a sparse counter
            }

        # fleet telemetry: this service's OWN stats ride the reports as
        # delta-encoded snapshots (see AsyncPSService._join_coordinator)
        from ps_tpu.config import env_flag
        from ps_tpu.obs.collector import collect_telemetry

        telemetry = None
        if env_flag("PS_TELEMETRY", True):
            def telemetry() -> dict:
                return collect_telemetry(self.transport, counters={
                    "ps_applies_total": lambda: self.apply_log.total,
                })

        self._coord_member = CoordinatorMember(
            self._coordinator, f"{advertise_host}:{self.port}",
            key_bytes, kind="sparse", report=report_extra,
            telemetry=telemetry)
        self.table_epoch = self._coord_member.table.epoch

    def stop(self, grace: float = 10.0) -> None:
        m = self._coord_member
        if m is not None:
            m.close(goodbye=True)  # clean leave: membership shows 'left'
        super().stop(grace=grace)

    def kill(self) -> None:
        m = self._coord_member
        if m is not None:
            m.close(goodbye=False)  # SIGKILL-equivalent: beats just stop
        super().kill()

    # -- server internals -----------------------------------------------------

    def _hello_extra(self) -> dict:
        return {
            "tables": self._meta,
            "shard": self.shard,
            "num_shards": self.num_shards,
            "versions": dict(self.versions),
            "epoch": self.epoch,
            "role": self.role,
        }

    def _split(self, tensors: Dict[str, np.ndarray]
               ) -> Dict[str, Dict[str, np.ndarray]]:
        """``{"deep/ids": x}`` frames -> ``{"deep": {"ids": x}}``."""
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for k, v in tensors.items():
            name, _, field = k.partition("/")
            if name not in self._tables:
                raise KeyError(f"unknown table {name!r}")
            out.setdefault(name, {})[field] = v
        return out

    def _localize(self, name: str, ids: np.ndarray) -> np.ndarray:
        m = self._meta[name]
        ids = np.asarray(ids, np.int32)
        if ids.size and (ids.min() < m["lo"] or ids.max() >= m["hi"]):
            raise IndexError(
                f"ids outside this server's {name!r} range "
                f"[{m['lo']}, {m['hi']})"
            )
        return ids - m["lo"]

    def _apply_push(self, worker: int,
                    per_table: Dict[str, Dict[str, np.ndarray]],
                    copy: bool = True,
                    extra: Optional[dict] = None
                    ) -> Tuple[Optional[int], bool]:
        """Apply one multi-table push; returns ``(replication_seq, dedup)``.

        ``extra``'s ``pseq``/``pnonce``/``pfan`` are the worker's cycle
        token: seq at or below the last applied one (same incarnation
        nonce) is a failover replay and is acked WITHOUT applying."""
        extra = extra or {}
        pseq = extra.get("pseq")
        pnonce = extra.get("pnonce")
        pfan = extra.get("pfan")
        # copy out of the recv buffer: the engine keeps references beyond
        # this frame's lifetime (bucket-assembled pushes own their buffers)
        arr = np.array if copy else np.asarray
        todo = []
        wire: Dict[str, np.ndarray] = {}  # global-id form, for replication
        for name, t in per_table.items():
            if "ids" not in t or "grads" not in t:
                raise KeyError(f"push for {name!r} needs ids + grads")
            ids = np.asarray(arr(t["ids"]), np.int32)
            grads = arr(t["grads"])
            todo.append((name, self._localize(name, ids), grads))
            wire[f"{name}/ids"] = ids
            wire[f"{name}/grads"] = grads
        if not todo:
            # push_pull with no rows for this server: nothing applied
            return None, False
        # tiered prefetch (README "Tiered embedding storage"): stage the
        # cold-tier DRAM gather BEFORE taking the apply lock, so it
        # overlaps whatever apply currently holds it — the generation
        # tag discards the slab if that apply moves rows first
        for name, ids, _g in todo:
            pf = getattr(self._tables[name], "prefetch", None)
            if pf is not None:
                pf(ids)
        # per-step breakdown phase tagging (ps_tpu/obs/breakdown.py):
        # the apply — lock wait included — lands in the always-on
        # ps_server_apply_seconds histogram; a traced request also gets
        # a server_apply child span. Dedup replays record nothing.
        t_apply = _ptime.perf_counter()
        apply_s = None
        with obs.tracer().child("server_apply", cat="server"), \
                self._lock:
            # the native admission stamp proves the loop classified this
            # frame strictly fresh at a generation no apply superseded
            # (checked under the lock): the replay check would find
            # nothing, so skip it. Stale/absent stamps take the full
            # check — never a double apply.
            if pseq is not None and not self._admit_fresh_hint():
                last = self._applied_pseq.get(worker)
                if (last is not None and last[0] == pnonce
                        and int(pseq) <= last[1]):
                    self.transport.record_dedup_hit()
                    return None, True
            while (self._paused and not self._draining
                   and not self._admit_while_paused(worker)):
                self._pause_wait_begin()
                try:
                    self._pause_cond.wait()  # checkpoint snapshot in flight
                finally:
                    self._pause_wait_end()
            if self._draining:
                raise RuntimeError("server is draining; push refused")
            import jax as _jax

            t_rows = _ptime.perf_counter()
            rows = 0
            for name, ids, grads in todo:
                self._tables[name].push(ids, grads)
                self.versions[name] += 1
                self.rows_applied[name] += int(ids.size)
                rows += int(ids.size)
            # block on the updated tables INSIDE the timed window: push
            # dispatches async, and an enqueue-time histogram would show
            # no jump when a shard falls off the fused tier — the signal
            # this family exists for. The wait moves, it doesn't add:
            # the next request on this lock syncs on the same queued
            # work (pulls np.asarray the very tables).
            _jax.block_until_ready([self._tables[n].table
                                    for n, _, _ in todo])
            self.transport.record_sparse_apply(
                rows, _ptime.perf_counter() - t_rows)
            self._rows_counter.inc(rows)
            # tiered tables: harvest this push's admission/eviction log
            # for the replication stream (the backup replays it verbatim
            # — tier placement is part of the replicated state) and feed
            # the cold-path latency into its histogram family
            tier_moves = self._pop_tier_moves(todo)
            # invalidation-on-apply (README "Read path"), PER KEY: only
            # cached id-sets intersecting the applied rows drop (their
            # bytes changed); disjoint hot sets keep serving natively.
            # The generation floor still rises for everyone, so an
            # in-flight pre-apply publish is refused either way. A tier
            # move IS a state change: rows it touched beyond the push's
            # own id-set (TTL/CLOCK demotion victims) join the tag set.
            self._invalidate_reads(
                tags=self._move_tags(
                    self._tags_for(per_table, APPLY_TAG_CAP),
                    tier_moves))
            # one birth for every table this push touched (they
            # committed atomically under this lock)
            stamp = freshness.birth_record()
            for name, _ids, _g in todo:
                self._births[name] = stamp
            apply_s = _ptime.perf_counter() - t_apply
            if pseq is not None:
                self._applied_pseq[worker] = (pnonce, int(pseq),
                                              list(pfan or []))
            # republish this worker's settled ledger row + the fresh
            # replay-ack template to the native admission mirror at the
            # post-apply generation (_invalidate_reads above bumped it)
            self._admit_publish(worker)
            self._pause_cond.notify_all()  # a drain_to waiter may watch
            with self._log_lock:
                self.apply_log.append(worker)
            rseq = self._replicate("push", worker, wire, {  # pslint: disable=PSL101 -- deliberate backpressure: a full ack window MUST stall commits under the apply lock (that IS the bounded-lag contract), and stall_timeout degrades a corpse instead of wedging
                "pseq": pseq, "pnonce": pnonce, "pfan": pfan,
                "tier_moves": tier_moves or None,
                "birth": stamp["birth"],
            })
        if apply_s is not None:
            self.transport.record_apply(apply_s)
            # push->first-servable on the primary (the lock is released,
            # the invalidation floor raised): ps_freshness_lag_seconds
            self.transport.record_fresh_lag(
                _ptime.perf_counter() - t_apply)
        return rseq, False

    def _admit_while_paused(self, worker: int) -> bool:
        """Under pause, admit exactly the pushes a drain_to round is
        waiting on: this worker's applied cycle seq still lags its
        cross-shard target (same incarnation)."""
        tgt = self._drain_targets.get(worker)
        if tgt is None:
            return False
        nonce, seq = tgt
        rec = self._applied_pseq.get(worker)
        if rec is None:
            return True  # the targeted cycle's message is still in flight
        return rec[0] == nonce and rec[1] < seq

    # -- zero-upcall push plane (README "Push path") ---------------------------

    def _admit_kind(self):
        # flat ROW_PUSH only: ROW_PUSH_PULL replies with rows (no
        # template can pre-encode them) and bucketed row pushes stage
        return tv.ROW_PUSH

    def _admit_entry(self, worker: int):
        """The scalar sparse ledger row: a worker's last applied cycle is
        one (nonce, seq) — lo == hi == seq, so a replay at/below it is
        settled and anything above is strictly fresh (exactly the pump's
        replay predicate)."""
        rec = self._applied_pseq.get(worker)
        if rec is None or not isinstance(rec[0], str):
            return None
        return rec[0], int(rec[1]), int(rec[1])

    def _admit_ack_bytes(self):
        # byte-for-byte the pump's pure-replay ack (worker id patched by
        # the loop): current table versions, dedup flag set
        return tv.encode(tv.OK, 0, None, extra={
            "versions": dict(self.versions), "dedup": True,
        })

    def _rows_payload(self, worker: int,
                      per_table: Dict[str, Dict[str, np.ndarray]]) -> bytes:
        out = {}
        with self._lock:
            for name, t in per_table.items():
                ids = self._localize(name, t["ids"])
                out[f"{name}/rows"] = np.asarray(self._tables[name].pull(ids))
            versions = dict(self.versions)
        if self.writev:
            # vectored reply: pulled rows go out as live views, unstaged
            return tv.encode_parts(tv.OK, worker, out,
                                   extra={"versions": versions})
        return tv.encode(tv.OK, worker, out, extra={"versions": versions})

    def _read_rows_payload(self, per_table, extra=None) -> bytes:
        """Serve one READ (README "Read path"): side-effect-free row
        fetch, byte-deterministic for byte-identical requests (fixed
        worker id 0) — a hot id-set's reply is therefore shareable from
        the native read cache until any row apply invalidates it. The
        publish generation is captured under the table lock with the
        rows, closing the publish-vs-apply race at the native floor.

        A conditional request (``extra["conds"]`` maps table -> the
        caller's known per-table version) ships only changed bytes:
        per table, the rows whose ``row_version`` stamp exceeds the
        caller's version go out as a delta (``<table>/dids`` global ids
        + ``<table>/drows``); when EVERY requested table is unchanged
        for the caller the whole reply collapses to a NOT_MODIFIED
        version stamp. A table the caller sent no cond for serves full
        rows as before — mixed requests degrade per table, never
        whole-request."""
        conds = None
        if isinstance(extra, dict) and isinstance(extra.get("conds"), dict):
            conds = extra["conds"]
        out = {}
        delta_rows = 0
        with self._lock:
            versions = dict(self.versions)
            gen = self._read_gen_snapshot()
            # per-table birth stamps for every REQUESTED table, captured
            # atomically with the rows (committed state — deterministic
            # for byte-identical requests, so native-cache servable):
            # [wall, monotonic, stamper token] triples, json-able
            births = {}
            for name in per_table:
                b = self._births.get(name)
                if b is not None:
                    births[name] = [b["birth"], b["bmono"], b["bpid"]]
            for name, t in per_table.items():
                v = conds.get(name) if conds is not None else None
                if v is None:
                    ids = self._localize(name, t["ids"])
                    out[f"{name}/rows"] = np.asarray(
                        self._tables[name].pull(ids))
                    continue
                v = int(v)
                if int(versions[name]) <= v:
                    continue  # provably unchanged: nothing to ship
                emb = self._tables[name]
                uids = np.unique(np.asarray(t["ids"], np.int64))
                uids = uids[uids >= 0]
                lids = self._localize(name, uids)
                rv = getattr(emb, "row_version", None)
                if rv is not None:
                    changed = np.asarray(rv)[lids] > v
                    uids, lids = uids[changed], lids[changed]
                if uids.size == 0:
                    continue  # stamp moved, the requested rows did not
                out[f"{name}/dids"] = uids.astype(np.int64)
                out[f"{name}/drows"] = np.asarray(emb.pull(lids))
                delta_rows += int(uids.size)
        vsum = self._vsum(versions)
        # the serve-side age sample judges the OLDEST requested table —
        # the staleness a reader of merged bytes actually feels
        oldest = (min((freshness.from_extra({"births": births}, table=n)
                       for n in births),
                      key=lambda b: b["birth"]) if births else None)
        if conds is not None and not out:
            # every requested table unchanged for this caller: a tiny
            # version-stamp frame — the steady-state revalidation reply
            # (births included: an NM must still REFRESH the age)
            reply = tv.encode(tv.NOT_MODIFIED, 0, None,
                              extra={"versions": versions,
                                     "version": vsum, "births": births})
            self._note_read_snapshot(gen, vsum,
                                     tags=self._tags_for(per_table,
                                                         READ_TAG_CAP))
            self.transport.record_read_served()
            self.transport.record_read_not_modified()
            self._note_serve_age(oldest)
            return reply
        if conds is not None:
            reply = tv.encode(tv.OK, 0, out,
                              extra={"versions": versions,
                                     "version": vsum, "delta": 1,
                                     "births": births})
            self._note_read_snapshot(gen, vsum,
                                     tags=self._tags_for(per_table,
                                                         READ_TAG_CAP))
            self.transport.record_read_served()
            if delta_rows:
                self.transport.record_read_delta_rows(delta_rows)
            self._note_serve_age(oldest)
            return reply
        reply = tv.encode(tv.OK, 0, out, extra={"versions": versions,
                                                "version": vsum,
                                                "births": births})
        # tag the publish with the id-set it covers, so a disjoint row
        # apply leaves the cached entry serving (per-key invalidation)
        self._note_read_snapshot(gen, vsum,
                                 tags=self._tags_for(per_table,
                                                     READ_TAG_CAP))
        self.transport.record_read_served()
        self._note_serve_age(oldest)
        return reply

    def _tbl_hash(self, name: str) -> int:
        cache = getattr(self, "_table_hashes", None)
        if cache is None:
            cache = self._table_hashes = {}
        h = cache.get(name)
        if h is None:
            h = cache[name] = _table_hash(name)
        return h

    def _tags_for(self, per_table, cap: int):
        """Invalidation tags for one request/apply's GLOBAL id-sets, or
        None past ``cap`` (degrade to the conservative untagged/full
        behavior). The id COUNT gates before any hashing — a 100k-row
        embedding push must cost zero tag arithmetic under the apply
        lock, not build-then-discard a 100k-element set."""
        if sum(int(np.asarray(t["ids"]).size)
               for t in per_table.values()) > cap:
            return None
        tags: set = set()
        for name, t in per_table.items():
            tags |= _row_tags(self._tbl_hash(name), t["ids"])
            if len(tags) > cap:
                return None  # unreachable in practice (dedup only
                # shrinks), kept as the hard bound
        return sorted(tags) if tags else None

    def _pop_tier_moves(self, todo) -> Dict[str, dict]:
        """Harvest tiered tables' admission/eviction logs for this push
        (README "Tiered embedding storage") and drain their cold-path
        latencies into ``ps_embed_cold_gather_seconds``. Empty logs are
        dropped from the wire — the backup replays an empty log for an
        absent entry, it NEVER plans moves of its own."""
        tier_moves: Dict[str, dict] = {}
        for name, _ids, _g in todo:
            emb = self._tables[name]
            pop = getattr(emb, "pop_moves", None)
            if pop is None:
                continue
            mv = pop()
            if mv.get("ops"):
                tier_moves[name] = mv
            for s in emb.drain_cold_gather():
                self.transport.record_cold_gather(s)
        return tier_moves

    def _move_tags(self, tags, tier_moves: Dict[str, dict]):
        """Union apply tags with the tags of rows a tier move touched —
        TTL/CLOCK demotion victims are OUTSIDE the push's id-set, and a
        cached read pinned to them must drop like any other applied row.
        ``tags`` None (already degraded to full invalidation) stays
        None; past the cap the union degrades the same way."""
        if tags is None or not tier_moves:
            return tags
        out = set(tags)
        for name, mv in tier_moves.items():
            lo = self._meta[name]["lo"]
            moved = np.asarray([rid for kind, rid, _s in mv["ops"]
                                if kind != "r"], np.int64) + lo
            if moved.size:
                out |= _row_tags(self._tbl_hash(name), moved)
            if len(out) > APPLY_TAG_CAP:
                return None
        return sorted(out)

    @staticmethod
    def _vsum(versions) -> int:
        return int(sum(int(v) for v in versions.values()))

    def _read_version(self):
        # deliberately LOCK-FREE: this runs on the native loop's one pump
        # thread (REPLICA_STATE replies, the gauge tick) and must never
        # queue behind a long apply or a checkpoint save holding _lock.
        # The table set is fixed after construction (values rebind, keys
        # never change) and versions only grow, so an unlocked sum is a
        # monotone-bounded freshness probe — exactly what the staleness
        # contract needs, never a torn structure.
        return self._vsum(self.versions)

    def _handle(self, kind: int, worker: int, tensors, extra) -> bytes:
        if kind == tv.HELLO:
            return tv.encode(tv.OK, worker, None, extra=self._hello_extra())
        elif kind == tv.READ:
            return self._read_rows_payload(self._split(tensors), extra)
        elif kind == tv.ROW_PULL:
            return self._rows_payload(worker, self._split(tensors))
        elif kind == tv.ROW_PUSH:
            tensors = decode_tree(dict(tensors), extra.get("enc"),
                                  stats=self.transport)
            rseq, dedup = self._apply_push(worker, self._split(tensors),
                                           extra=extra)
            self._await_replication(rseq)
            return tv.encode(tv.OK, worker, None, extra={
                "versions": dict(self.versions), "dedup": dedup,
            })
        elif kind == tv.ROW_PUSH_PULL:
            tensors = decode_tree(dict(tensors), extra.get("enc"),
                                  stats=self.transport)
            per = self._split(tensors)
            push = {n: t for n, t in per.items() if "grads" in t}
            pull = {n: {"ids": t["pull_ids"]}
                    for n, t in per.items() if "pull_ids" in t}
            rseq, _ = self._apply_push(worker, push, extra=extra)
            self._await_replication(rseq)
            return self._rows_payload(worker, pull)
        elif kind == tv.ROW_BUCKET_PUSH:
            # one fusion bucket of a multi-bucket row push: stage until the
            # epoch completes, then apply the WHOLE multi-table push
            # atomically (a torn push is never observable — row state may
            # tolerate partial pushes semantically, but a bucketed push
            # commits as the single unit the worker sent)
            tree = self._stage_bucket_push(
                worker, int(extra["bucket"]), int(extra["nbuckets"]),
                int(extra["epoch"]), tensors["raw"], extra["slices"],
                nonce=extra.get("nonce"),
            )
            if tree is None:
                return tv.encode(tv.OK, worker, None,
                                 extra={"staged": int(extra["bucket"])})
            tree = decode_tree(tree, extra.get("enc"), stats=self.transport)
            rseq, dedup = self._apply_push(worker, self._split(tree),
                                           copy=False, extra=extra)
            self._await_replication(rseq)
            return tv.encode(tv.OK, worker, None, extra={
                "versions": dict(self.versions), "committed": True,
                "dedup": dedup,
            })
        elif kind == tv.STATS:
            with self._log_lock:
                # bounded tail + true total, never the unbounded list
                log = log_tail(self.apply_log)
                log_total = self.apply_log.total
            out = {
                "versions": dict(self.versions),
                "rows_applied": dict(self.rows_applied),
                # fused-apply view (README "Sparse apply"): per-table
                # tier + total raw row updates — ps_top's tier/rows
                # columns; a shard off the fused tier is visible here
                "fused": {
                    "tiers": dict(self.fused_tiers),
                    "rows_applied": sum(self.rows_applied.values()),
                },
                # tiered-storage view (README "Tiered embedding
                # storage"): per-table hot-set residency, hit rate and
                # promotion/eviction churn — ps_top's hot%/evict columns
                "tier": {
                    n: emb.tier_stats()
                    for n, emb in self._tables.items()
                    if hasattr(emb, "tier_stats")
                },
                "apply_log": log,
                "apply_log_total": log_total,
                "stale_epochs": self.transport.stale_epochs,
                "stale_epoch_buckets": self.transport.stale_epoch_buckets,
                # extended STATS (ps_tpu/obs): gauges + latency quantiles
                "metrics": self.transport.metrics_snapshot(),
            }
            out.update(self.replica_state())
            if self._coord_member is not None:
                out["table_epoch"] = self.table_epoch
            return tv.encode(tv.OK, worker, None, extra=out)
        elif kind == tv.CHECKPOINT:
            return self._checkpoint(worker, extra)
        return tv.encode(tv.ERR, worker, None,
                         extra={"error": f"bad kind {kind}"})

    def _checkpoint(self, worker: int, extra: dict) -> bytes:
        """Coordinated multi-server checkpoint, the same cross-shard-atomic
        protocol as the dense service (pause -> per-worker applied cycles
        -> cross-shard max -> drain laggards -> save every owned table
        under ``<dir>[/shard<i>]/<table>`` -> resume). A sparse cycle
        routes to a SUBSET of shards (per the row ranges of its ids), so
        bare per-worker counts are not comparable across shards — instead
        every push carries its worker's global cycle seq AND the fanout
        set of shards that cycle addressed. Pause reports each worker's
        last applied (nonce, seq, fanout); the coordinator takes the
        cross-shard max per worker and ``drain_to`` makes every shard in
        that cycle's fanout admit the in-flight sub-push before the save —
        so a cycle is captured on ALL the shards it addressed or none,
        never torn. (Because a worker's cycles are fully acked before the
        next begins, at most the LATEST cycle per worker is ever in
        flight; TCP guarantees its already-fanned-out sub-pushes arrive,
        and the deadline guards a worker that died mid-fanout.) A
        restarted server inits its range-sliced tables, ``restore``s each,
        and the service re-seeds versions from the restored push counts.
        Triggered by :meth:`RemoteSparseWorker.checkpoint_all`; the
        endpoint writes server-host paths and is unauthenticated — another
        reason ``bind`` defaults to loopback."""
        import os

        phase = extra.get("phase", "save")
        if phase == "pause":
            with self._lock:
                token = self._ckpt_issue_token()
                if token is None:
                    return tv.encode(tv.ERR, worker, None,
                                     extra={"error": self._ckpt_busy_error()})
                self._paused = True
                # paused: every push must reach the pump (drain_to decides
                # admission there) — drop the native mirror until resume
                self._admit_drop()
                applied = {str(w): [nonce, seq, fan]
                           for w, (nonce, seq, fan)
                           in self._applied_pseq.items()}
            return tv.encode(tv.OK, worker, None, extra={
                "versions": dict(self.versions), "token": token,
                "applied_pseq": applied,
            })
        if phase == "resume" and extra.get("force"):
            # operator escape hatch for a coordinator that died holding the
            # token (see AsyncPSService._checkpoint)
            with self._lock:
                self._paused = False
                self._ckpt_clear_token()
                self._admit_sync(locked=True)  # pause over: reseed
                self._pause_cond.notify_all()
            return tv.encode(tv.OK, worker, None,
                             extra={"versions": dict(self.versions),
                                    "forced": True})
        err = self._ckpt_token_error(phase, extra)
        if err is not None:
            return tv.encode(tv.ERR, worker, None, extra={"error": err})
        if phase == "drain_to":
            # admit blocked/in-flight sub-pushes until every targeted
            # worker's applied cycle reaches its cross-shard max, then
            # report back (dense drain_to's twin, keyed by cycle seq
            # instead of bare counts). A worker that reconnected mid-round
            # (nonce mismatch) is treated as satisfied — its old
            # incarnation's messages can no longer arrive.
            import time as _time

            targets = {int(w): (t[0], int(t[1]))
                       for w, t in extra.get("targets", {}).items()}
            deadline = _time.monotonic() + float(
                extra.get("timeout", DRAIN_TO_TIMEOUT_S))

            def lagging(w, nonce, seq):
                rec = self._applied_pseq.get(w)
                if rec is None:
                    return True  # the targeted cycle is still in flight
                if rec[0] != nonce:
                    return False  # new incarnation: old stream is dead
                return rec[1] < seq

            with self._lock:
                self._drain_targets = targets
                self._pause_cond.notify_all()
                while any(lagging(w, n, s) for w, (n, s) in targets.items()):
                    left = deadline - _time.monotonic()
                    if left <= 0 or self._draining:
                        self._drain_targets = {}
                        return tv.encode(tv.ERR, worker, None, extra={
                            "error": ("drain_to aborted: server draining"
                                      if self._draining else
                                      "drain_to timed out: a worker's "
                                      "in-flight push never arrived"),
                        })
                    self._pause_cond.wait(left)
                self._drain_targets = {}
            return tv.encode(tv.OK, worker, None,
                             extra={"versions": dict(self.versions)})
        if phase == "resume":
            with self._lock:
                self._paused = False
                self._ckpt_clear_token()
                self._admit_sync(locked=True)  # pause over: reseed the
                # admission mirror from the drained ledger
                self._pause_cond.notify_all()
            return tv.encode(tv.OK, worker, None,
                             extra={"versions": dict(self.versions)})
        base = resolve_ckpt_dir(self._ckpt_root, extra["dir"])
        root = (base if self.num_shards is None
                else os.path.join(base, f"shard{self.shard}"))
        with self._lock:
            for name, emb in self._tables.items():
                emb.save(os.path.join(root, name))
            versions = dict(self.versions)
        return tv.encode(tv.OK, worker, None,
                         extra={"versions": versions, "path": root})

    def _set_draining(self) -> None:
        with self._lock:
            self._draining = True
            self._pause_cond.notify_all()  # paused pushes wake into refusal
        self._invalidate_reads()  # no native hit may outlive the drain
        self._admit_drop()  # nor any native push ack: the pump's
        # draining refusal is the only correct answer now

    # -- shard replication hooks (ps_tpu/replica) -----------------------------

    def _service_lock(self):
        return self._lock

    def _replica_hello_extra(self) -> dict:
        return {
            "kind": "sparse",
            "tables": self._meta,
            "shard": self.shard,
            "num_shards": self.num_shards,
            "versions": dict(self.versions),
            "start_seq": 0,
        }

    def _replica_validate(self, extra: dict) -> Optional[str]:
        if extra.get("kind") != "sparse":
            return (f"replication stream kind {extra.get('kind')!r} does "
                    f"not match this sparse service")
        if extra.get("tables") != self._meta:
            return "primary and backup disagree on table metadata"
        if (extra.get("shard"), extra.get("num_shards")) \
                != (self.shard, self.num_shards):
            return (f"primary is shard {extra.get('shard')}/"
                    f"{extra.get('num_shards')}, backup is shard "
                    f"{self.shard}/{self.num_shards}")
        if {n: int(v) for n, v in (extra.get("versions") or {}).items()} \
                != self.versions:
            return (f"state-point mismatch: primary versions "
                    f"{extra.get('versions')}, backup {self.versions} — "
                    f"start the pair from the same initial tables or a "
                    f"common checkpoint")
        return None

    def _replica_apply(self, op: str, worker: int, tensors, extra) -> None:
        # table lock HELD by the dispatcher: apply inline, never through
        # _apply_push (which re-acquires it)
        if op != "push":
            raise ValueError(f"unknown replica op {op!r}")
        import jax as _jax

        tree = decode_tree(dict(tensors), extra.get("enc"),
                           stats=self.transport)
        split = self._split(tree)
        moves = extra.get("tier_moves") or {}
        t_rows = _ptime.perf_counter()
        rows = 0
        for name, t in split.items():
            ids = self._localize(name, np.array(t["ids"]))
            grads = np.array(t["grads"])  # own memory past the frame
            emb = self._tables[name]
            if hasattr(emb, "pop_moves"):
                # tiered table: REPLAY the primary's recorded
                # admission/eviction log verbatim (an absent entry is an
                # empty log) — the backup never plans moves itself, so
                # its directory stays bitwise-equal to the primary's and
                # a promoted backup's fused applies cannot diverge
                emb.push(ids, grads,
                         moves=moves.get(name) or {"ops": [],
                                                   "hand": None})
                emb.pop_moves()  # a backup replicates nowhere further
            else:
                emb.push(ids, grads)
            self.versions[name] += 1
            self.rows_applied[name] += int(ids.size)
            rows += int(ids.size)
        # the backup's fused tier is observable too: a promoted replica
        # must not silently serve the table-sized path (block inside the
        # window, as in _apply_push — dispatch time is not apply time)
        _jax.block_until_ready([self._tables[n].table for n in split])
        self.transport.record_sparse_apply(
            rows, _ptime.perf_counter() - t_rows)
        self._rows_counter.inc(rows)
        for name in split:
            drain = getattr(self._tables[name], "drain_cold_gather", None)
            if drain is not None:
                for s in drain():
                    self.transport.record_cold_gather(s)
        # per-key, like the primary's apply: a backup's cached reads for
        # disjoint id-sets stay valid across this replicated row apply,
        # with the replayed tier moves' rows joining the tag set
        self._invalidate_reads(tags=self._move_tags(
            self._tags_for(split, APPLY_TAG_CAP), moves))
        # install the PRIMARY's birth for the touched tables (foreign:
        # wall stamp only — a replica's monotonic clock is not the
        # stamper's), so replica-served reads report the push->now age
        b = extra.get("birth")
        stamp = (freshness.foreign_record(float(b)) if b is not None
                 else freshness.birth_record())
        for name in split:
            self._births[name] = stamp
        if extra.get("pseq") is not None:
            self._applied_pseq[worker] = (extra.get("pnonce"),
                                          int(extra["pseq"]),
                                          list(extra.get("pfan") or []))
        with self._log_lock:
            self.apply_log.append(worker)


def serve_sparse(tables: Dict[str, Any], port: int = 0,
                 bind: str = "127.0.0.1", shard: Optional[int] = None,
                 num_shards: Optional[int] = None,
                 total_rows: Optional[Dict[str, int]] = None,
                 ckpt_root: Optional[str] = None,
                 backup: bool = False,
                 native_loop: Optional[bool] = None,
                 loop_threads: Optional[int] = None
                 ) -> "SparsePSService":
    """Expose initialized sparse tables to remote worker processes.

    Single-server: each table holds its full row space, no shard args.
    Multi-server (the reference's range-sharded topology): server ``s`` of
    ``N`` inits each table with ``hi - lo`` rows for
    ``lo, hi = row_range(s, N, total)`` and passes
    ``total_rows={name: total}``. Workers connect with
    :func:`connect_sparse`. ``backup=True`` starts in backup role
    (follows a primary's replication stream until promoted — README
    "Replication & failover")."""
    return SparsePSService(tables, port=port, bind=bind, shard=shard,
                           num_shards=num_shards, total_rows=total_rows,
                           ckpt_root=ckpt_root, backup=backup,
                           native_loop=native_loop,
                           loop_threads=loop_threads)


def connect_sparse(uri: Optional[str], worker: int,
                   tables: Dict[str, Tuple[int, int]],
                   bucket_bytes: Optional[int] = None,
                   pool_size: Optional[int] = None,
                   compress=None, writev: Optional[bool] = None,
                   shm: Optional[bool] = None,
                   shm_bytes: Optional[int] = None,
                   failover_timeout: Optional[float] = None,
                   coordinator=None) -> "RemoteSparseWorker":
    """Join a cross-process sparse PS as worker ``worker``.

    ``uri`` is ``host:port`` or a comma-separated list naming every server
    of the row partition; ``tables`` is ``{name: (total_rows, dim)}`` — the
    worker-side expectation validated against what the servers advertise
    (coverage must be exact and disjoint). ``bucket_bytes`` enables the
    bucketed transport and :meth:`RemoteSparseWorker.push_async`.

    ``compress`` (a codec name or spec dict, see ``ps_tpu.compress``)
    quantizes the ``<table>/grads`` payloads on the wire; ids always travel
    raw (they are int32 — the policy's dtype gate). ``topk`` is refused
    here: row pushes already ARE a sparsification, and error-feedback
    residuals keyed by table would mix different row sets.

    ``writev``/``shm``/``shm_bytes`` select the zero-copy transport lanes
    exactly as in :func:`~ps_tpu.backends.remote_async.connect_async`
    (README "Transport lanes"; env PS_WRITEV / PS_SHM / PS_SHM_BYTES).

    Replica sets: each shard's entry may list replicas separated by ``|``
    (primary first) — a dead primary is retried against the set within
    ``failover_timeout`` seconds (README "Replication & failover").

    Elastic membership (README "Elastic membership"): pass
    ``coordinator="host:port"`` (env PS_COORD_URI) INSTEAD of ``uri`` —
    the worker discovers the server topology from the coordinator's shard
    table (polling until the registered members cover the whole row
    partition) rather than a static URI list. Sparse row ranges do not
    LIVE-migrate (that would resize serving tables — checkpoint-restart
    territory), so the table is discovery + liveness here, not a moving
    assignment."""
    if coordinator is not None:
        addrs, replica_sets = _sparse_topology_from_coordinator(
            coordinator, worker, tables)
    elif uri is None:
        raise ValueError("connect_sparse needs a server uri or a "
                         "coordinator address")
    else:
        addrs, replica_sets = parse_replica_uri(uri)
    return RemoteSparseWorker(addrs, worker, tables,
                              bucket_bytes=bucket_bytes, pool_size=pool_size,
                              compress=compress, writev=writev, shm=shm,
                              shm_bytes=shm_bytes, replica_sets=replica_sets,
                              failover_timeout=failover_timeout,
                              coordinator=coordinator)


def _sparse_topology_from_coordinator(coordinator, worker: int,
                                      tables: Dict[str, Tuple[int, int]],
                                      timeout: float = 30.0):
    """Poll the coordinator until the registered sparse members cover
    every row of every expected table (members register one
    ``<table>@<lo>:<hi>`` key per owned range), then return their URIs
    as the dial list. Connect-time HELLO validation still runs — the
    coordinator bootstraps the topology, the servers prove it."""
    import time as _time

    from ps_tpu.elastic.member import fetch_view

    want = {name: int(total) for name, (total, _d) in tables.items()}
    deadline = _time.monotonic() + timeout
    while True:
        view = fetch_view(coordinator)
        table = view["table"]
        owners = _sparse_owner_shards(table, want)
        if owners:
            return parse_replica_uri(
                ",".join(table["shards"][s] for s in owners))
        if _time.monotonic() >= deadline:
            raise TimeoutError(
                f"coordinator's members never covered the row partition "
                f"of {sorted(want)} within {timeout}s "
                f"({len(table['shards'])} member(s) registered)")
        _time.sleep(0.05)


def _sparse_owner_shards(table: dict,
                         want: Dict[str, int]) -> Optional[List[int]]:
    """The shard indices serving ``want``'s whole row partition, ordered
    by row range (the dial order the worker's ``row_range`` math and the
    servers' HELLO validation both expect) — or ``None`` while coverage
    is incomplete. Assignment keys that are not this fleet's
    ``<table>@<lo>:<hi>`` entries (a dense member's parameter keys on a
    shared coordinator) are SKIPPED, not failed: the coordinator may own
    more than one fleet."""
    spans: Dict[str, List[Tuple[int, int, int]]] = {}
    for k, s in table["assign"].items():
        name, _, rng = k.partition("@")
        if name not in want or ":" not in rng:
            continue  # a dense key (or junk) — not this worker's fleet
        lo, hi = rng.split(":")
        spans.setdefault(name, []).append((int(lo), int(hi), int(s)))
    for name, total in want.items():
        pos = 0
        for lo, hi, _s in sorted(spans.get(name, [])):
            if lo > pos:
                return None  # hole (overlap is HELLO's job to refuse)
            pos = max(pos, hi)
        if pos < total:
            return None
    # dial order = row order of the (alphabetically) first table; every
    # table is sharded over the same members in the same split, which
    # connect-time HELLO validation re-proves against each server
    first = sorted(want)[0]
    owners: List[int] = []
    for _lo, _hi, s in sorted(spans.get(first, [])):
        if s not in owners:
            owners.append(s)
    return owners


class RemoteSparseWorker(BucketedTransportMixin, CheckpointRoundsMixin):
    """A worker NODE of the cross-process sparse PS.

    Routes global row ids to owner servers by range, fans per-server
    requests out concurrently (one round trip per server per cycle), and
    reassembles pulled rows in id order. ``versions[name]`` sums the
    per-server apply counters for the table.

    Transport: as the dense worker — ``bucket_bytes=None`` (default) sends
    each cycle as one frame per server; with it set, row pushes travel as
    fusion buckets striped over ``pool_size`` extra connections per server
    and :meth:`push_async`/:meth:`flush` give non-blocking pushes whose
    transport hides under the next batch's compute."""

    _failure_noun = "sparse PS server"

    def __init__(self, addrs: Sequence[Tuple[str, int]], worker: int,
                 tables: Dict[str, Tuple[int, int]],
                 bucket_bytes: Optional[int] = None,
                 pool_size: Optional[int] = None,
                 compress=None, writev: Optional[bool] = None,
                 shm: Optional[bool] = None,
                 shm_bytes: Optional[int] = None,
                 replica_sets=None,
                 failover_timeout: Optional[float] = None,
                 coordinator=None):
        # elastic membership: remembered so a topology change (a member
        # drained/replaced between this worker's dials) re-discovers the
        # fleet from the coordinator instead of failing the job
        self._coord = coordinator
        self._init_multi(list(addrs), worker, tables,
                         bucket_bytes=bucket_bytes, pool_size=pool_size,
                         compress=compress, writev=writev, shm=shm,
                         shm_bytes=shm_bytes, replica_sets=replica_sets,
                         failover_timeout=failover_timeout)

    def _init_multi(self, addrs: List[Tuple[str, int]], worker: int,
                    tables: Dict[str, Tuple[int, int]],
                    bucket_bytes: Optional[int] = None,
                    pool_size: Optional[int] = None,
                    compress=None, writev: Optional[bool] = None,
                    shm: Optional[bool] = None,
                    shm_bytes: Optional[int] = None,
                    replica_sets=None,
                    failover_timeout: Optional[float] = None) -> None:
        """Fresh dial + validation — ``__init__``'s whole body, factored so
        :meth:`reconnect` re-inits without re-running ``__init__`` on a
        live instance (and so a failed re-dial leaves the identity fields
        intact for a clean retry)."""
        self.worker = worker
        self._addrs = list(addrs)
        self._spec = {n: (int(v), int(d)) for n, (v, d) in tables.items()}
        n = len(self._addrs)
        self._chs: List[tv.Channel] = []
        # per table: sorted [(lo, hi, server_index)]
        self._ranges: Dict[str, List[Tuple[int, int, int]]] = {
            name: [] for name in self._spec
        }
        self._dtype: Dict[str, np.dtype] = {}
        self._versions: Dict[str, List[int]] = {
            name: [0] * n for name in self._spec
        }
        # REAL wire bytes, same counter surface as KVStore / the dense
        # remote worker so TrainMetrics reports GB/s unchanged
        self.bytes_pushed = 0
        self.bytes_pulled = 0
        self.collective_bytes = 0
        self._bytes_lock = threading.Lock()
        # revalidating read snapshots, ONE per server (README "Read
        # path"): a repeat read_rows over the same id-set sends the
        # versions it already holds and merges the server's row DELTA
        # in place of a full refetch (NOT_MODIFIED = reuse as-is)
        from ps_tpu.config import env_flag, env_float
        self.read_conditional = env_flag("PS_READ_CONDITIONAL", True)
        self._read_snaps: Dict[int, dict] = {}
        self._read_lock = threading.Lock()
        # freshness plane (README "Online serving & freshness"): the
        # staleness bound served row ages are judged against (age%)
        self.freshness_slo = env_float("PS_FRESHNESS_SLO", 0.5, lo=1e-3)
        spec = resolve_spec(compress)
        if spec is not None and spec.get("codec") == "topk":
            raise ValueError(
                "topk is not a sparse-push codec: row pushes already "
                "sparsify, and per-table error-feedback residuals would "
                "mix different row sets across steps — use cast16 or int8"
            )
        self._init_transport(bucket_bytes, pool_size, compress=spec,
                             writev=writev, shm=shm, shm_bytes=shm_bytes)
        self._init_failover(replica_sets, failover_timeout)
        try:
            self._connect_and_validate(worker)
        except Exception:
            for ch in self._chs:
                ch.close()
            raise
        self._pool = None
        if n > 1:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=n)
        if self.bucket_bytes is not None:
            try:
                self._open_pumps(range(len(self._addrs)))
            except Exception:
                self._close_transport()
                for ch in self._chs:
                    ch.close()
                raise

    def _connect_and_validate(self, worker: int) -> None:
        n = len(self._addrs)
        for i in range(n):
            # preferred address, or the replica-set member currently
            # serving as primary (a worker may join mid-promotion)
            ch, extra = self._hello_any(i)
            host, port = self._addrs[i]
            self._chs.append(ch)
            self._epochs[i] = int(extra.get("epoch") or 0)
            ns = extra.get("num_shards")
            if ns is not None and int(ns) != n:
                raise ValueError(
                    f"server {i} ({host}:{port}) is shard {extra['shard']}/"
                    f"{ns} but this worker dialed {n} server(s)"
                )
            meta = extra["tables"]
            if sorted(meta) != sorted(self._spec):
                raise ValueError(
                    f"server {i} serves tables {sorted(meta)}, worker "
                    f"expects {sorted(self._spec)}"
                )
            for name, m in meta.items():
                total, dim = self._spec[name]
                if int(m["total_rows"]) != total or int(m["dim"]) != dim:
                    raise ValueError(
                        f"table {name!r}: server {i} says "
                        f"({m['total_rows']}, {m['dim']}), worker expects "
                        f"({total}, {dim})"
                    )
                dt = np.dtype(m["dtype"])
                if self._dtype.setdefault(name, dt) != dt:
                    raise ValueError(f"table {name!r}: servers disagree "
                                     f"on dtype")
                self._ranges[name].append((int(m["lo"]), int(m["hi"]), i))
            # seed from the server's advertised counters (nonzero when the
            # server restarted from a checkpoint), like the dense worker
            for name, v in extra.get("versions", {}).items():
                self._versions[name][i] = int(v)
            # validated: offer the same-host shm lane (TCP on any failure)
            self._chs[i] = self._maybe_upgrade(ch)
        for name, ranges in self._ranges.items():
            ranges.sort()
            total = self._spec[name][0]
            pos, prev = 0, None
            for lo, hi, i in ranges:
                if hi <= lo:
                    continue
                if lo < pos:
                    # overlap (e.g. two unsharded servers, or the same
                    # server dialed twice) — distinct from a hole
                    raise ValueError(
                        f"table {name!r}: rows [{lo}, {min(hi, pos)}) "
                        f"claimed by both server {prev} and server {i} "
                        f"(overlapping partition)"
                    )
                if lo != pos:
                    raise ValueError(
                        f"table {name!r}: rows [{pos}, {lo}) owned by no "
                        f"server (partition has a hole)"
                    )
                pos, prev = hi, i
            if pos != total:
                raise ValueError(
                    f"table {name!r}: rows [{pos}, {total}) owned by no "
                    f"server"
                )

    def versions(self) -> Dict[str, int]:
        """Per-table total applies across all servers."""
        return {n: sum(v) for n, v in self._versions.items()}

    def _validate_failover_hello(self, i: int, extra: dict) -> Optional[str]:
        """A promoted replica must advertise exactly the row ranges the
        worker validated for this shard at connect time."""
        meta = extra.get("tables") or {}
        if sorted(meta) != sorted(self._spec):
            return (f"replica of server {i} serves tables {sorted(meta)}, "
                    f"worker expects {sorted(self._spec)}")
        for name, m in meta.items():
            want = next(((lo, hi) for lo, hi, s in self._ranges[name]
                         if s == i), None)
            got = (int(m["lo"]), int(m["hi"]))
            if want is not None and got != want:
                return (f"replica of server {i} owns {name!r} rows "
                        f"{got}, worker validated {want}")
            total, dim = self._spec[name]
            if int(m["total_rows"]) != total or int(m["dim"]) != dim:
                return (f"replica of server {i} disagrees on {name!r} "
                        f"shape")
            if np.dtype(m["dtype"]) != self._dtype.get(name):
                return f"replica of server {i} disagrees on {name!r} dtype"
        return None

    # -- protocol -------------------------------------------------------------

    def _request(self, i: int, payload):
        try:
            reply = request_payload(self._chs[i], payload)
        except tv.VanError as e:
            host, port = self._addrs[i]
            raise ServerFailureError(
                f"sparse PS server {i} ({host}:{port}) failed mid-job: {e}",
                server=i
            ) from e
        with self._bytes_lock:
            self.bytes_pushed += payload_nbytes(payload)
            self.bytes_pulled += len(reply)
        return reply

    def _fanout(self, payloads: Dict[int, bytes]) -> Dict[int, memoryview]:
        """One concurrent round (same wait-all discipline as the dense
        worker: never abandon an in-flight request on a shared channel)."""
        if self._pool is None or len(payloads) == 1:
            return {i: self._request(i, p) for i, p in payloads.items()}
        import concurrent.futures

        futs = {i: self._pool.submit(self._request, i, p)
                for i, p in payloads.items()}
        concurrent.futures.wait(futs.values())
        return {i: f.result() for i, f in futs.items()}

    def _route(self, name: str, ids: np.ndarray
               ) -> Dict[int, np.ndarray]:
        """``{server: positions into ids}`` for the table's range split."""
        ids = np.asarray(ids)
        out: Dict[int, np.ndarray] = {}
        for lo, hi, i in self._ranges[name]:
            pos = np.nonzero((ids >= lo) & (ids < hi))[0]
            if pos.size:
                out[i] = pos
        covered = sum(p.size for p in out.values())
        if covered != ids.size:
            bad = ids[(ids < 0) | (ids >= self._spec[name][0])]
            raise IndexError(
                f"table {name!r}: ids out of range, e.g. {bad[:3]}"
            )
        return out

    def _check(self, i: int, msg: memoryview):
        kind, _, tensors, extra = tv.decode(msg)
        if kind != tv.OK:
            raise self._reply_error(i, extra)
        for name, v in extra.get("versions", {}).items():
            self._versions[name][i] = int(v)
        return tensors

    def pull(self, requests: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """``{table: global ids [N]} -> {table: rows [N, dim]}`` — one
        concurrent round over the owners, rows reassembled in id order."""
        if self._pending_cycles:
            self.flush()  # a pull must not overtake an in-flight push
        reqs, routes = self._build_pull(requests)
        with self._op("pull") as sp:
            extra = self._tc_extra(None, sp)

            def once():
                msgs = self._fanout({
                    i: tv.encode(tv.ROW_PULL, self.worker, t, extra=extra)
                    for i, t in reqs.items()
                })
                return self._merge_rows(requests, routes, msgs)

            return self._with_failover(once)

    def read_rows(self, requests: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Side-effect-free row read (README "Read path"): like
        :meth:`pull` but over READ frames — no pull event at the server,
        a FIXED worker id 0 and deterministic extra, so byte-identical
        hot id-sets are answered from the server's native read cache
        with zero upcalls on repeat (and by backup replicas, version-
        stamped for the staleness contract). Does not flush in-flight
        cycles: a read observes whatever is committed when it lands.

        With ``PS_READ_CONDITIONAL`` (default on) a repeat read over
        the same per-server id-set is CONDITIONAL: the request carries
        the per-table versions of the rows already in hand, an
        unchanged server answers NOT_MODIFIED (stamp only), and a
        changed one ships a row DELTA — only rows whose per-row
        version moved — merged into the held snapshot in place of a
        full refetch."""
        reqs, routes = self._build_pull(requests)
        with self._op("read"):
            def once():
                payloads, snaps = {}, {}
                for i, t in reqs.items():
                    snap = None
                    if self.read_conditional:
                        sig = self._read_sig(t)
                        with self._read_lock:
                            cand = self._read_snaps.get(i)
                        if cand is not None and cand["sig"] == sig:
                            snap = cand
                    if snap is not None:
                        # "cond" LAST: the native loop's bounded tail
                        # sniff keys the version-floor cache off the
                        # final occurrence of the literal
                        conds = {n: int(v)
                                 for n, v in snap["conds"].items()}
                        payloads[i] = tv.encode(
                            tv.READ, 0, t,
                            extra={"conds": conds,
                                   "cond": int(sum(conds.values()))})
                    else:
                        payloads[i] = tv.encode(tv.READ, 0, t)
                    snaps[i] = snap
                msgs = self._fanout(payloads)
                tensors = {i: self._revalidate(i, reqs[i], snaps[i], m)
                           for i, m in msgs.items()}
                return self._assemble_rows(requests, routes, tensors)

            return self._with_failover(once)

    def _note_rows_age(self, extra: dict, req, tier: str) -> None:
        """One age sample per table this reply served (``now - birth``
        from the reply's per-table stamps): the data age a serving
        caller of :meth:`read_rows` actually feels. No ClockSync rides
        the sparse worker (no version watcher), so cross-process ages
        fall to the wall clock — tagged, and clamped when negative."""
        for key in req:
            b = freshness.from_extra(extra, table=key[: -len("/ids")])
            if b is None:
                continue  # pre-freshness peer (or unknown table)
            age, src, clamped = freshness.age_of(b)
            self.transport.record_read_age(age, src=src, tier=tier,
                                           bound=self.freshness_slo,
                                           clamped=clamped)

    @staticmethod
    def _read_sig(req: Dict[str, np.ndarray]) -> tuple:
        """Hashable identity of one server's id-set: a snapshot only
        revalidates the EXACT request it was built from."""
        return tuple(sorted(
            (k, np.asarray(v).tobytes()) for k, v in req.items()))

    def _revalidate(self, i: int, req, snap, msg) -> Dict[str, np.ndarray]:
        """Turn one server's conditional-read reply into full per-server
        row tensors: NOT_MODIFIED reuses the snapshot, a delta reply
        merges changed rows into a COPY of it (a concurrent reader of
        the old snapshot never sees a torn merge), a full reply
        replaces it. Updates the stored snapshot for the next read."""
        kind, _, tensors, extra = tv.decode(msg)
        if kind == tv.NOT_MODIFIED and snap is not None:
            for name, v in (extra.get("versions") or {}).items():
                self._versions[name][i] = int(v)
            # the stamp's births describe the rows we already hold: an
            # NM revalidation REFRESHES the age of a hot cached id-set
            self._note_rows_age(extra, req, "nm")
            return snap["tensors"]
        if kind != tv.OK:
            raise self._reply_error(i, extra)
        versions = extra.get("versions") or {}
        for name, v in versions.items():
            self._versions[name][i] = int(v)
        self._note_rows_age(extra, req, "wire")
        out: Dict[str, np.ndarray] = {}
        if extra.get("delta") and snap is not None:
            for key in req:
                name = key[: -len("/ids")]
                rk = f"{name}/rows"
                dk = f"{name}/dids"
                if dk in tensors:
                    ids = np.asarray(req[key], np.int64)
                    dids = np.asarray(tensors[dk])  # unique, sorted
                    drows = np.asarray(tensors[f"{name}/drows"])
                    rows = np.array(snap["tensors"][rk])
                    pos = np.nonzero(np.isin(ids, dids))[0]
                    rows[pos] = drows[np.searchsorted(dids, ids[pos])]
                    out[rk] = rows
                elif rk in tensors:
                    out[rk] = np.array(tensors[rk])
                else:  # table unchanged since its cond: keep held rows
                    out[rk] = snap["tensors"][rk]
        else:
            out = {k: np.array(v) for k, v in tensors.items()}
        if self.read_conditional:
            conds = {}
            for key in req:
                name = key[: -len("/ids")]
                v = versions.get(name)
                if v is None or f"{name}/rows" not in out:
                    conds = None
                    break
                conds[name] = int(v)
            if conds is not None:
                with self._read_lock:
                    self._read_snaps[i] = {
                        "sig": self._read_sig(req),
                        "conds": conds, "tensors": out,
                    }
        return out

    def _build_pull(self, requests):
        reqs: Dict[int, Dict[str, np.ndarray]] = {}
        routes: Dict[str, Dict[int, np.ndarray]] = {}
        for name, ids in requests.items():
            ids = np.asarray(ids, np.int32).reshape(-1)
            routes[name] = self._route(name, ids)
            for i, pos in routes[name].items():
                reqs.setdefault(i, {})[f"{name}/ids"] = ids[pos]
        return reqs, routes

    def _merge_rows(self, requests, routes, msgs) -> Dict[str, np.ndarray]:
        tensors = {i: self._check(i, m) for i, m in msgs.items()}
        return self._assemble_rows(requests, routes, tensors)

    def _assemble_rows(self, requests, routes, tensors
                       ) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for name, per_server in routes.items():
            n = int(np.asarray(requests[name]).reshape(-1).shape[0])
            rows = np.zeros((n, self._spec[name][1]), self._dtype[name])
            for i, pos in per_server.items():
                rows[pos] = np.asarray(tensors[i][f"{name}/rows"])
            out[name] = rows
        return out

    def _build_push(self, pushes: Dict[str, Tuple[Any, Any]], dedupe: bool
                    ) -> Dict[int, Dict[str, np.ndarray]]:
        """Per-server ``{"<table>/ids", "<table>/grads"}`` payloads: dedupe
        (optional worker-side merge of duplicate rows, SURVEY.md §4c — the
        server segment-sums either way) then range-route to owners. The one
        assembly both :meth:`push` and :meth:`push_pull` ride."""
        reqs: Dict[int, Dict[str, np.ndarray]] = {}
        for name, (ids, grads) in pushes.items():
            ids = np.asarray(ids, np.int32).reshape(-1)
            grads = np.asarray(grads).reshape(ids.shape[0],
                                             self._spec[name][1])
            if dedupe:
                ids, grads = dedupe_rows_np(ids, grads)
            for i, pos in self._route(name, ids).items():
                reqs.setdefault(i, {})[f"{name}/ids"] = ids[pos]
                reqs[i][f"{name}/grads"] = grads[pos]
        return reqs

    def push(self, pushes: Dict[str, Tuple[Any, Any]],
             dedupe: bool = True) -> None:
        """``{table: (global ids [N], row_grads [N, dim])}`` — owners
        scatter-apply immediately (async semantics). ``dedupe`` merges
        duplicate rows worker-side first, shrinking the wire payload.
        Bucketed transport (``bucket_bytes`` set) slices each server's
        payload into fusion buckets over the pool; the server applies the
        reassembled push as one atomic unit either way."""
        reqs = self._build_push(pushes, dedupe)
        pseq, pfan = self._next_push_seq(), sorted(reqs)
        with self._op("push") as sp:
            tc = sp.wire()
            if self.bucket_bytes is not None:
                self.flush()  # keep per-worker push order == epoch order
                self._with_failover(
                    lambda: self._push_buckets_sync(reqs, pseq=pseq,
                                                    pfan=pfan, tc=tc))
                return

            def once():
                msgs = self._fanout({
                    i: self._encode_serial_push(tv.ROW_PUSH, t,
                                                pseq=pseq, pfan=pfan, tc=tc)
                    for i, t in reqs.items()
                })
                for i, m in msgs.items():
                    self._check(i, m)

            self._with_failover(once)

    def _encode_serial_push(self, kind: int, t: Dict[str, np.ndarray],
                            pseq: Optional[int] = None,
                            pfan: Optional[List[int]] = None, tc=None):
        """One serial row-push frame, grads compressed per the policy
        (zero-copy parts when ``writev`` is on, as in the dense worker),
        tagged with the (nonce, cycle seq, fanout) token — the dedup key
        under failover replay AND what the checkpoint drain round compares
        across shards — plus the op's trace context when sampled."""
        t, enc = self._encode_push_tree(t)
        extra = {}
        if enc:
            extra["enc"] = enc
        if pseq is not None:
            extra.update({"pseq": pseq, "pnonce": self._transport_nonce,
                          "pfan": pfan})
        if tc is not None:
            extra[obs.WIRE_KEY] = tc
        extra = extra or None
        if self.writev:
            return tv.encode_parts(kind, self.worker, t, extra)
        return tv.encode(kind, self.worker, t, extra)

    # -- bucketed, non-blocking push (the pipelined transport) ----------------

    def _push_buckets_sync(self, reqs: Dict[int, Dict[str, np.ndarray]],
                           pseq: Optional[int] = None,
                           pfan: Optional[List[int]] = None,
                           tc=None) -> None:
        """Stripe each server's ``{table/ids, table/grads}`` payload over
        the pool as byte-sliced fusion buckets; the completing bucket's
        reply carries the committed versions. ``pseq``/``pfan`` tag every
        bucket with the logical push's cycle token (dedup + drain)."""
        self._push_epoch += 1
        epoch = self._push_epoch
        futs: List[Tuple[int, Any]] = []
        for i, t in reqs.items():
            # codec pass first (grads compress, int32 ids pass the policy's
            # dtype gate untouched), then contiguous-normalize once per
            # payload (see the dense twin)
            t, enc = self._encode_push_tree(t)
            t = {k: np.ascontiguousarray(v) for k, v in t.items()}
            plan = BucketPlan.from_arrays(t, self.bucket_bytes)
            pumps = self._pumps[i]
            # zero-copy frames when writev is on (see the dense twin)
            enc_bucket = plan.bucket_encoder(self.writev)
            for b in range(plan.nbuckets):
                extra = {"epoch": epoch,
                         "nonce": self._transport_nonce,
                         "pseq": pseq,
                         "pnonce": self._transport_nonce,
                         "pfan": pfan,
                         "enc": enc}
                if tc is not None:
                    extra[obs.WIRE_KEY] = tc
                payload = enc_bucket(tv.ROW_BUCKET_PUSH, self.worker, t, b,
                                     extra=extra)
                futs.append((i, pumps[b % len(pumps)].submit(
                    payload, priority=self._bucket_submit_priority(b))))
        for i, fut in futs:
            reply = self._bucket_reply(i, fut)
            try:
                self._check(i, reply)
            finally:
                self._release_frame(reply)  # even when _check raises

    def push_async(self, pushes: Dict[str, Tuple[Any, Any]],
                   dedupe: bool = True) -> PendingCycle:
        """Non-blocking :meth:`push`: payloads are built now (so the caller
        may mutate its arrays), then a background sender drains the bucket
        queue while the caller computes the next batch. Returns a handle;
        :meth:`flush` (or ``handle.wait()``) is the barrier that restores
        synchronous semantics — per-worker push order is preserved either
        way, so async staleness bounds are unchanged."""
        if self.bucket_bytes is None:
            raise RuntimeError(
                "push_async needs the bucketed transport — construct the "
                "worker with bucket_bytes=... (e.g. 4 << 20)"
            )
        reqs = self._build_push(pushes, dedupe)
        pseq, pfan = self._next_push_seq(), sorted(reqs)
        pending = PendingCycle(self.transport)
        self._track_pending(pending)

        def run():
            import time as _time

            t0 = _time.perf_counter()
            try:
                with self._op("cycle", pseq=pseq) as sp:
                    tc = sp.wire()
                    self._with_failover(lambda: self._push_buckets_sync(
                        reqs, pseq=pseq, pfan=pfan, tc=tc))
            except BaseException as e:
                pending._fail(e)
            else:
                pending._resolve(None)
            finally:
                self.transport.record_cycle(_time.perf_counter() - t0)

        self._bg_executor().submit(run)
        return pending

    def push_pull(self, pushes: Dict[str, Tuple[Any, Any]],
                  requests: Dict[str, Any],
                  dedupe: bool = True) -> Dict[str, np.ndarray]:
        """Push this cycle's row grads and pull the next cycle's rows in ONE
        round trip per server (the sparse async cycle)."""
        if self._pending_cycles:
            self.flush()  # a cycle must not overtake an in-flight push
        reqs = self._build_push(pushes, dedupe)
        # the cycle's fanout is the servers receiving GRADS — a pull-only
        # message must not count toward the drain round's expectations
        pseq, pfan = self._next_push_seq(), sorted(reqs)
        pull_reqs, routes = self._build_pull(requests)
        for i, t in pull_reqs.items():
            for name_ids, v in t.items():
                name = name_ids.split("/")[0]
                reqs.setdefault(i, {})[f"{name}/pull_ids"] = v

        with self._op("push_pull") as sp:
            tc = sp.wire()

            def once():
                msgs = self._fanout({
                    i: self._encode_serial_push(tv.ROW_PUSH_PULL, t,
                                                pseq=pseq, pfan=pfan, tc=tc)
                    for i, t in reqs.items()
                })
                return self._merge_rows(requests, routes, msgs)

            return self._with_failover(once)

    def checkpoint_all(self, path: str) -> Dict[str, int]:
        """Trigger a coordinated, CROSS-SHARD-ATOMIC checkpoint — the
        dense protocol's four phases, keyed by cycle seq instead of bare
        counts: **pause** (every server blocks new applies and reports
        each worker's last applied (nonce, cycle seq, fanout)),
        **drain_to** (a cycle may already be applied on one shard of its
        fanout and in flight to the rest, so every shard in the max
        cycle's fanout admits exactly the in-flight sub-pushes needed to
        reach it; TCP guarantees those arrive), **save** (each server
        writes its tables under ``path``, ``path/shard<i>/<table>`` when
        partitioned), **resume**. The state on disk therefore captures
        whole cycles — a push is on every shard it addressed, or none.
        Returns the per-table total versions at snapshot time. Restart:
        each server re-inits its range-sliced tables, ``restore``s each
        from its shard dir, and serves again (versions resume from the
        restored push counts); workers :meth:`reconnect`."""
        tokens: Dict[int, dict] = {}
        try:
            # pause inside the protected region: a failed round must still
            # resume the surviving servers (never wedge the fleet). As in
            # the dense protocol, pause hands out per-server ownership
            # tokens that every later phase must present.
            try:
                paused = self._checkpoint_round({"dir": path,
                                                 "phase": "pause"})
            except CheckpointRoundError as e:
                tokens = self._ckpt_tokens(e.oks)
                raise
            tokens = self._ckpt_tokens(paused)
            drain = self._drain_targets_from_pause(paused)
            if drain:
                per_server = {
                    i: dict(tokens.get(i, {}), targets=drain.get(i, {}))
                    for i in range(len(self._chs))
                }
                # the drain deadline is the coordinator's to set, and the
                # dense and sparse coordinators must agree on who owns it
                self._checkpoint_round({"dir": path, "phase": "drain_to",
                                        "timeout": DRAIN_TO_TIMEOUT_S},
                                       per_server=per_server)
            saves = self._checkpoint_round({"dir": path, "phase": "save"},
                                           per_server=tokens)
        except BaseException:
            try:
                self._checkpoint_round({"dir": path, "phase": "resume"},
                                       per_server=tokens)
            except Exception:
                pass  # the original failure names the culprit
            raise
        self._checkpoint_round({"dir": path, "phase": "resume"},
                               per_server=tokens)
        totals: Dict[str, int] = {n: 0 for n in self._spec}
        for extra in saves.values():
            for n, v in extra["versions"].items():
                totals[n] += int(v)
        return totals

    def _drain_targets_from_pause(self, paused: Dict[int, dict]
                                  ) -> Dict[int, Dict[int, list]]:
        """The dense drain round's cross-shard max, keyed by cycle seq:
        from each shard's pause report of per-worker (nonce, seq, fanout),
        find each worker's highest applied cycle, and return per-shard
        ``{worker: [nonce, seq]}`` targets for exactly the shards in that
        cycle's fanout that still lag it. Empty = no drain round needed.
        A worker whose nonce differs across shards reconnected mid-round:
        its old incarnation's messages can no longer arrive, so it is
        skipped (its in-flight cycle died with the old connections)."""
        per_shard: Dict[int, dict] = {
            i: extra.get("applied_pseq", {}) for i, extra in paused.items()
        }
        nonces: Dict[int, str] = {}
        best: Dict[int, tuple] = {}  # w -> (seq, fan)
        skip = set()
        for table in per_shard.values():
            for w_s, rec in table.items():
                w, nonce, seq = int(w_s), rec[0], int(rec[1])
                if w in nonces and nonces[w] != nonce:
                    skip.add(w)
                    continue
                nonces[w] = nonce
                if w not in best or seq > best[w][0]:
                    best[w] = (seq, [int(x) for x in (rec[2] or [])])
        targets: Dict[int, Dict[int, list]] = {}
        for i in per_shard:
            t: Dict[int, list] = {}
            for w, (seq, fan) in best.items():
                if w in skip or i not in fan:
                    continue
                rec = per_shard[i].get(str(w))
                applied = (int(rec[1]) if rec is not None
                           and rec[0] == nonces[w] else 0)
                if applied < seq:
                    t[w] = [nonces[w], seq]
            if t:
                targets[i] = t
        return targets

    def reconnect(self, addrs: Optional[Sequence[Tuple[str, int]]] = None
                  ) -> None:
        """Re-dial every server (optionally at new addresses) and
        revalidate the row partition — the worker half of the
        checkpoint/restart story. Cumulative wire counters, transport
        stats, and the push epoch stream survive the re-dial — even a
        FAILED one (TrainMetrics GB/s continuity across a restart, and a
        retried ``reconnect`` just works)."""
        try:
            self.flush()  # land (or fail fast) in-flight background pushes
        except Exception:
            pass  # a dead server is exactly why we are reconnecting
        obs.record_event("reconnect", worker=self.worker,
                         servers=len(self._addrs),
                         new_addrs=addrs is not None)
        saved = self._saved_transport_state()
        self._close_transport()
        for ch in self._chs:
            ch.close()  # dead or stale; no SHUTDOWN owed
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        try:
            self._init_multi(
                list(addrs) if addrs is not None else self._addrs,
                self.worker, dict(self._spec),
                bucket_bytes=self.bucket_bytes, pool_size=self.pool_size,
                compress=self.compress, writev=self.writev, shm=self.shm,
                shm_bytes=self.shm_bytes,
                replica_sets=None if addrs is not None
                else self._replica_sets,
                failover_timeout=self.failover_timeout)
        finally:
            self._restore_transport_state(saved)

    def _on_table_moved(self, err, deadline: float) -> None:
        """Elastic membership: re-discover the fleet from the coordinator
        and re-dial. Sparse ranges never live-migrate, so this only fires
        when membership itself changed — a dead member whose slot a
        replacement took over (the coordinator's exact-key-set takeover)
        — via :meth:`_on_server_lost`. Polls within the failover deadline:
        the replacement may still be booting/registering when the worker
        first notices the death; the re-dial revalidates the whole row
        partition (HELLO)."""
        import time as _time

        if self._coord is None:
            super()._on_table_moved(err, deadline)  # raises: no recovery
        while True:
            budget = deadline - _time.monotonic()
            if budget <= 0:
                raise err
            try:
                addrs, replica_sets = _sparse_topology_from_coordinator(
                    self._coord, self.worker, dict(self._spec),
                    timeout=min(budget, 30.0))
                self.reconnect(addrs)
            except (tv.VanError, OSError, TimeoutError,
                    ServerFailureError, RuntimeError):
                # the table may still name the corpse (replacement not
                # registered yet) — wait it out within the deadline
                _time.sleep(0.2)
                continue
            self._replica_sets = replica_sets
            self.transport.record_table_reroute()
            obs.record_event("table_reroute", worker=self.worker,
                             shards=len(addrs), fleet="sparse")
            return

    def _on_server_lost(self, err, deadline: float) -> None:
        """A member died with no replica to cycle to: with a coordinator,
        the fleet may already have a replacement registered for the same
        row range (slot takeover) — re-discover and re-dial instead of
        surfacing the death; without one, surface it unchanged."""
        if self._coord is None:
            raise err
        self._on_table_moved(err, deadline)

    def stats(self) -> dict:
        msgs = self._fanout({
            i: tv.encode(tv.STATS, self.worker, None)
            for i in range(len(self._chs))
        })
        extras = {}
        for i, m in msgs.items():
            _, _, _, extra = tv.decode(m)
            extras[i] = extra
        if len(self._chs) == 1:
            return extras[0]
        return {"servers": [extras.get(i) for i in range(len(self._chs))],
                "versions": self.versions()}

    def close(self) -> None:
        try:
            if self._pending_cycles:
                self.flush()  # land in-flight pushes before the goodbyes
        except Exception:
            pass  # a dead server must not block the local teardown
        self._close_transport()  # pool channels hang up silently (no goodbye)
        for ch in self._chs:
            try:
                ch.request(tv.encode(tv.SHUTDOWN, self.worker, None))
            except tv.VanError:
                pass
            ch.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
