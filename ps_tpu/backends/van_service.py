"""Shared serve/accept/drain machinery for van-backed PS services.

Both cross-process services (dense-async :class:`AsyncPSService`, sparse
:class:`SparsePSService`) are the same shape: a TCP listener, one serve
thread per worker connection, a request→reply loop over framed tensor
messages, and a stop that must never tear a reply off the wire. This base
class owns that shape; subclasses provide only the protocol dispatch
(:meth:`_handle`) and the commit gate (:meth:`_set_draining`).

The drain contract (VERDICT r4 item 1 — the round-4 flake was ``stop()``
severing a ``PUSH_PULL`` reply mid-send):

1. ``stop()`` first stops admitting connections (accept thread joined,
   listener closed), so the channel set is frozen;
2. then waits (bounded by ``grace``) for every IN-FLIGHT request — one
   whose frame has been received — to finish its reply send;
3. only then flips the draining flag (refusing any straggler commit under
   the subclass's apply lock) and severs the remaining channels, which at
   that point are idle in ``recv``.

A request whose processing has begun (its serve thread is past the
in-flight mark) therefore completes: its push is applied and its reply
arrives intact at the worker. A request still RACING ``stop()`` — sent
concurrently, or whose frame arrived in the microseconds before the sever
(TCP offers no atomic "refuse from now", so that window cannot be closed,
only shrunk — the drain wait double-checks stability across a confirm
delay) — may instead fail at the worker with a typed
:class:`~ps_tpu.backends.remote_async.ServerFailureError`. Workers that
need a clean end must quiesce first by sending ``SHUTDOWN``
(``worker.close()`` does), which is counted in :attr:`goodbyes` so a
server can :meth:`wait_for_goodbyes` before stopping; after the goodbye
no request of that worker can race anything.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from ps_tpu.control import tensor_van as tv


class VanService:
    """One listener + per-connection serve threads over the tensor van.

    Subclass obligations:
      - call ``VanService.__init__(port, bind)`` LAST in your ``__init__``
        (it starts accepting immediately — your state must be ready);
      - implement ``_handle(kind, worker, tensors, extra) -> bytes``
        returning the encoded reply (raise to send an ERR reply);
      - implement ``_set_draining()``: under your apply lock, set the flag
        your commit path checks so no push lands after ``stop()`` returns.
    """

    def __init__(self, port: int = 0, bind: str = "127.0.0.1"):
        self._listener = tv.Listener(port=port, bind=bind)
        self._stop = threading.Event()
        self._chan_lock = threading.Lock()
        self._conns: List[threading.Thread] = []
        self._channels: List[tv.Channel] = []
        # requests whose frame arrived but whose reply is not yet fully
        # sent — what stop() waits out before severing anything
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self.goodbyes = 0  # workers that sent SHUTDOWN (clean departures)
        self._goodbye_cond = threading.Condition()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self._listener.port

    # -- to be provided by the concrete service -------------------------------

    def _handle(self, kind: int, worker: int, tensors, extra) -> bytes:
        raise NotImplementedError

    def _set_draining(self) -> None:
        raise NotImplementedError

    # -- accept / serve --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            ch = self._listener.accept(timeout_ms=200)
            if ch is None:
                continue
            with self._chan_lock:
                # prune finished serve threads so a long-lived server with
                # many reconnects doesn't accumulate dead Thread objects
                # (ident is None = appended but not yet started — keep: an
                # un-started thread also reports is_alive() False)
                self._conns = [t for t in self._conns
                               if t.ident is None or t.is_alive()]
                if self._stop.is_set():
                    ch.close()  # raced stop(): admit nothing new
                    return
                self._channels.append(ch)
                t = threading.Thread(
                    target=self._serve, args=(ch,), daemon=True
                )
                self._conns.append(t)
            t.start()

    def _serve(self, ch: tv.Channel) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = ch.recv()
                except tv.VanError:
                    return  # worker hung up (or stop() severed an idle conn)
                with self._inflight_cond:
                    self._inflight += 1
                try:
                    kind, worker, tensors, extra = tv.decode(msg)
                    goodbye = kind == tv.SHUTDOWN
                    if goodbye:
                        reply = tv.encode(tv.OK, worker, None)
                    else:
                        try:
                            reply = self._handle(kind, worker, tensors, extra)
                        except Exception as e:  # surface to the worker
                            reply = tv.encode(tv.ERR, worker, None,
                                              extra={"error": repr(e)})
                    try:
                        ch.send(reply)
                    except tv.VanError:
                        return  # worker vanished mid-reply; nothing to tell it
                finally:
                    with self._inflight_cond:
                        self._inflight -= 1
                        self._inflight_cond.notify_all()
                if goodbye:
                    with self._goodbye_cond:
                        self.goodbyes += 1
                        self._goodbye_cond.notify_all()
                    return
        finally:
            ch.close()
            with self._chan_lock:
                try:
                    self._channels.remove(ch)
                except ValueError:
                    pass  # stop() snapshot may already hold it

    # -- lifecycle -------------------------------------------------------------

    def wait_for_goodbyes(self, n: int, timeout: Optional[float] = None
                          ) -> bool:
        """Block until ``n`` workers have sent SHUTDOWN (clean departure).

        The quiescence signal a server should wait on before ``stop()``:
        a worker's ``close()`` sends SHUTDOWN only after every one of its
        pushes has been applied AND replied, so ``goodbyes == num_workers``
        implies no request is outstanding anywhere. Returns False on
        timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._goodbye_cond:
            while self.goodbyes < n:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._goodbye_cond.wait(left)
        return True

    def stop(self, grace: float = 10.0) -> None:
        """Graceful drain, then sever. No push is applied after this
        returns, and no reply in flight when it was called is torn.

        The guarantee has two legs: the in-flight wait lets every received
        request finish its reply (bounded by ``grace`` seconds), and the
        subclass's draining flag — set under its apply lock — refuses every
        later commit, so even a serve thread that outlives the bounded
        join (e.g. stuck in a minutes-long jit compile) can never land a
        push after this method returns."""
        self._stop.set()
        # join BEFORE closing: the accept thread may be inside tv_accept on
        # the listener handle (its 200ms timeout bounds the wait); closing
        # first would hand it a freed pointer
        self._accept_thread.join(timeout=5)
        self._listener.close()
        deadline = time.monotonic() + grace
        while True:
            with self._inflight_cond:
                while self._inflight > 0 and time.monotonic() < deadline:
                    self._inflight_cond.wait(deadline - time.monotonic())
                drained = self._inflight == 0
            if not drained:
                logging.getLogger(__name__).warning(
                    "request(s) still in flight after %.1fs drain grace; "
                    "severing anyway", grace
                )
                break
            # stability confirm: a serve thread whose recv JUST returned a
            # frame may not have reached its in-flight mark yet (the window
            # between recv returning and the increment cannot be closed —
            # TCP has no atomic refuse). Re-check after a beat; only a
            # stable zero proceeds to the sever.
            time.sleep(0.05)
            with self._inflight_cond:
                if self._inflight == 0:
                    break
            if time.monotonic() >= deadline:
                break
        self._set_draining()
        with self._chan_lock:
            chans = list(self._channels)
            conns = list(self._conns)
        for ch in chans:
            ch.shutdown()  # non-freeing sever; each serve thread closes own
        for t in conns:
            t.join(timeout=5)
        stragglers = [t for t in conns if t.is_alive()]
        if stragglers:
            logging.getLogger(__name__).warning(
                "%d serve thread(s) outlived the drain join; their pushes "
                "are refused by the draining flag", len(stragglers)
            )
