"""Shared serve/accept/drain machinery for van-backed PS services.

Both cross-process services (dense-async :class:`AsyncPSService`, sparse
:class:`SparsePSService`) are the same shape: a TCP listener, one serve
thread per worker connection, a request→reply loop over framed tensor
messages, and a stop that must never tear a reply off the wire. This base
class owns that shape; subclasses provide only the protocol dispatch
(:meth:`_handle`) and the commit gate (:meth:`_set_draining`).

The drain contract (VERDICT r4 item 1 — the round-4 flake was ``stop()``
severing a ``PUSH_PULL`` reply mid-send):

1. ``stop()`` first stops admitting connections (accept thread joined,
   listener closed), so the channel set is frozen;
2. then waits (bounded by ``grace``) for every IN-FLIGHT request — one
   whose frame has been received — to finish its reply send;
3. only then flips the draining flag (refusing any straggler commit under
   the subclass's apply lock) and severs the remaining channels, which at
   that point are idle in ``recv``.

A request whose processing has begun (its serve thread is past the
in-flight mark) therefore completes: its push is applied and its reply
arrives intact at the worker. A request still RACING ``stop()`` — sent
concurrently, or whose frame arrived in the microseconds before the sever
(TCP offers no atomic "refuse from now", so that window cannot be closed,
only shrunk — the drain wait double-checks stability across a confirm
delay) — may instead fail at the worker with a typed
:class:`~ps_tpu.backends.remote_async.ServerFailureError`. Workers that
need a clean end must quiesce first by sending ``SHUTDOWN``
(``worker.close()`` does), which is counted in :attr:`goodbyes` so a
server can :meth:`wait_for_goodbyes` before stopping; after the goodbye
no request of that worker can race anything.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from ps_tpu import obs
from ps_tpu.backends.common import BucketAssembler, send_payload
from ps_tpu.control import tensor_van as tv
from ps_tpu.utils.metrics import TransportStats


class NotServingError(RuntimeError):
    """Raised inside a handler when this service must refuse the request
    retryably (it was fenced mid-request, or flipped out of primary). The
    serve loop encodes it as an ERR reply carrying ``backup: True`` — the
    same retry-able shape an unpromoted backup sends — so the worker's
    failover loop re-routes instead of failing the job."""


class StaleTableError(RuntimeError):
    """Raised inside a handler when the request's key range is not (or no
    longer) served here because the SHARD TABLE moved — a live rebalance
    migrated keys between shards (ps_tpu/elastic). Typed apart from
    :class:`NotServingError` because the remedy differs: the server is
    healthy, only the assignment changed, so the worker must re-fetch the
    table from the coordinator and re-split — NOT cycle this shard's
    replica set. The serve loop encodes it as an ERR reply carrying
    ``moved: True`` plus this service's ``table_epoch``."""


class RingLog:
    """Fixed-size tail of an append-only log, plus the total count.

    A 10⁶-apply server must not hold O(applies) memory: the services'
    ``apply_log``/``event_log`` default to this ring (most recent
    ``maxlen`` entries retained, ``total`` counts everything ever
    appended). ``record_full_history=True`` swaps in :class:`FullLog`
    for the replay-parity tests, which genuinely need every entry.
    """

    def __init__(self, maxlen: int = 4096):
        import collections

        self._d = collections.deque(maxlen=int(maxlen))
        self.total = 0

    def append(self, x) -> None:
        self._d.append(x)
        self.total += 1

    def __len__(self) -> int:
        return len(self._d)

    def __iter__(self):
        return iter(self._d)

    def __repr__(self) -> str:
        return (f"RingLog(tail={len(self._d)}/{self._d.maxlen}, "
                f"total={self.total})")


class FullLog(list):
    """Unbounded history (``record_full_history=True``): a plain list —
    json-serializable, as the replay-parity subprocess dumps require —
    with the same ``total`` surface as :class:`RingLog`."""

    @property
    def total(self) -> int:
        return len(self)


def make_history_log(record_full_history: bool, maxlen: int = 4096):
    return FullLog() if record_full_history else RingLog(maxlen)


#: how many trailing log entries a STATS reply ships — bounded even when
#: the service records full history, so stats frames never grow multi-MB
STATS_LOG_TAIL = 4096


def log_tail(log, n: int = STATS_LOG_TAIL) -> list:
    """The last ``n`` entries of a RingLog/FullLog as a json-ready list."""
    entries = list(log)
    return entries[-n:] if len(entries) > n else entries


def resolve_ckpt_dir(root: Optional[str], client_dir: str) -> str:
    """Resolve a client-supplied CHECKPOINT dir under the service's
    ``ckpt_root``.

    With no root configured the legacy behavior stands (the client names an
    arbitrary server-host path — loopback-bind deployments only). With a
    root, the client path must be relative and may not escape: absolute
    paths and ``..`` traversals are refused, so an unauthenticated peer can
    never direct the server's filesystem writes outside the root.
    """
    if root is None:
        return client_dir
    if os.path.isabs(client_dir):
        raise ValueError(
            f"absolute checkpoint path {client_dir!r} refused: this server "
            f"confines checkpoints under ckpt_root={root!r} — pass a "
            f"relative path"
        )
    norm = os.path.normpath(client_dir)
    if norm == ".." or norm.startswith(".." + os.sep):
        raise ValueError(
            f"checkpoint path {client_dir!r} escapes ckpt_root={root!r}"
        )
    return os.path.join(root, norm)


class _DaemonPool:
    """Tiny reusable-thread pool of DAEMON workers for the native-loop
    punt path. Spawns a worker per submit only while none is idle (up to
    ``max_workers``); excess tasks queue. Daemon threads on purpose: a
    punted request can legitimately park forever (a pause nothing ever
    resumes after ``kill()``), and that must never block interpreter
    exit — the same reason per-connection serve threads are daemons. No
    shutdown needed or offered; an exhausted-and-parked pool only queues
    work that would have parked anyway, and the draining flag wakes
    parked tasks into refusal on a normal ``stop()``."""

    def __init__(self, max_workers: int = 32, name: str = "pool"):
        import queue

        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._max = int(max_workers)
        self._name = name
        self._lock = threading.Lock()
        self._nthreads = 0
        self._idle = 0

    def submit(self, fn, *args) -> None:
        # spawn BEFORE queuing: if Thread.start() raises (thread
        # exhaustion), the exception must reach the caller with the task
        # NOT enqueued — queue-then-fail would leave a stale task that an
        # existing worker later runs against state the caller's error
        # path already released. `idle` may be stale by one task either
        # way — worst case an extra worker spawns (capped) or a task
        # briefly queues.
        with self._lock:
            if self._idle == 0 and self._nthreads < self._max:
                threading.Thread(
                    target=self._run, daemon=True,
                    name=f"{self._name}-{self._nthreads}",
                ).start()
                self._nthreads += 1  # only counted once start succeeded
        self._q.put((fn, args))

    def _run(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            fn, args = self._q.get()
            with self._lock:
                self._idle -= 1
            try:
                fn(*args)
            except Exception:
                logging.getLogger(__name__).exception(
                    "punted van request failed")


class VanService:
    """One listener + per-connection serve threads over the tensor van.

    Subclass obligations:
      - call ``VanService.__init__(port, bind)`` LAST in your ``__init__``
        (it starts accepting immediately — your state must be ready);
      - implement ``_handle(kind, worker, tensors, extra) -> bytes``
        returning the encoded reply (raise to send an ERR reply);
      - implement ``_set_draining()``: under your apply lock, set the flag
        your commit path checks so no push lands after ``stop()`` returns.
    """

    def __init__(self, port: int = 0, bind: str = "127.0.0.1",
                 writev: Optional[bool] = None,
                 shm: Optional[bool] = None,
                 backup: bool = False,
                 native_loop: Optional[bool] = None,
                 loop_threads: Optional[int] = None):
        from ps_tpu.config import env_flag

        # vectored replies (scatter-gather send of live snapshot tensors —
        # no staging bytearray) and willingness to accept a worker's
        # same-host shared-memory lane offer. None = the PS_WRITEV /
        # PS_SHM env defaults; PS_SHM=0 is the job-wide lane off-switch
        # (workers then never offer, and this side also refuses — note the
        # asymmetric defaults: workers only OFFER on explicit PS_SHM=1,
        # servers ACCEPT offers unless explicitly told not to).
        self.writev = (env_flag("PS_WRITEV", True)
                       if writev is None else bool(writev))
        self._shm_accept = (env_flag("PS_SHM", True)
                            if shm is None else bool(shm))
        # priority bucket scheduling, server half: bucket replies carry
        # their bucket index into the native loop's priority writev drain
        # (front-of-model bytes flush before tail layers' when several
        # conns back up). Off = every reply at priority 0 = FIFO drain.
        self._bucket_priority = env_flag("PS_BUCKET_PRIORITY", True)
        self._listener = tv.Listener(port=port, bind=bind)
        self._stop = threading.Event()
        self._chan_lock = threading.Lock()
        self._conns: List[threading.Thread] = []
        self._channels: List[tv.Channel] = []
        # requests whose frame arrived but whose reply is not yet fully
        # sent — what stop() waits out before severing anything
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        # of those, how many are parked on a checkpoint-pause condition
        # (not executing): stop()'s drain wait subtracts them instead of
        # burning the full grace on requests that can only finish once the
        # draining flag wakes them into refusal
        self._pause_blocked = 0
        # multi-bucket push staging (BUCKET_PUSH / ROW_BUCKET_PUSH): one
        # in-flight epoch per worker; only a COMPLETE epoch is handed to the
        # subclass's apply, so a torn multi-bucket push is never observable
        self._stage_lock = threading.Lock()
        self._push_stage: Dict[int, BucketAssembler] = {}
        # server-side transport accounting: stale-epoch drops (observable
        # via STATS and the worker's StepLogger line), codec seconds for
        # compressed pushes/pulls, and the zero-copy lane counters (shm
        # frames, spill, vectored-reply bytes, recv-pool hit rate)
        self.transport = TransportStats()
        # freshness plane (README "Online serving & freshness"): the
        # staleness bound served ages are judged against — the
        # within-bound share is ps_top's age% column
        from ps_tpu.config import env_float

        self._fresh_slo = env_float("PS_FRESHNESS_SLO", 0.5, lo=1e-3)
        # reusable receive buffers for the serve loop: a request frame is
        # provably dead once its reply is sent, so the loop borrows and
        # returns per request instead of allocating per frame
        self._recv_pool = tv.RecvBufferPool(stats=self.transport)
        # checkpoint ownership token (issued at pause, validated by every
        # later phase, cleared at resume) — shared bookkeeping for both
        # concrete services; mutated only under the subclass's apply lock
        self._ckpt_token: Optional[int] = None
        self._ckpt_seq = 0
        # shard replication & failover (ps_tpu/replica): a backup-role
        # service applies REPLICA_APPEND events and refuses worker traffic
        # until promoted; a primary may attach_backup() a session. The
        # epoch is the shard-table fencing token — promotion bumps it, and
        # workers refuse to re-route to a lower-epoch (zombie) server.
        self.role = "backup" if backup else "primary"
        self.epoch = 0
        # elastic membership (ps_tpu/elastic): the shard-table epoch this
        # service last observed (0 = static topology). Migration commits
        # advance it; stale-table refusals carry it so workers know which
        # epoch to wait past when they re-fetch from the coordinator.
        self.table_epoch = 0
        self._primary_epoch = 0       # backup: learned at REPLICA_HELLO
        self._replica_applied_seq = 0  # backup: last applied stream seq
        self._replica_attached = False
        self._backup_session = None    # primary: BackupSession or None
        self.promote_reason: Optional[str] = None
        self.promotion_s: Optional[float] = None  # promote() call duration
        self.goodbyes = 0  # workers that sent SHUTDOWN (clean departures)
        self._goodbye_cond = threading.Condition()
        # chaos fault-injection hook (ps_tpu/chaos, README "Autopilot &
        # chaos"): when set, every dispatched frame is offered to the
        # hook FIRST — a returned reply short-circuits the handler
        # (blackhole refusals, fault drills); None serves normally.
        # Harness-only surface: nothing in the serving path ever sets it.
        self.chaos = None
        # observability (ps_tpu/obs): request counter into the process
        # registry (several services in one process merge by name), and
        # the opt-in /metrics endpoint — a no-op unless PS_METRICS_PORT
        # is set (start_metrics_server is idempotent per process)
        self._req_counter = obs.default_registry().counter(
            "ps_server_requests_total", "frames served (all kinds)")
        obs.start_metrics_server()
        # native epoll event-loop data plane (README "Native event loop"):
        # accept, frame reads, and scatter-gather reply writes run on a
        # small fixed pool of native threads with the GIL out of the hot
        # path; ONE Python pump thread drains batches of complete requests
        # through the same _dispatch the threaded path uses, so typed
        # refusals, replica forwarding, dedup tokens and tracing spans are
        # identical by construction. None = the PS_VAN_NATIVE_LOOP env
        # default (off); non-Linux (or a van build without the nl_* ABI)
        # falls back to thread-per-connection with a log line.
        from ps_tpu.control import native_loop as nlmod

        want_loop = (env_flag("PS_VAN_NATIVE_LOOP", False)
                     if native_loop is None else bool(native_loop))
        if loop_threads is None:
            # validated service-level read (pslint PSL406): env_int
            # clamps to Config.van_loop_threads' [1, 64] with a warning,
            # so a value that bypassed Config cannot abort server
            # startup with an opaque nl_start failure
            from ps_tpu.config import env_int

            loop_threads = env_int("PS_VAN_LOOP_THREADS", 1, lo=1, hi=64)
        if not (1 <= loop_threads <= 64):
            # explicit arguments clamp to the same bound, same warning
            logging.getLogger(__name__).warning(
                "van loop_threads %d outside [1, 64]; clamping", loop_threads)
            loop_threads = min(max(loop_threads, 1), 64)
        self._nloop = None
        self._pump_thread = None
        self._accept_thread = None
        # requests that can BLOCK commit kinds (a punted CHECKPOINT whose
        # pause flag is not yet visible): raised by the pump before the
        # blocker thread starts, so the punt decision never races the flag
        self._loop_blockers = 0
        # kill() flips this so the pump DROPS queued read-ahead frames
        # instead of applying them — the SIGKILL-equivalence contract
        self._pump_abort = False
        # of _pause_blocked, how many parks sit on native-loop punted
        # threads (each holding one claimed loop body) — the native
        # drain's nl_pending discount
        self._loop_pause_parked = 0
        if want_loop:
            if not nlmod.available():
                logging.getLogger(__name__).warning(
                    "van_native_loop requested but the native event loop "
                    "is unavailable on this platform — falling back to "
                    "thread-per-connection serving"
                )
            else:
                try:
                    self._nloop = nlmod.NativeEventLoop(
                        self._listener, threads=loop_threads)
                except OSError as e:
                    # genuine nl_start failure (fd exhaustion:
                    # epoll/eventfd creation) — the documented contract
                    # is degrade to thread-per-connection, never abort
                    # server startup
                    logging.getLogger(__name__).warning(
                        "native event loop failed to start (%s); falling "
                        "back to thread-per-connection serving", e)
        # high-QPS read path (README "Read path"): generation counter for
        # native read-cache invalidation. Every committed state change a
        # cached READ reply could observe bumps it (_invalidate_reads);
        # READ handlers capture it UNDER their apply lock with the
        # snapshot (_read_gen_snapshot) and the pump publishes the reply
        # at that generation — a put superseded by an apply is refused at
        # the native floor, so a stale reply can never park in the cache.
        self._read_gen = 0
        self._read_gen_lock = threading.Lock()
        self._read_pub = threading.local()
        self._read_pub_version = 0  # version of the last published snapshot
        self._native_read_cache = False
        if self._nloop is not None:
            from ps_tpu.config import env_int as _env_int

            # validated service-level read (pslint PSL406): the native
            # read-cache byte budget; 0 disables hot-key serving and
            # every READ takes the pump path
            cache_bytes = _env_int("PS_NATIVE_READ_CACHE_BYTES", 64 << 20,
                                   lo=0)
            if cache_bytes:
                self._nloop.cache_config(tv.READ, cache_bytes)
                self._native_read_cache = True
        # zero-upcall push plane (README "Push path"): the loop classifies
        # steady-state push frames against a per-worker (nonce, settled
        # seq) ledger mirror ON THE OWNER THREAD — pure replays acked
        # natively with the recorded dedup template, role refusals
        # (backup/fenced) answered natively with the pump's exact bytes,
        # fresh pushes admission-stamped so the apply can skip the dedup
        # scan. off|on|auto (auto == on wherever the loop runs); the pump
        # path stays the drop-in parity oracle, and blocker kinds,
        # aggregator rounds, and paused/draining states always punt.
        self._native_admit = False
        if self._nloop is not None:
            from ps_tpu.config import env_str as _env_str

            # validated service-level read (pslint PSL406): mirrors
            # Config.push_native_admit; an unknown token warns and keeps
            # the default instead of taking the service down
            admit_mode = (_env_str("PS_PUSH_NATIVE_ADMIT", "auto")
                          or "auto").strip().lower()
            if admit_mode not in ("off", "on", "auto"):
                logging.getLogger(__name__).warning(
                    "PS_PUSH_NATIVE_ADMIT=%r not in off|on|auto; keeping "
                    "'auto'", admit_mode)
                admit_mode = "auto"
            admit_kind = self._admit_kind()
            if admit_mode != "off" and admit_kind is not None:
                self._nloop.admit_config(admit_kind)
                self._native_admit = True
                # seed the mirror from the engine's settled ledger (a
                # checkpoint-restored or backup service starts with
                # history; a fresh one arms the role refusal only)
                self._admit_sync()
        # in-loop native telemetry (README "Native observability"):
        # PS_NL_STATS arms the loop's own lock-free histograms (frame
        # read, queue wait, native read-hit serve, tail flush — the
        # ps_nl_* families) and PS_NL_SLOW_FRAME_MS the slow-frame
        # watchdog; both validated service-level reads (pslint PSL406),
        # strict=False — observability knobs must never take a service
        # down with them
        self._nl_stats = False
        if self._nloop is not None:
            from ps_tpu.config import env_float as _env_float

            self._nl_stats = env_flag("PS_NL_STATS", True)
            slow_ms = _env_float("PS_NL_SLOW_FRAME_MS", 250.0, lo=0.0,
                                 strict=False)
            self._nloop.telemetry_config(
                self._nl_stats,
                int(slow_ms * 1e6) if self._nl_stats else 0)
        if self._nloop is not None:
            self._loop_conn_gauge = obs.default_registry().gauge(
                "ps_van_live_connections",
                "connections registered in the native event loop")
            self._loop_iter_gauge = obs.default_registry().gauge(
                "ps_van_loop_iterations_total",
                "cumulative native-loop epoll iterations")
            self._loop_req_gauge = obs.default_registry().gauge(
                "ps_van_loop_requests_total",
                "cumulative frames read by the native loop")
            self._read_hits_gauge = obs.default_registry().gauge(
                "ps_pull_native_hits_total",
                "READ frames answered by the native read cache with "
                "zero upcalls")
            self._read_miss_gauge = obs.default_registry().gauge(
                "ps_pull_native_misses_total",
                "cacheable READ frames that fell through to the pump")
            self._read_lag_gauge = obs.default_registry().gauge(
                "ps_pull_cache_version_lag",
                "engine versions the cached READ snapshot trails by "
                "(0 = fresh or empty)")
            self._padm_acks_gauge = obs.default_registry().gauge(
                "ps_push_native_acks_total",
                "push replays acked by the native admission ledger with "
                "zero upcalls")
            self._padm_ref_gauge = obs.default_registry().gauge(
                "ps_push_native_refusals_total",
                "push frames refused natively (backup/fenced role) with "
                "zero upcalls")
            self._pump_thread = threading.Thread(
                target=self._loop_pump, daemon=True
            )
            self._pump_thread.start()
        else:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True
            )
            self._accept_thread.start()

    @property
    def native_loop(self) -> bool:
        """True when this service serves through the native epoll loop."""
        return self._nloop is not None

    @property
    def port(self) -> int:
        return self._listener.port

    # -- to be provided by the concrete service -------------------------------

    def _handle(self, kind: int, worker: int, tensors, extra) -> bytes:
        raise NotImplementedError

    def _set_draining(self) -> None:
        raise NotImplementedError

    # replication hooks (only services that support primary/backup pairs
    # implement these; the base dispatch never calls them otherwise)

    def _service_lock(self):
        """The apply lock replication serializes against (dense: the
        engine lock; sparse: the table lock)."""
        raise NotImplementedError

    def _replica_hello_extra(self) -> dict:
        """Primary: the attach-time topology + state-point description
        (called under the apply lock by :meth:`attach_backup`)."""
        raise NotImplementedError

    def _replica_validate(self, extra: dict) -> Optional[str]:
        """Backup: refuse a mismatched stream (error string) or accept
        (None). Must check topology AND the state point — a backup that
        did not start from the primary's exact state diverges silently."""
        raise NotImplementedError

    def _replica_apply(self, op: str, worker: int, tensors, extra) -> None:
        """Backup: apply one replicated event through the local engine.
        Called with :meth:`_service_lock` HELD (stream order is engine
        order); must not re-acquire it."""
        raise NotImplementedError

    def _replica_seed(self, worker: int, tensors, extra):
        """Backup: install the full state point a re-seeding primary
        shipped (``RESEED`` → ``REPLICA_SEED``, the autopilot's replica
        heal). Returns an error string to refuse, None to accept. The
        base refuses — only services whose state fits the row codec
        (dense) opt in."""
        return "this service does not support re-seed"

    # -- replication / promotion ----------------------------------------------

    _REPLICA_KINDS = frozenset({tv.REPLICA_HELLO, tv.REPLICA_APPEND,
                                tv.REPLICA_PROMOTE, tv.REPLICA_STATE,
                                tv.REPLICA_SEED})

    def _dispatch(self, kind: int, worker: int, tensors, extra) -> bytes:
        """Route one request: replication-plane kinds are handled here;
        data-plane kinds reach the subclass only on a serving primary — a
        backup refuses them with a typed, retry-able reply (the worker's
        failover loop keys off ``extra["backup"]`` to wait out the
        promotion instead of failing the job)."""
        # chaos hook first (both serve paths funnel through here): an
        # injected fault answers INSTEAD of the handler, so a drill
        # exercises the worker's real refusal/retry machinery — the
        # exact frames a genuinely broken shard would emit
        hook = self.chaos
        if hook is not None:
            reply = hook(self, kind, worker, extra)
            if reply is not None:
                return reply
        # server-side tracing hook — THE one chokepoint every kind passes
        # through: a frame whose header carries a propagated trace
        # context gets a span named for its kind, parented to the
        # sender's span (the worker op, or the primary's apply for
        # replica appends). Untraced frames cost one dict lookup.
        ctx = obs.from_wire(extra)
        if ctx is not None:
            with obs.tracer().span(tv.kind_name(kind), cat="server",
                                   parent=ctx).set(worker=worker,
                                                   role=self.role):
                return self._dispatch_traced(kind, worker, tensors, extra)
        return self._dispatch_traced(kind, worker, tensors, extra)

    def _dispatch_traced(self, kind: int, worker: int, tensors,
                         extra) -> bytes:
        if kind in self._REPLICA_KINDS:
            return self._handle_replica(kind, worker, tensors, extra)
        if self.role != "primary" and kind != tv.STATS:
            if kind == tv.READ and self.role == "backup":
                # replica reads (README "Read path"): a BACKUP answers
                # side-effect-free READs from its replicated state — the
                # reply's version stamp is what lets workers enforce the
                # bounded-staleness contract (PS_READ_STALENESS) and fall
                # back to the primary when the bound is exceeded. Fenced
                # zombies stay refused: their version stream is dead, and
                # routing reads at them would only burn a fallback.
                return self._handle(kind, worker, tensors, extra)
            return tv.encode(tv.ERR, worker, None, extra={
                "error": (f"shard backup is not serving worker traffic "
                          f"(role={self.role}, epoch {self.epoch}) — "
                          f"retry after promotion"),
                "backup": True, "epoch": self.epoch,
            })
        return self._handle(kind, worker, tensors, extra)

    def _handle_replica(self, kind: int, worker: int, tensors,
                        extra) -> bytes:
        if kind == tv.REPLICA_STATE:
            return tv.encode(tv.OK, worker, None, extra=self.replica_state())
        if kind == tv.REPLICA_PROMOTE:
            if self.role != "backup":
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": f"cannot promote a {self.role} service",
                })
            epoch = self.promote(reason=str(extra.get("reason", "request")))  # pslint: disable=PSL203 -- REPLICA_PROMOTE is an operator/test-sent frame; in-tree promotion goes through PromotionWatch.promote(), so no in-tree encoder produces "reason"
            return tv.encode(tv.OK, worker, None,
                             extra={"epoch": epoch, "role": self.role})
        if self.role != "backup":
            # a zombie primary still appending after this backup promoted:
            # refuse WITH the fencing signal — the zombie's session calls
            # its on_fenced hook and the old primary stops serving workers
            # instead of forking history (split-brain). The fence lands on
            # the zombie's next commit attempt; workers that re-routed are
            # protected sooner by the epoch check in their failover loop.
            return tv.encode(tv.ERR, worker, None, extra={
                "error": (f"replication stream refused: this service is "
                          f"{self.role} (epoch {self.epoch}), not a backup"),
                "fenced": True, "epoch": self.epoch,
            })
        if kind == tv.REPLICA_SEED:
            # full state-point install onto an EMPTY spare (autopilot
            # re-seed, README "Autopilot & chaos"): the quiesced primary
            # shipped its whole state in one frame; install it so the
            # REPLICA_HELLO that follows validates against an exact copy
            err = self._replica_seed(worker, tensors, extra)
            if err is not None:
                return tv.encode(tv.ERR, worker, None,
                                 extra={"error": err})
            return tv.encode(tv.OK, worker, None,
                             extra={"epoch": self.epoch})
        if kind == tv.REPLICA_HELLO:
            err = self._replica_validate(extra)
            if err is not None:
                return tv.encode(tv.ERR, worker, None, extra={"error": err})
            with self._service_lock():
                self._primary_epoch = int(extra.get("epoch", 0))
                self._replica_applied_seq = int(extra.get("start_seq", 0))
                self._replica_attached = True
            return tv.encode(tv.OK, worker, None, extra={
                "applied_seq": self._replica_applied_seq,
                "epoch": self.epoch,
            })
        # REPLICA_APPEND
        seq = int(extra["seq"])
        with self._service_lock():
            if self.role != "backup":
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": "promoted mid-append: stream refused",
                })
            if not self._replica_attached:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": "REPLICA_APPEND before REPLICA_HELLO",
                })
            if seq != self._replica_applied_seq + 1:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": (f"replication gap: expected seq "
                              f"{self._replica_applied_seq + 1}, got {seq}"),
                })
            self._replica_apply(str(extra["op"]),
                                int(extra.get("w", worker)), tensors, extra)
            self._replica_applied_seq = seq
        return tv.encode(tv.OK, worker, None, extra={"applied_seq": seq})

    # -- high-QPS read path (README "Read path") ------------------------------

    def _read_version(self):
        """Subclass hook: the engine version a READ reply is stamped with
        (dense: engine.version; sparse: summed table versions). None =
        this service serves no READ kind."""
        return None

    def _read_gen_snapshot(self) -> int:
        """The current read-cache publish generation. READ handlers call
        this UNDER their apply lock, atomically with the snapshot they
        serialize, and hand the pair to :meth:`_note_read_snapshot` — the
        ordering that makes invalidation-on-apply airtight."""
        with self._read_gen_lock:
            return self._read_gen

    def _invalidate_reads(self, tags=None) -> None:
        """Invalidation-on-apply: call after ANY committed state change a
        cached READ reply could observe (engine applies, replica-stream
        applies, migration cutovers, promotion, drain — and tiered-
        embedding tier moves, whose demotion victims fall OUTSIDE the
        triggering push's id-set: the sparse service unions their row
        tags in before calling here, because a tier move IS a state
        change under this contract). ``tags``
        optionally names the touched state slice (the sparse service's
        per-(table, row) hashes): the publish floor still rises — an
        in-flight pre-apply publish is refused either way — but only
        cached entries whose tag set intersects are dropped, so hot
        id-sets disjoint from the apply keep serving natively. None (the
        dense services, and every structural change) drops everything.
        The native push-admission mirror rides the same generation: the
        bump raises its floor too (dropping the version-stamped ack
        template, which the post-apply :meth:`_admit_publish` re-arms),
        so a pre-apply classification can never ack a post-apply replay.
        Cheap no-op when both native mirrors are off."""
        if not (self._native_read_cache or self._native_admit):
            return
        with self._read_gen_lock:
            self._read_gen += 1
            gen = self._read_gen
        nloop = self._nloop
        if nloop is not None:
            if self._native_read_cache:
                nloop.cache_invalidate(gen, tags=tags)
            if self._native_admit:
                nloop.admit_invalidate(gen)

    def _note_serve_age(self, birth: Optional[dict],
                        tier: Optional[str] = None) -> None:
        """Record one serve's data age (``now - version birth``) into
        ``ps_read_staleness_seconds``. READ handlers call this with the
        birth record they just encoded into the reply; the tier defaults
        to this endpoint's serving role — ``pump`` on a primary (the
        Python serve path; zero-upcall native hits re-serve the same
        stamped bytes), ``replica`` on a backup."""
        if birth is None:
            return
        from ps_tpu.obs import freshness

        age, src, clamped = freshness.age_of(birth)
        self.transport.record_read_age(
            age, src=src,
            tier=tier or ("pump" if self.role == "primary" else "replica"),
            bound=self._fresh_slo, clamped=clamped)

    def _note_read_snapshot(self, gen: int, version: int,
                            tags=None) -> None:
        """READ handlers record the (generation, version) their reply
        serializes — plus, optionally, the invalidation ``tags`` naming
        the rows it covers; the pump publishes the encoded frame into the
        native cache under exactly that generation (and those tags).
        Thread-local: handlers run on the pump or punted threads."""
        self._read_pub.gen = gen
        self._read_pub.version = int(version)
        self._read_pub.tags = tags

    # -- zero-upcall push plane (README "Push path") ---------------------------

    def _admit_kind(self) -> Optional[int]:
        """Subclass hook: the ONE wire kind the native admission mirror
        may classify (dense: PUSH; sparse: ROW_PUSH). None = this service
        never admits natively — the aggregator's group rounds barrier on
        the pump, and bucketed/push-pull kinds carry replies no template
        can pre-encode, so they stay pump-only everywhere."""
        return None

    def _admit_entry(self, worker: int) -> Optional[tuple]:
        """Subclass hook: this worker's settled-ledger row as
        ``(nonce, lo, hi)`` — a replay at/below ``lo`` is fully applied
        (ackable), above ``hi`` is strictly fresh, between punts. None =
        not publishable (no uniform token across the served key range);
        the native loop then punts this worker's frames to the pump."""
        return None

    def _admit_entries(self):
        """Every publishable ledger row (for a full mirror reseed)."""
        out = []
        for w in list(getattr(self, "_applied_pseq", None) or ()):
            ent = self._admit_entry(int(w))
            if ent is not None:
                out.append((int(w), ent[0], int(ent[1]), int(ent[2])))
        return out

    def _admit_ack_bytes(self) -> Optional[bytes]:
        """Subclass hook: the encoded replay-ack reply (worker id 0 — the
        loop patches the requester's id in before sending), byte-for-byte
        what the pump would produce for a pure dedup replay RIGHT NOW.
        Version-stamped: every apply invalidates it at the native floor
        and the post-apply publish re-arms it, so a native ack can never
        carry a superseded version stamp."""
        return None

    def _admit_refusal_bytes(self) -> Optional[bytes]:
        """The typed role refusal the native loop answers push frames
        with while this service is not serving worker traffic — the
        EXACT bytes of :meth:`_dispatch_traced`'s backup/fenced refusal
        (worker id 0; the loop patches the requester's id). None on a
        serving primary."""
        if self.role == "primary":
            return None
        return tv.encode(tv.ERR, 0, None, extra={
            "error": (f"shard backup is not serving worker traffic "
                      f"(role={self.role}, epoch {self.epoch}) — "
                      f"retry after promotion"),
            "backup": True, "epoch": self.epoch,
        })

    def _admit_sync(self, locked: bool = False) -> None:
        """Structural reseed of the native admission mirror (promotion,
        fencing, checkpoint resume, migration cutover, startup): drop
        everything at a fresh generation, then republish the settled
        ledger — or arm the role refusal instead on a non-primary. Takes
        the service (apply) lock unless the caller already holds it, so
        the ledger it reads cannot move under the reseed."""
        if not self._native_admit or self._nloop is None:
            return
        if not locked:
            with self._service_lock():
                return self._admit_sync(locked=True)
        nloop = self._nloop
        with self._read_gen_lock:
            self._read_gen += 1
            gen = self._read_gen
        nloop.admit_reset(gen)
        refusal = self._admit_refusal_bytes()
        if refusal is not None:
            nloop.admit_set_refusal(refusal)
            return
        nloop.admit_set_refusal(b"")
        if getattr(self, "_paused", False) or getattr(self, "_draining",
                                                      False):
            return  # paused/draining: every push must reach the pump
        for w, nonce, lo, hi in self._admit_entries():
            nloop.admit_put(w, nonce, lo, hi, gen)
        ack = self._admit_ack_bytes()
        if ack is not None:
            nloop.admit_set_ack(ack, gen)

    def _admit_drop(self) -> None:
        """Suspend native admission (checkpoint pause, drain): drop the
        whole mirror at a fresh generation so every push frame punts to
        the pump until :meth:`_admit_sync` reseeds. Needs no service
        lock — the bump only ever makes classification MORE conservative."""
        if not self._native_admit or self._nloop is None:
            return
        with self._read_gen_lock:
            self._read_gen += 1
            gen = self._read_gen
        self._nloop.admit_reset(gen)

    def _admit_publish(self, *workers) -> None:
        """Per-apply incremental publish (call under the apply lock,
        AFTER the apply's :meth:`_invalidate_reads` bumped the
        generation): push the named workers' settled-ledger rows and the
        fresh replay-ack template to the native mirror at the post-apply
        generation. The floor the invalidation raised refuses any
        laggard publish from a superseded apply."""
        if (not self._native_admit or self._nloop is None
                or self.role != "primary"
                or getattr(self, "_paused", False)
                or getattr(self, "_draining", False)):
            return
        nloop = self._nloop
        with self._read_gen_lock:
            gen = self._read_gen
        for w in workers:
            if w is None:
                continue
            ent = self._admit_entry(int(w))
            if ent is not None:
                nloop.admit_put(int(w), ent[0], int(ent[1]), int(ent[2]),
                                gen)
        ack = self._admit_ack_bytes()
        if ack is not None:
            nloop.admit_set_ack(ack, gen)

    def _admit_fresh_hint(self) -> bool:
        """Consume this thread's native admission stamp: True iff the
        loop classified the frame strictly fresh AND no apply/reseed
        landed since (the stamp is floor+1 of its classification; every
        state change bumps the shared generation). Call under the apply
        lock — applies serialize there, so a True return proves the
        dedup scan would find nothing and can be skipped. Any staleness
        degrades to False: the full scan, never a double apply."""
        gen = getattr(self._read_pub, "admit", 0)
        if not gen:
            return False
        self._read_pub.admit = 0
        with self._read_gen_lock:
            return gen - 1 == self._read_gen

    def promote(self, reason: str = "request") -> int:
        """The backup→primary transition (idempotent): under the apply
        lock — so no replica append is mid-apply and no worker push is
        admitted across the flip — bump the shard-table epoch past the
        primary's and start serving. Everything the primary committed
        (sync ack: everything it ever ACKNOWLEDGED to a worker) is already
        in this engine; there is nothing to rebuild, which is what makes
        promotion a millisecond flip instead of a restart."""
        import time as _time

        t0 = _time.perf_counter()
        with self._service_lock():
            if self.role == "primary":
                return self.epoch
            self.role = "primary"
            self.epoch = self._primary_epoch + 1
            self.promote_reason = reason
        # role flipped: a cached reply published as a backup must not
        # outlive the promotion (its bytes are still correct state, but
        # freshness semantics changed — republish as primary)
        self._invalidate_reads()
        # re-seed the admission mirror from the replicated ledger: the
        # promoted backup suppresses exactly the replays its dead primary
        # would have, natively, from the first post-promotion frame —
        # and stops answering the backup refusal
        self._admit_sync()
        self.promotion_s = _time.perf_counter() - t0
        obs.record_event("promotion", reason=reason, epoch=self.epoch,
                         promotion_s=round(self.promotion_s, 6))
        logging.getLogger(__name__).warning(
            "backup promoted to primary (reason=%s, epoch %d) in %.1fms",
            reason, self.epoch, self.promotion_s * 1e3,
        )
        return self.epoch

    def attach_backup(self, host: str, port: int, ack: str = "sync",
                      window: int = 256, compress=None,
                      stall_timeout: float = 30.0):
        """Primary: attach a warm backup and start replicating every
        commit to it. Attach BEFORE admitting worker traffic (or from a
        quiesced state): the handshake validates that both replicas stand
        at the same state point and refuses otherwise — the deltas-only
        stream cannot catch a backup up past missed commits.

        ``ack="sync"``: push/pull replies wait for the backup's ack —
        promotion is bitwise-identical to what workers observed.
        ``ack="async"``: replies return immediately; the backup trails by
        at most ``window`` commits (metrics-visible ``repl_lag``).
        ``compress`` optionally runs the replica stream through a
        stateless gradient codec (ps_tpu/compress)."""
        from ps_tpu.replica.session import BackupSession

        if self.role != "primary":
            raise RuntimeError("only a primary can attach a backup")
        with self._service_lock():
            old = self._backup_session
            if old is not None and not old.degraded:
                raise RuntimeError("a live backup session is already "
                                   "attached")
            if old is not None:
                old.close()  # degraded: replaceable — redundancy must be
                # restorable without restarting the primary (quiesce,
                # checkpoint, seed the new backup from it, re-attach)
            hello = self._replica_hello_extra()
            hello.update({"epoch": self.epoch, "ack": ack})
            session = BackupSession(host, port, hello, ack=ack,  # pslint: disable=PSL101 -- attach-time only (before worker traffic, or quiesced): the dial+HELLO must be atomic with the state-point snapshot the lock protects, and connect_timeout_ms bounds it
                                    window=window, compress=compress,
                                    stats=self.transport,
                                    stall_timeout=stall_timeout)
            session.on_fenced = self._fence
            self._backup_session = session
        return session

    def _fence(self, peer_epoch: int) -> None:
        """Self-fencing: our backup promoted past us (it refused the
        replication stream as a primary of ``peer_epoch``). This service
        is a zombie — stop serving workers so history cannot fork; the
        retry-able refusal routes still-connected workers to the real
        primary through their replica sets."""
        with self._service_lock():
            if self.role != "primary":
                return
            self.role = "fenced"
        # a zombie's cached reads die with its serving rights — and its
        # admission mirror flips to the fenced refusal (native, byte-
        # identical to the pump's): no ledger row may ack a push here
        self._invalidate_reads()
        self._admit_sync()
        obs.record_event("self_fence", peer_epoch=int(peer_epoch),
                         epoch=self.epoch)
        logging.getLogger(__name__).error(
            "FENCED: this shard's backup promoted to primary (epoch %d) "
            "while we were still serving — refusing all worker traffic "
            "from now on (workers re-route via their replica sets)",
            peer_epoch,
        )

    def _replicate(self, op: str, worker: int, tensors=None,
                   meta: Optional[dict] = None) -> Optional[int]:
        """Primary commit hook (call under the apply lock): append one
        committed event to the replication stream. None = unreplicated
        (no session, or it degraded)."""
        s = self._backup_session
        if s is None or s.degraded:
            return None
        meta = dict(meta or {})
        # propagate the serve span (if this commit is being traced) so
        # the backup's replica_append span parents to THIS apply — the
        # worker→primary→backup chain stays one trace
        ctx = obs.tracer().current()
        if ctx is not None:
            meta[obs.WIRE_KEY] = [ctx.trace_id, ctx.span_id]
        return s.publish(op, worker, tensors, meta)

    def _await_replication(self, seq: Optional[int]) -> None:
        """Sync-ack gate (call OUTSIDE the apply lock, before sending the
        reply): block until the backup acked ``seq``. No-op for async ack,
        unreplicated commits, and degraded sessions — EXCEPT a session
        that degraded because the backup PROMOTED: then this zombie's
        commit never reached the real primary, so the reply must be a
        retryable refusal — the worker re-routes and replays the push at
        the promoted backup (dedup makes it exactly-once), and the commit
        survives the fence instead of dying with the zombie."""
        s = self._backup_session
        if s is None:
            return
        if seq is not None and s.ack_mode == "sync":
            # `child` piggybacks on the serve span: untraced requests get
            # the NOOP (never a fresh sampling decision mid-server)
            with obs.tracer().child("replica_ack_wait", cat="server"):
                s.wait_acked(seq)
        # checked for EVERY commit (even unreplicated ones after the
        # degrade): once fenced, no reply may tell a worker its commit
        # stuck at this zombie
        if s.fenced:
            raise NotServingError(
                "fenced mid-commit: this shard's backup promoted — retry "
                "at the new primary"
            )

    def replica_state(self) -> dict:
        """Role/epoch/replication introspection (REPLICA_STATE, and merged
        into both services' STATS replies)."""
        out = {"role": self.role, "epoch": self.epoch,
               # wall clock for the NTP-style trace-clock probe
               # (ps_tpu/obs/clock.py): REPLICA_STATE is the cheapest
               # round trip every role answers, so offsets ride it
               "now": time.time()}
        s = self._backup_session
        if s is not None:
            out["repl"] = s.state()
        if self._replica_attached:
            out["replica_applied_seq"] = self._replica_applied_seq
        if self.promote_reason is not None:
            out["promote_reason"] = self.promote_reason
            out["promotion_s"] = self.promotion_s
        out["dedup_hits"] = self.transport.dedup_hits
        v = self._read_version()
        if v is not None and "version" not in out:
            # the cheap per-role version probe the worker-side parameter
            # cache rides (REPLICA_STATE on the heartbeat cadence):
            # version bumps invalidate cached reads without a full pull
            out["version"] = v
        if self.transport.reads_served or self.transport.read_native_hits:
            # serve-path visibility (ps_top's read columns): READs this
            # endpoint answered in Python vs natively, and the native
            # cache's live footprint
            out["read"] = {
                "served": self.transport.reads_served,
                "native_hits": self.transport.read_native_hits,
                "native_misses": self.transport.read_native_misses,
                "entries": self.transport.read_cache_entries,
                # conditional reads: NOT_MODIFIED replies served (pump),
                # delta rows shipped, and native version-floor hits —
                # ps_top's nm% column sums pump NMs + native cond hits
                "nm": self.transport.read_not_modified,
                "delta_rows": self.transport.read_delta_rows,
                "native_cond_hits": self.transport.read_native_cond_hits,
            }
        f = self.transport.fresh_snapshot()
        if f is not None:
            # freshness plane (README "Online serving & freshness"):
            # ps_top's fresh/age% columns and ps_doctor's stalest-tier
            # section render this dict straight off the STATS frame
            out["fresh"] = f
        if self._nloop is not None:
            # native event-loop serve path: live connections + frames
            # read — the cell ps_top renders per shard (iterations and
            # upcall-batch distributions ride the /metrics gauges and
            # the fleet-telemetry counters instead) — plus the in-loop
            # p99s ps_top's nlp99/qw99 columns and ps_doctor's native
            # section render (µs: these are sub-ms surfaces)
            loop = {"conns": self.transport.loop_conns,
                    "requests": self.transport.loop_requests,
                    "slow_frames": self.transport.nl_slow_frames}
            s = self.transport.hist["nl_read_hit_s"].summary()
            if s:
                loop["nlp99_us"] = round(s["p99"] * 1e6, 1)
            s = self.transport.hist["nl_queue_wait_s"].summary()
            if s:
                loop["qw99_us"] = round(s["p99"] * 1e6, 1)
            t = self.transport
            classified = (t.push_native_acks + t.push_native_refusals
                          + t.push_native_fresh + t.push_native_punts)
            if classified:
                # push-admission visibility (ps_top's padm% column): how
                # much of the push plane the native mirror settled without
                # an upcall (acks + refusals), plus the raw counters
                loop["padm"] = {
                    "acks": t.push_native_acks,
                    "refusals": t.push_native_refusals,
                    "fresh": t.push_native_fresh,
                    "punts": t.push_native_punts,
                    "share": round((t.push_native_acks
                                    + t.push_native_refusals)
                                   / classified, 4),
                }
            out["loop"] = loop
        return out

    # -- bucketed-push staging -------------------------------------------------

    def _stage_bucket_push(self, worker: int, bucket: int, nbuckets: int,
                           epoch: int, raw, slices,
                           nonce: Optional[str] = None) -> Optional[dict]:
        """Stage one bucket of worker's multi-bucket push; returns the fully
        assembled ``{key: tensor}`` tree when this bucket completes the
        epoch, else None (reply with a plain ack).

        One epoch in flight per worker (the worker's sender serializes
        cycles, and waits out every bucket of an epoch before starting the
        next). A bucket of a different (epoch, incarnation-nonce) pair
        therefore always means the worker moved on — forward after
        abandoning a push mid-flight, or into a new incarnation after a
        restart/reconnect reset its epoch counter (its old connections are
        severed, so a genuine straggler of the staged epoch can no longer
        arrive; the nonce catches even an epoch-NUMBER collision between
        incarnations). Either way the incomplete epoch is dropped whole,
        never half-applied — and merged with nothing — and the new epoch
        stages fresh. A malformed bucket (duplicate, bad range) also drops
        the whole staged epoch, so a retry starts clean instead of
        completing against poisoned state.
        """
        stale = None  # (epoch, staged, nbuckets) of a dropped stale epoch
        try:
            with self._stage_lock:
                asm = self._push_stage.get(worker)
                if asm is not None and (asm.epoch != epoch
                                        or getattr(asm, "nonce",
                                                   None) != nonce):
                    # record the drop, but account/log it OUTSIDE the
                    # stage lock: metrics/flight/logging do their own
                    # locking and I/O, and every bucket of every worker
                    # serializes here
                    stale = (asm.epoch, len(asm._seen), asm.nbuckets)
                    asm = None
                if asm is None:
                    asm = BucketAssembler(epoch, nbuckets)
                    asm.nonce = nonce
                    self._push_stage[worker] = asm
                try:
                    complete = asm.add(bucket, raw, slices, epoch)
                except Exception:
                    self._push_stage.pop(worker, None)
                    raise
                if complete:
                    del self._push_stage[worker]
        finally:
            # finally, not fallthrough: a malformed first bucket of the
            # SUPERSEDING epoch raises out of the block above, and the
            # dropped stale epoch must still reach the black box — the
            # double-fault is exactly when the record matters most
            if stale is not None:
                # observable, not just a log line: STATS carries the
                # counts so a fleet-wide rash of abandoned pushes shows
                # up in the worker's StepLogger instead of only in
                # server stderr
                old_epoch, staged, nbuckets = stale
                self.transport.record_stale_epoch(staged)
                obs.record_event("stale_epoch", worker=worker,
                                 epoch=old_epoch, superseded_by=epoch,
                                 buckets=staged)
                logging.getLogger(__name__).warning(
                    "worker %d abandoned push epoch %d (%d/%d buckets); "
                    "superseded by epoch %d", worker, old_epoch,
                    staged, nbuckets, epoch,
                )
        return asm.finish() if complete else None

    # -- checkpoint ownership tokens ------------------------------------------

    def _ckpt_issue_token(self) -> Optional[int]:
        """Issue the pause ownership token (call under the apply lock);
        None when a checkpoint is already outstanding — the caller replies
        with :meth:`_ckpt_busy_error`."""
        if self._ckpt_token is not None:
            return None
        self._ckpt_seq += 1
        self._ckpt_token = self._ckpt_seq
        return self._ckpt_token

    def _ckpt_busy_error(self) -> str:
        return (f"checkpoint already in progress (token {self._ckpt_token} "
                f"outstanding) — serialize checkpoint coordinators")

    def _ckpt_token_error(self, phase: str, extra: dict) -> Optional[str]:
        """Error string when the phase's presented token does not match the
        outstanding one; None when it does. (``resume`` with ``force`` is
        the caller's deliberate bypass and skips this gate.)"""
        token = extra.get("token")
        token = None if token is None else int(token)
        if token != self._ckpt_token:
            return (f"checkpoint {phase} with invalid token {token!r} "
                    f"(outstanding: {self._ckpt_token!r})")
        return None

    def _ckpt_clear_token(self) -> None:
        """Call under the apply lock, at (any) resume."""
        self._ckpt_token = None

    # -- checkpoint-pause drain accounting ------------------------------------

    def _pause_wait_begin(self) -> None:
        """Subclass hook: call immediately before parking a serve thread on
        a checkpoint-pause condition (so stop() can discount it). Parks
        on native-loop punted threads are ALSO counted separately: each
        of those holds exactly one claimed loop body, which the native
        drain must discount from nl_pending — while a park on an
        shm-detached classic serve thread holds none."""
        with self._inflight_cond:
            self._pause_blocked += 1
            if getattr(threading.current_thread(), "_ps_loop_req", False):
                self._loop_pause_parked += 1
            self._inflight_cond.notify_all()

    def _pause_wait_end(self) -> None:
        with self._inflight_cond:
            self._pause_blocked -= 1
            if getattr(threading.current_thread(), "_ps_loop_req", False):
                self._loop_pause_parked -= 1
            self._inflight_cond.notify_all()

    # -- accept / serve --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            ch = self._listener.accept(timeout_ms=200)
            if ch is None:
                continue
            ch.stats = self.transport
            ch.pool = self._recv_pool
            with self._chan_lock:
                # prune finished serve threads so a long-lived server with
                # many reconnects doesn't accumulate dead Thread objects
                # (ident is None = appended but not yet started — keep: an
                # un-started thread also reports is_alive() False)
                self._conns = [t for t in self._conns
                               if t.ident is None or t.is_alive()]
                if self._stop.is_set():
                    ch.close()  # raced stop(): admit nothing new
                    return
                self._channels.append(ch)
                t = threading.Thread(
                    target=self._serve, args=(ch,), daemon=True
                )
                self._conns.append(t)
            t.start()

    def _try_shm_upgrade(self, ch: tv.Channel, worker: int, extra: dict):
        """Attach the worker's offered ring segments; returns
        ``(lane_or_None, reply_frame)`` — any failure becomes an ERR reply
        and the connection stays plain TCP."""
        from ps_tpu.control import shm_lane

        if not self._shm_accept:
            return None, tv.encode(tv.ERR, worker, None, extra={
                "error": "shm lane disabled on this server (PS_SHM=0)",
            })
        try:
            lane = shm_lane.accept_upgrade(ch, extra, stats=self.transport)
        except Exception as e:
            return None, tv.encode(tv.ERR, worker, None,
                                   extra={"error": repr(e)})
        return lane, tv.encode(tv.OK, worker, None, extra={"shm": True})

    @staticmethod
    def _send_reply(conn, reply) -> None:
        """Reply in either form: contiguous frame, or zero-copy
        ``(header, chunks)`` parts (vectored TCP send / one ring write)."""
        send_payload(conn, reply)

    def _serve(self, ch: tv.Channel, lane=None) -> None:
        # `conn` is the data plane: the TCP channel until a successful
        # SHM_SETUP, the shared-memory lane after (the lane's recv hands
        # out ring frames IN PLACE and polls the TCP side for oversize
        # spills and peer death; stop() still severs via the TCP channel).
        # `lane` is pre-set when the native event loop detached an
        # already-upgraded connection to this thread.
        conn = lane if lane is not None else ch
        try:
            while not self._stop.is_set():
                try:
                    msg = (conn.recv() if lane is None
                           else lane.recv(stop=self._stop.is_set))
                except tv.VanError:
                    return  # worker hung up (or stop() severed an idle conn)
                with self._inflight_cond:
                    self._inflight += 1
                try:
                    kind, worker, tensors, extra = tv.decode(msg)
                    self._req_counter.inc()
                    goodbye = kind == tv.SHUTDOWN
                    new_lane = None
                    if goodbye:
                        reply = tv.encode(tv.OK, worker, None)
                    elif kind == tv.SHM_SETUP and lane is None:
                        new_lane, reply = self._try_shm_upgrade(
                            ch, worker, extra)
                    else:
                        reply = self._dispatch_reply_payload(
                            kind, worker, tensors, extra)
                    try:
                        self._send_reply(conn, reply)
                    except tv.VanError:
                        if new_lane is not None:
                            # attached but never adopted (the OK reply
                            # died): release its mappings deterministically
                            new_lane.close()
                        return  # worker vanished mid-reply; nothing to tell
                    finally:
                        # ONLY now is the request frame provably dead: the
                        # reply may alias it (a handler may echo zero-copy
                        # views of the request), so the buffer goes back
                        # to the pool after the send attempt — success or
                        # failure — never before. The shm lane's ring
                        # bytes are likewise released at the NEXT recv.
                        tensors = None
                        self._recv_pool.ret(msg)
                        msg = None
                    if new_lane is not None:
                        conn = lane = new_lane  # data plane switches here
                finally:
                    with self._inflight_cond:
                        self._inflight -= 1
                        self._inflight_cond.notify_all()
                if goodbye:
                    with self._goodbye_cond:
                        self.goodbyes += 1
                        self._goodbye_cond.notify_all()
                    return
        finally:
            if lane is not None:
                lane.close()  # closes the TCP channel too
            else:
                ch.close()
            with self._chan_lock:
                try:
                    self._channels.remove(ch)
                except ValueError:
                    pass  # stop() snapshot may already hold it
                # self-prune: under a reconnect storm with NO later
                # accepts, the accept-loop prune never runs again — a
                # finished serve thread must not linger in _conns until
                # the next connection (or forever, on an idle listener)
                try:
                    self._conns.remove(threading.current_thread())
                except ValueError:
                    pass  # stop() snapshot may already hold it

    # -- native event-loop pump ------------------------------------------------

    #: data-plane kinds that can PARK inside their handler waiting for a
    #: FUTURE request of this same service (checkpoint pause wakes on
    #: resume; the sync replica-ack gate can stall on a hung backup):
    #: the single pump thread must never park, so these are punted to a
    #: short-lived thread exactly when they could block — everything
    #: else dispatches inline in the batch.
    _COMMIT_KINDS = frozenset({tv.PUSH, tv.PUSH_PULL, tv.BUCKET_PUSH,
                               tv.ROW_PUSH, tv.ROW_PUSH_PULL,
                               tv.ROW_BUCKET_PUSH})
    #: kinds whose handlers orchestrate long multi-request protocols
    #: (checkpoint phases park between coordinator requests; a rebalance /
    #: outbound migration runs for the whole move) — always punted.
    _PUNT_KINDS = frozenset({tv.CHECKPOINT, tv.MIGRATE_OUT,
                             tv.COORD_REBALANCE, tv.RESEED})
    #: subclass hook: kinds whose handlers can PARK waiting for ANOTHER
    #: member's future request of this same service (the aggregator's
    #: group barrier: a push waits for its host group's other pushes) —
    #: always punted to a FRESH thread, never the pool: at fan-in >
    #: pool-size, the round-completing push queued behind parked pool
    #: workers would deadlock the barrier it is supposed to release.
    _BARRIER_KINDS: frozenset = frozenset()

    def _loop_pump(self) -> None:
        """The ONE Python thread of the native-loop serve path: drain
        batches of complete requests from the native loop, dispatch each
        through the same `_dispatch` as the threaded path, reply via the
        loop's scatter-gather writer. Exits when the loop reports
        stopped (poll() -> None). A failure serving ONE request must
        never kill the pump (it is the only consumer): the per-request
        guard logs, releases the body (free is idempotent), and moves
        on — the threaded path's one-bad-connection blast radius."""
        nloop = self._nloop
        last_sync = 0.0
        while True:
            try:
                batch = nloop.poll(timeout_ms=100)
            except Exception:
                logging.getLogger(__name__).exception(
                    "native-loop poll failed; pump exiting")
                return
            # gauge sync is an O(conns) native lock sweep (nl_pending
            # touches every conn's write mutex): run it on idle ticks or
            # at most ~1/s under load — /metrics and ps_top refresh at
            # human timescales, the hot path must not pay per batch
            now = time.monotonic()
            if not batch or now - last_sync >= 1.0:
                last_sync = now
                st = nloop.stats()
                self.transport.set_loop_stats(st["iters"], st["requests"],
                                              st["conns"])
                self._loop_conn_gauge.set(st["conns"])
                self._loop_iter_gauge.set(st["iters"])
                self._loop_req_gauge.set(st["requests"])
                if self._native_read_cache:
                    cs = nloop.cache_stats()
                    self.transport.set_read_cache_stats(
                        cs["hits"], cs["misses"], cs["entries"],
                        cs["bytes"], cond_hits=cs.get("cond_hits", 0))
                    self._read_hits_gauge.set(cs["hits"])
                    self._read_miss_gauge.set(cs["misses"])
                    v = self._read_version()
                    # versions the cached snapshot trails the engine by
                    # (0 when empty — nothing stale is being served)
                    self._read_lag_gauge.set(
                        max(0, int(v) - self._read_pub_version)
                        if v is not None and cs["entries"] else 0)
                if self._native_admit:
                    asn = nloop.admit_stats()
                    self.transport.set_admit_stats(
                        asn["acks"], asn["refusals"], asn["fresh"],
                        asn["punts"])
                    self._padm_acks_gauge.set(asn["acks"])
                    self._padm_ref_gauge.set(asn["refusals"])
                if self._nl_stats:
                    self._sync_nl_telemetry(nloop)
            if batch is None:
                return
            if not batch:
                continue
            if self._pump_abort:
                # kill(): drop read-ahead frames unserved — engine state
                # must stay exactly as a SIGKILL would leave it
                for _, _, ptr, _ in batch:
                    nloop.free(ptr)
                continue
            self.transport.record_upcall(len(batch))
            with self._inflight_cond:
                self._inflight += len(batch)
            for cid, view, ptr, admit_gen in batch:
                try:
                    self._loop_serve_one(cid, view, ptr, admit_gen)
                except Exception:
                    logging.getLogger(__name__).exception(
                        "native-loop request failed; connection %d "
                        "continues", cid)
                    nloop.free(ptr)  # idempotent: no-op if already freed
                finally:
                    with self._inflight_cond:
                        self._inflight -= 1
                        self._inflight_cond.notify_all()

    def _sync_nl_telemetry(self, nloop) -> None:
        """Fold the loop's own telemetry into this service's stats (the
        pump's ~1/s gauge tick): the in-loop histograms land ABSOLUTE in
        the ps_nl_* TransportStats families — the native stripes own the
        counting — so they ride /metrics, STATS frames, and the
        delta-encoded fleet telemetry exactly like every Python-recorded
        surface; and the slow-frame ring drains into ``slow_frame``
        flight events, each with a reconstructed span when the frame
        carried a trace context (the zero-upcall path cannot open spans
        itself — this is where one hiccup on it becomes a traceable
        incident instead of a p999 mystery)."""
        self.transport.set_nl_hists(nloop.hist_snapshots())
        ns = nloop.stats_snapshot()
        self.transport.set_nl_stats(ns["slow_frames"],
                                    ns["tail_backlog_bytes"])
        for fr in nloop.slow_drain():
            total_ns = fr["read_ns"] + fr["wait_ns"] + fr["serve_ns"]
            obs.record_event(
                "slow_frame", conn=fr["conn"],
                wire_kind=tv.kind_name(fr["kind"]), size=fr["size"],
                read_ms=round(fr["read_ns"] / 1e6, 3),
                wait_ms=round(fr["wait_ns"] / 1e6, 3),
                serve_ms=round(fr["serve_ns"] / 1e6, 3),
                total_ms=round(total_ns / 1e6, 3),
                trace_id=fr["trace_id"] or None)
            if fr["trace_id"]:
                obs.tracer().record_external(
                    "slow_frame", "server", fr["trace_id"],
                    fr["span_id"] or None,
                    ts_us=time.time() * 1e6
                    - (fr["age_ns"] + total_ns) / 1e3,
                    dur_us=total_ns / 1e3,
                    conn=fr["conn"], wire_kind=tv.kind_name(fr["kind"]),
                    size=fr["size"],
                    read_us=round(fr["read_ns"] / 1e3, 1),
                    wait_us=round(fr["wait_ns"] / 1e3, 1),
                    serve_us=round(fr["serve_ns"] / 1e3, 1))

    def _punt_pool(self) -> "_DaemonPool":
        """Lazily-built pool for non-blocker punted requests (threads
        spawn on demand and are reused; only the pump calls this, so the
        lazy init needs no lock). 32 workers: parked pause-era pushes cap
        there and the rest queue — they would have parked anyway — while
        resume always arrives on a fresh thread. Daemon threads, NOT a
        ThreadPoolExecutor: its workers are joined at interpreter exit,
        so a task parked on a pause that nothing will ever resume (e.g.
        after kill()) would hang process shutdown — the exact hazard the
        threaded path avoids by making serve threads daemons."""
        pool = getattr(self, "_punt_executor", None)
        if pool is None:
            pool = _DaemonPool(max_workers=32, name="van-punt")
            self._punt_executor = pool
        return pool

    def _loop_close_conn(self, cid: int) -> None:
        """Drop one event-loop connection (malformed frame — the framing
        is gone, like the threaded path poisoning its channel)."""
        fd = self._nloop.detach(cid)
        if fd >= 0:
            os.close(fd)

    def _loop_serve_one(self, cid: int, msg, ptr: int,
                        admit_gen: int = 0) -> None:
        nloop = self._nloop
        if self._pump_abort:  # kill() landed mid-batch: drop, don't apply
            nloop.free(ptr)
            return
        try:
            kind, worker, tensors, extra = tv.decode(msg)
        except Exception:
            nloop.free(ptr)
            self._loop_close_conn(cid)
            return
        self._req_counter.inc()
        # a READ reaching the pump IS a native-cache miss: remember its
        # exact request bytes so the reply can be published into the
        # native cache (the next identical READ is answered inside the
        # loop with zero upcalls). The copy is tiny — READ requests are
        # a header + (sparse) an id list. The publish rides whichever
        # dispatch path the kind takes (inline here, or punted — the
        # aggregator barriers READs off-pump because its coalesced fetch
        # does upstream I/O).
        raw = (bytes(msg) if kind == tv.READ and self._native_read_cache
               else None)
        if kind == tv.SHUTDOWN:
            nloop.reply(cid, tv.encode(tv.OK, worker, None),
                        close_after=True)
            tensors = None
            nloop.free(ptr)
            with self._goodbye_cond:
                self.goodbyes += 1
                self._goodbye_cond.notify_all()
            return
        if kind == tv.SHM_SETUP:
            self._loop_shm_upgrade(cid, worker, extra, ptr)
            return
        barrier = kind in self._BARRIER_KINDS
        if kind in self._PUNT_KINDS or barrier or (
                kind in self._COMMIT_KINDS
                and (getattr(self, "_paused", False)
                     or self._loop_blockers > 0
                     or self._backup_session is not None)):
            # a request that may park must not park THE pump: hand it a
            # thread of its own (the threaded path's shape), bounded by
            # one in-flight request per connection. `_loop_blockers`
            # closes the pause TOCTOU: a punted CHECKPOINT sets `_paused`
            # on ITS thread, so the pump could otherwise inline-dispatch
            # a push in the race window and park forever on the pause
            # condition — the counter is raised HERE (synchronously,
            # before the blocker's thread even starts) and held until
            # that blocker's reply went out, so every commit the pump
            # sees after the blocker frame punts too.
            blocker = kind in self._PUNT_KINDS
            with self._inflight_cond:
                self._inflight += 1  # the punted task's share; pump's
                # own share is released when this method returns
                if blocker:
                    self._loop_blockers += 1
            try:
                if blocker or barrier or getattr(self, "_paused", False) \
                        or self._loop_blockers > 0:
                    # fresh threads whenever parking is on the table:
                    # blockers (a resume must never queue behind pool
                    # workers parked on the very pause it would lift),
                    # and EVERY commit while a pause/blocker is live —
                    # at >pool-size fan-in, a drain_to-admitted push
                    # queued behind parked pool workers would deadlock
                    # the checkpoint round until its timeout.
                    threading.Thread(
                        target=self._loop_dispatch_reply,
                        args=(cid, kind, worker, tensors, extra, ptr,
                              True, blocker, raw, admit_gen),
                        daemon=True,
                    ).start()
                else:
                    # steady-state punts (every replicated push) reuse a
                    # small pool — one fresh thread per request would be
                    # strictly worse churn than the thread-per-connection
                    # path this loop replaces. Pool exhaustion only
                    # queues work that genuinely only needs the engine
                    # lock (no parking condition is live on this branch).
                    self._punt_pool().submit(
                        self._loop_dispatch_reply, cid, kind, worker,
                        tensors, extra, ptr, True, False, raw, admit_gen)
            except Exception as e:  # thread exhaustion: refuse, don't die
                with self._inflight_cond:
                    self._inflight -= 1
                    if blocker:
                        self._loop_blockers -= 1
                    self._inflight_cond.notify_all()
                nloop.reply(cid, tv.encode(tv.ERR, worker, None,
                                           extra={"error": repr(e)}))
                tensors = None
                nloop.free(ptr)
            return
        self._loop_dispatch_reply(cid, kind, worker, tensors, extra, ptr,
                                  False, raw=raw, admit_gen=admit_gen)

    def _dispatch_reply_payload(self, kind: int, worker: int, tensors,
                                extra):
        """Dispatch + the typed-refusal ERR mapping, shared by BOTH serve
        paths so the frames can never drift (tests pin them
        byte-identical): NotServing -> retryable backup refusal,
        StaleTable -> re-route (the key range moved shards), anything
        else -> a plain ERR surfaced to the worker."""
        try:
            return self._dispatch(kind, worker, tensors, extra)
        except NotServingError as e:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": str(e), "backup": True,
                "epoch": self.epoch,
            })
        except StaleTableError as e:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": str(e), "moved": True,
                "table_epoch": self.table_epoch,
            })
        except Exception as e:
            return tv.encode(tv.ERR, worker, None,
                             extra={"error": repr(e)})

    def _reply_priority(self, kind: int, extra) -> int:
        """Native-loop writev priority of this request's reply: bucket
        frames drain front-of-model first (their bucket index), every
        other kind at 0 — PS_BUCKET_PRIORITY=0 restores the pure FIFO
        drain. Priorities only reorder tails across CONNECTIONS awaiting
        EPOLLOUT; per-connection reply order is untouched, so the framed
        request/reply contract cannot tear."""
        if not self._bucket_priority:
            return 0
        if kind in (tv.BUCKET_PULL, tv.BUCKET_PUSH, tv.ROW_BUCKET_PUSH):
            try:
                return int((extra or {}).get("bucket") or 0)
            except (TypeError, ValueError):
                return 0
        return 0

    def _loop_dispatch_reply(self, cid: int, kind: int, worker: int,
                             tensors, extra, ptr: int,
                             punted: bool, blocker: bool = False,
                             raw=None, admit_gen: int = 0) -> None:
        nloop = self._nloop
        prio = self._reply_priority(kind, extra)
        # mark this thread as serving a LOOP request for the dispatch's
        # duration, so a pause park inside the handler is counted toward
        # the native drain's claimed-body discount (reset in the finally:
        # pool threads are reused)
        this = threading.current_thread()
        this._ps_loop_req = True
        # the frame's native admission stamp (0 = unclassified) rides a
        # thread-local to the engine's apply, which consumes it via
        # _admit_fresh_hint — set unconditionally: pool/pump threads are
        # reused and a previous request's stamp must never leak forward
        self._read_pub.admit = int(admit_gen)
        try:
            if raw is not None:
                self._read_pub.gen = None  # pool/pump threads are reused:
                # never publish under a PREVIOUS request's generation
                self._read_pub.tags = None  # (nor its row tags)
            reply = self._dispatch_reply_payload(kind, worker, tensors,
                                                 extra)
            if raw is not None and isinstance(reply, (bytes, bytearray)):
                gen = getattr(self._read_pub, "gen", None)
                if gen is not None:
                    # publish-on-miss: the reply the pump is about to send
                    # becomes the native cache's entry for these request
                    # bytes — hit replies are bitwise identical to this
                    # pump reply BY CONSTRUCTION (the cache only echoes).
                    # A put raced by an apply is refused at the floor.
                    # Three shapes: a NOT_MODIFIED reply publishes as a
                    # version-floor entry (the request's cond digits are
                    # excised native-side so revalidators at ANY version
                    # >= the stamp share it); any OTHER reply to a
                    # conditional request is version-dependent (a delta,
                    # or a full payload for a lagging caller) and must
                    # not park under a key later conditionals would
                    # exact-match — skipped; unconditional replies keep
                    # the exact-byte publish unchanged.
                    tags = getattr(self._read_pub, "tags", None)
                    if len(reply) >= 1 and reply[0] == tv.NOT_MODIFIED:
                        if nloop.cache_put_cond(
                                raw, reply, gen, tags=tags,
                                vfloor=int(getattr(self._read_pub,
                                                   "version", 0))):
                            self._read_pub_version = int(
                                getattr(self._read_pub, "version", 0))
                    elif b'"cond":' in raw[-4096:]:
                        pass  # conditional miss: reply is caller-specific
                    elif nloop.cache_put(raw, reply, gen, tags=tags):
                        self._read_pub_version = int(
                            getattr(self._read_pub, "version", 0))
            try:
                nloop.reply(cid, reply, priority=prio)  # False = gone
            finally:
                # ONLY now is the request frame provably dead (the reply
                # may alias zero-copy views of it)
                tensors = None
                nloop.free(ptr)
        finally:
            this._ps_loop_req = False
            if punted:
                with self._inflight_cond:
                    self._inflight -= 1
                    if blocker:
                        self._loop_blockers -= 1
                    self._inflight_cond.notify_all()

    def _loop_shm_upgrade(self, cid: int, worker: int, extra: dict,
                          ptr: int) -> None:
        """SHM_SETUP on the event-loop path: detach the fd from the loop
        and serve the upgraded connection from a dedicated thread — the
        ring wait (tv_wait_u64) is already GIL-free native code, and epoll
        cannot wait on ring cursors, so the lane gains nothing from the
        loop. A refused upgrade keeps the connection on the thread too
        (plain TCP), mirroring the threaded path's behavior."""
        from ps_tpu.control import native_loop as nlmod

        nloop = self._nloop
        nloop.free(ptr)  # SHM_SETUP carries no tensors; extra is decoded
        fd = nloop.detach(cid)
        if fd < 0:
            return  # connection died under the request
        ch = nlmod.adopt_channel(fd)
        ch.stats = self.transport
        ch.pool = self._recv_pool
        lane, reply = self._try_shm_upgrade(ch, worker, extra)
        try:
            self._send_reply(ch, reply)
        except tv.VanError:
            if lane is not None:
                lane.close()
            else:
                ch.close()
            return
        with self._chan_lock:
            self._conns = [t for t in self._conns
                           if t.ident is None or t.is_alive()]
            if self._stop.is_set():
                (lane if lane is not None else ch).close()
                return
            self._channels.append(ch)
            t = threading.Thread(target=self._serve, args=(ch, lane),
                                 daemon=True)
            self._conns.append(t)
        t.start()

    # -- lifecycle -------------------------------------------------------------

    def wait_for_goodbyes(self, n: int, timeout: Optional[float] = None
                          ) -> bool:
        """Block until ``n`` workers have sent SHUTDOWN (clean departure).

        The quiescence signal a server should wait on before ``stop()``:
        a worker's ``close()`` sends SHUTDOWN only after every one of its
        pushes has been applied AND replied, so ``goodbyes == num_workers``
        implies no request is outstanding anywhere. Returns False on
        timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._goodbye_cond:
            while self.goodbyes < n:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._goodbye_cond.wait(left)
        return True

    def kill(self) -> None:
        """Simulate abrupt process death (failover drills / bench): sever
        the listener and every connection NOW — no drain, no goodbye, no
        draining flag, engine state left exactly as a SIGKILL would leave
        it. Workers observe the same typed connection failure a real
        primary death produces; an attached backup session degrades."""
        self._stop.set()
        if self._nloop is not None:
            self._pump_abort = True  # queued frames are DROPPED, not
            # applied: a kill must leave the engine as SIGKILL would
            self._nloop.stop_accept()
            self._nloop.shutdown_conns()
            self._nloop.begin_stop()
            self._pump_thread.join(timeout=5)
            if not self._pump_thread.is_alive():
                self._nloop.close()  # a pump stuck mid-apply keeps the
                # handle alive (its reply/free calls no-op after close)
        else:
            self._accept_thread.join(timeout=5)
        self._listener.close()
        s = self._backup_session
        if s is not None:
            s.close()
        with self._chan_lock:
            chans = list(self._channels)
        for ch in chans:
            ch.shutdown()  # serve threads wake with VanError and close

    def stop(self, grace: float = 10.0) -> None:
        """Graceful drain, then sever. No push is applied after this
        returns, and no reply in flight when it was called is torn.

        The guarantee has two legs: the in-flight wait lets every received
        request finish its reply (bounded by ``grace`` seconds), and the
        subclass's draining flag — set under its apply lock — refuses every
        later commit, so even a serve thread that outlives the bounded
        join (e.g. stuck in a minutes-long jit compile) can never land a
        push after this method returns.

        Requests parked on a checkpoint-pause condition do NOT count toward
        the drain wait (they cannot finish until the draining flag wakes
        them into refusal — a coordinator that died between pause and
        resume must not cost the full grace); they are woken by
        ``_set_draining`` and given a short bounded window to send their
        ERR replies before the sever."""
        self._stop.set()
        if self._nloop is not None:
            self._stop_native(grace)
            return
        # join BEFORE closing: the accept thread may be inside tv_accept on
        # the listener handle (its 200ms timeout bounds the wait); closing
        # first would hand it a freed pointer
        self._accept_thread.join(timeout=5)
        self._listener.close()
        deadline = time.monotonic() + grace
        while True:
            with self._inflight_cond:
                while (self._inflight - self._pause_blocked > 0
                       and time.monotonic() < deadline):
                    self._inflight_cond.wait(deadline - time.monotonic())
                drained = self._inflight - self._pause_blocked == 0
            if not drained:
                logging.getLogger(__name__).warning(
                    "request(s) still in flight after %.1fs drain grace; "
                    "severing anyway", grace
                )
                break
            # stability confirm: a serve thread whose recv JUST returned a
            # frame may not have reached its in-flight mark yet (the window
            # between recv returning and the increment cannot be closed —
            # TCP has no atomic refuse). Re-check after a beat; only a
            # stable zero proceeds to the sever.
            time.sleep(0.05)
            with self._inflight_cond:
                if self._inflight - self._pause_blocked == 0:
                    break
            if time.monotonic() >= deadline:
                break
        self._set_draining()
        # pause-parked requests just woke into refusal: give them a short
        # bounded window to send their ERR replies intact before severing
        with self._inflight_cond:
            end = min(deadline, time.monotonic() + 2.0)
            while self._inflight > 0 and time.monotonic() < end:
                self._inflight_cond.wait(max(end - time.monotonic(), 0.01))
        with self._chan_lock:
            chans = list(self._channels)
            conns = list(self._conns)
        for ch in chans:
            ch.shutdown()  # non-freeing sever; each serve thread closes own
        for t in conns:
            t.join(timeout=5)
        stragglers = [t for t in conns if t.is_alive()]
        if stragglers:
            logging.getLogger(__name__).warning(
                "%d serve thread(s) outlived the drain join; their pushes "
                "are refused by the draining flag", len(stragglers)
            )
        s = self._backup_session
        if s is not None:
            s.close()  # after the drain: every acked commit replicated

    def _stop_native(self, grace: float) -> None:
        """stop() for the native event-loop path — the same drain
        contract, over different machinery: "in flight" is the pump's
        accounting PLUS the loop's pending count (frames read but not yet
        handed out, claimed frames awaiting their reply, and unflushed
        reply tails), so a reply the loop has not finished writing is
        never torn by the sever."""
        nloop = self._nloop
        nloop.stop_accept()  # freeze the connection set
        deadline = time.monotonic() + grace

        def quiet() -> bool:
            with self._inflight_cond:
                infl = self._inflight - self._pause_blocked
                parked = self._loop_pause_parked
            # pause-parked LOOP requests each hold exactly one claimed
            # body (freed only at their reply), so they must be
            # discounted from the loop's pending count too — same
            # docstring promise as the threaded drain: a coordinator
            # dead between pause and resume must not cost the full
            # grace. Only loop parks count here: a park on an
            # shm-detached serve thread holds no loop body, and
            # over-discounting could mask a genuinely unflushed tail.
            return infl <= 0 and nloop.pending() - parked <= 0

        drained = False
        while time.monotonic() < deadline:
            if quiet():
                # stability confirm, as in the threaded drain: a frame
                # the loop JUST completed may not be counted yet
                time.sleep(0.05)
                if quiet():
                    drained = True
                    break
            else:
                time.sleep(0.02)
        if not drained:
            logging.getLogger(__name__).warning(
                "request(s) still in flight after %.1fs drain grace; "
                "severing anyway", grace
            )
        self._set_draining()
        # pause-parked punted requests just woke into refusal: bounded
        # window for their ERR replies, then for the loop to flush them
        with self._inflight_cond:
            end = min(deadline, time.monotonic() + 2.0)
            while self._inflight > 0 and time.monotonic() < end:
                self._inflight_cond.wait(max(end - time.monotonic(), 0.01))
        end = min(deadline, time.monotonic() + 0.5)
        while nloop.pending() > 0 and time.monotonic() < end:
            time.sleep(0.02)
        nloop.shutdown_conns()  # idle peers observe EOF now
        nloop.begin_stop()
        self._pump_thread.join(timeout=5)
        # shm-detached connections are classic serve threads: sever + join
        with self._chan_lock:
            chans = list(self._channels)
            conns = list(self._conns)
        for ch in chans:
            ch.shutdown()
        for t in conns:
            t.join(timeout=5)
        stragglers = [t for t in conns if t.is_alive()]
        if self._pump_thread.is_alive():
            stragglers.append(self._pump_thread)
        if stragglers:
            logging.getLogger(__name__).warning(
                "%d serve/pump thread(s) outlived the drain join; their "
                "pushes are refused by the draining flag", len(stragglers)
            )
        if not self._pump_thread.is_alive():
            nloop.close()  # frees the loop; skipped only while the pump
            # (the one poll() caller) could still touch the raw handle —
            # punted threads' reply/free calls no-op after close
        self._listener.close()
        s = self._backup_session
        if s is not None:
            s.close()  # after the drain: every acked commit replicated
