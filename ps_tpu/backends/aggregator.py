"""Hierarchical two-level aggregation: the per-host aggregator role.

The reference's transport is two-tier (SURVEY §2, the ps-lite/BytePS
family design): gradients reduce INTRA-node first, then cross the slow
inter-node path once per node. Our remote data plane was flat
worker→shard — every worker on a host independently pushed the same-shaped
gradient tree over cross-host TCP. This module composes two finished
subsystems into that missing tier: the PR 3 shm lane makes the intra-host
worker→aggregator hop nearly free, and the PR 9 native epoll loop gives
the aggregator a GIL-free serve path, so the pre-reduction itself is the
only new work on the hot path.

:class:`AggregatorService` is a van service the host group's workers dial
INSTEAD of the shards (``connect_async(..., aggregator="host:port")``,
or discovered from the coordinator's membership table). To its group it
looks like a single shard owning the whole tree; upstream it is one
:class:`~ps_tpu.backends.remote_async.RemoteAsyncWorker` under a
synthetic identity (:data:`~ps_tpu.backends.common.AGG_WORKER_BASE` +
group index):

- **push pre-reduction**: member pushes stage into the current ROUND;
  when ``group_size`` distinct members staged (or the flush timeout
  passes — a dead member must not wedge its group), the round's trees
  are summed in ascending-member order (deterministic merge) and
  forwarded as ONE upstream push_pull. Cross-host bytes/step drop by the
  realized fan-in; the path composes unchanged with compression (the
  upstream client's codec) and the exactly-once ledger (below).
- **pull coalescing**: the merged flush's returned snapshot answers the
  whole group's pulls for that round locally; a pull with no flush in
  between triggers ONE upstream wire fetch, shared by every concurrent
  reader — one fetch per host per version.
- **exactly-once across the handoff**: the merged push travels under the
  aggregator's own derived (nonce, seq) token AND carries each
  constituent member's (nonce, seq) in ``members``; the shard records
  both (different worker ids — neither evicts the other). An aggregator
  death therefore cannot violate the ledger in either direction: the
  group degrades to the flat worker→shard path (the worker-side
  ``_on_server_lost`` hook), and a member's flat replay of a push its
  dead aggregator already forwarded is acked without re-applying.

Semantics note: the shards see ONE apply per group round (the summed
tree, DC-corrected against the AGGREGATOR's last pull) instead of
``group_size`` separate applies — the standard hierarchical-PS trade.
Under plain SGD the sum-then-apply is exactly the sequential applies;
under DC-ASGD the group shares one staleness term, which is the BytePS
semantic. tests/test_aggregation.py pins the exactly-once ledger bitwise
with integer-exact gradients.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ps_tpu import obs
from ps_tpu.backends.common import (
    AGG_WORKER_BASE,
    DEFAULT_BUCKET_BYTES,
    BucketPlan,
    parse_replica_uri,
)
from ps_tpu.backends.van_service import VanService
from ps_tpu.compress import decode_tree
from ps_tpu.control import tensor_van as tv
from ps_tpu.kv import keys as keymod
from ps_tpu.obs import freshness

__all__ = ["AggregatorService", "serve_aggregator"]


class AggregatorService(VanService):
    """Pre-reduce a host group's pushes into one upstream push per round.

    Args:
      uri: upstream shard URI list (``h0:p0,h1:p1,...``, ``|`` replica
        sets) — or None with ``coordinator`` set (the shard table is
        fetched, and this aggregator registers itself under this host's
        name so the group's workers discover it).
      params_like: the model's parameter structure (what the upstream
        client validates the partition against).
      group_size: local fan-in — how many same-host workers share this
        aggregator (None = PS_AGG_GROUP_SIZE, default 1). A round
        forwards as soon as this many distinct members staged.
      flush_timeout_ms: how long an incomplete round waits for its
        remaining members before flushing partial (None =
        PS_AGG_FLUSH_TIMEOUT_MS, default 2000) — a dead member degrades
        its group's latency, never wedges it.
      group: this aggregator's group index (its upstream identity is
        ``AGG_WORKER_BASE + group``).
      bucket_bytes/pool_size/compress/...: the UPSTREAM client's
        transport knobs (the cross-host hop — where compression and
        bucketing pay); the member-facing side accepts the same bucketed
        frames and shm-lane offers any VanService does.
      host: the group key this aggregator registers under at the
        coordinator (default: this machine's hostname — same-host
        workers resolve the same name).
    """

    def __init__(self, uri: Optional[str], params_like,
                 group_size: Optional[int] = None,
                 flush_timeout_ms: Optional[float] = None,
                 group: int = 0,
                 port: int = 0, bind: str = "127.0.0.1",
                 bucket_bytes: Optional[int] = None,
                 pool_size: Optional[int] = None,
                 compress=None, writev: Optional[bool] = None,
                 shm: Optional[bool] = None,
                 shm_bytes: Optional[int] = None,
                 failover_timeout: Optional[float] = None,
                 coordinator=None, host: Optional[str] = None,
                 advertise_host: str = "127.0.0.1",
                 native_loop: Optional[bool] = None,
                 loop_threads: Optional[int] = None):
        from ps_tpu.backends.remote_async import RemoteAsyncWorker
        from ps_tpu.config import env_float, env_int

        if group_size is None:
            # validated service-level read (pslint PSL406): Config's
            # agg_group_size floor of 1 applies here too
            group_size = env_int("PS_AGG_GROUP_SIZE", 1, lo=1)
        self.group_size = max(int(group_size), 1)
        if flush_timeout_ms is None:
            flush_timeout_ms = env_float("PS_AGG_FLUSH_TIMEOUT_MS",
                                         2000.0, lo=1.0)
        self._flush_timeout = float(flush_timeout_ms) / 1e3
        self.group = int(group)
        table = None
        if coordinator is not None:
            from ps_tpu.elastic.member import fetch_table

            want, _ = keymod.flatten_with_keys(params_like)
            table = fetch_table(coordinator, cover=want)
            addrs, replica_sets = table.addrs(), table.replica_sets()
        elif uri is None:
            raise ValueError("AggregatorService needs an upstream uri or "
                             "a coordinator address")
        else:
            addrs, replica_sets = parse_replica_uri(uri)
        # the upstream identity: ONE worker per group, outside the real
        # id space, so merged pushes get their own dedup/staleness slots
        self._client = RemoteAsyncWorker.connect_many(
            addrs, AGG_WORKER_BASE + self.group, params_like,
            bucket_bytes=bucket_bytes, pool_size=pool_size,
            compress=compress, writev=writev, shm=shm,
            shm_bytes=shm_bytes, replica_sets=replica_sets,
            failover_timeout=failover_timeout,
            coordinator=coordinator, table=table, agg_role=True)
        self._key_order = list(self._client._key_order)
        # the push key-set check runs per member per round: sort ONCE
        self._sorted_keys = sorted(self._key_order)
        # round state, all under _rcv: the CURRENT round fills until
        # group_size members staged (or its deadline passes), then the
        # flusher thread forwards it and installs a fresh one
        self._rcv = threading.Condition()
        self._rounds_done = 0
        self._round = self._new_round()
        self._draining = False
        self._stopped = False
        # coalesced-pull snapshot (one wire fetch per host per version):
        # guarded by _pcv; "round" names the flush count it reflects
        self._pcv = threading.Condition()
        self._pull_snap: Optional[dict] = None
        self._pull_fetching = False
        # THE upstream-client lock: the flusher thread (merged push_pull)
        # and member-serving threads (coalesced pull_all) share ONE
        # RemoteAsyncWorker whose channels allow a single driving thread
        # at a time — every upstream round trip serializes here
        self._ulock = threading.Lock()
        # member-facing bucketed pulls: per-worker snapshot + plan cache
        # (same shape as AsyncPSService._pull_cache, under _stage_lock)
        self._pull_cache: Dict[int, dict] = {}
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True, name="ps-agg-flush")
        super().__init__(port=port, bind=bind, writev=writev, shm=shm,
                         native_loop=native_loop, loop_threads=loop_threads)
        self.role = "aggregator"  # after super(): introspection truth
        self._flusher.start()
        self._coord = coordinator
        self.host = host
        if coordinator is not None:
            import socket

            self.host = host or socket.gethostname()
            self._register(coordinator, f"{advertise_host}:{self.port}")

    #: member pushes/pulls PARK on the group barrier (a push waits for
    #: the round's other members) — on the native loop they must never
    #: run inline on the pump, and never queue behind parked pool workers
    _BARRIER_KINDS = frozenset({tv.PUSH, tv.PUSH_PULL, tv.BUCKET_PUSH,
                                tv.PULL, tv.BUCKET_PULL, tv.READ})

    def _register(self, coordinator, uri: str) -> None:
        """Join the membership table as this host's aggregator (the
        coordinator-assigned grouping: workers on ``self.host`` discover
        ``uri`` from the table reply and dial it instead of the shards)."""
        if isinstance(coordinator, str):
            chost, cport = coordinator.rsplit(":", 1)
        else:
            chost, cport = coordinator
        ch = tv.Channel.connect(chost, int(cport))
        try:
            kind, _, _, extra = tv.decode(ch.request(tv.encode(
                tv.COORD_HELLO, 0, None,
                extra={"role": "aggregator", "uri": uri,
                       "host": self.host})))
            if kind != tv.OK:
                raise RuntimeError(f"aggregator registration refused: "
                                   f"{extra.get('error')}")
        finally:
            ch.close()
        logging.getLogger(__name__).info(
            "aggregator for host %s registered at %s (group %d, "
            "fan-in %d)", self.host, uri, self.group, self.group_size)

    # -- rounds ----------------------------------------------------------------

    def _new_round(self) -> dict:
        return {
            "id": self._rounds_done,
            "state": "filling",          # -> flush -> flushing -> done
            "members": {},               # worker -> grad tree (host kv)
            "tokens": {},                # worker -> (pnonce, pseq)
            "tcs": {},                   # worker -> TraceContext (traced
            "deadline": None,            # members only)
            "kv": None,                  # post-flush params snapshot
            "version": None,
            "error": None,
        }

    def _flush_loop(self) -> None:
        """THE flusher: waits for the current round to fill (or time
        out), swaps in a fresh round, and forwards the merged push —
        upstream I/O always OUTSIDE the round lock, so staging for the
        next round proceeds while this one crosses the host boundary."""
        while True:
            with self._rcv:
                while True:
                    if self._stopped:
                        return
                    r = self._round
                    if self._draining:
                        # stop() already woke this round's parked members
                        # into refusal — their staged gradients must NOT
                        # go upstream behind those failed replies (the
                        # member would retry under a new seq and
                        # double-apply). Abandon the round and idle
                        # until the stop completes.
                        if r["state"] != "done":
                            r["state"] = "done"
                            r["error"] = RuntimeError(
                                "aggregator is draining; push refused")
                            self._rcv.notify_all()
                        self._rcv.wait(0.05)
                        continue
                    if r["state"] == "flush":
                        break
                    if (r["members"] and r["deadline"] is not None
                            and time.monotonic() >= r["deadline"]):
                        # partial flush: a member died / lags — its group
                        # pays latency once per round, never a wedge
                        break
                    self._rcv.wait(0.05)
                r["state"] = "flushing"
                self._round = self._new_round()
                self._rcv.notify_all()  # stagers may start the next round
            self._do_flush(r)

    def _do_flush(self, r: dict) -> None:
        t0 = time.perf_counter()
        try:
            # trace the merge when any constituent was traced: the merge
            # span parents to the FIRST traced member's serve span
            # (deterministic — lowest worker id) and names the rest, and
            # staying open across the upstream push_pull parents the
            # upstream op span — and through it the shard's dispatch /
            # server_apply / replica_append spans — into the member's
            # trace: the worker→aggregator→shard chain is ONE trace.
            tcs = r.get("tcs") or {}
            if tcs:
                mspan = obs.tracer().span("agg_merge", cat="aggregator",
                                          parent=tcs[min(tcs)])
            else:
                mspan = obs.NOOP
            with mspan as sp:
                if sp:
                    sp.set(group=self.group, members=sorted(r["tokens"]),
                           member_traces={str(w): c.trace_id
                                          for w, c in tcs.items()})
                order = sorted(r["members"])  # deterministic merge order
                merged: Dict[str, np.ndarray] = {}
                for w in order:
                    tree = r["members"][w]
                    if not merged:
                        # own-memory accumulator (member trees may view
                        # request frames that die at their reply)
                        merged = {k: np.array(v) for k, v in tree.items()}
                    else:
                        for k, v in tree.items():
                            merged[k] += v
                r["members"] = None  # release members' frame views early
                members = {str(w): [t[0], int(t[1])]
                           for w, t in r["tokens"].items()
                           if t is not None and t[1] is not None}
                # the merged push carries every constituent's trace
                # context BESIDE its dedup token: the shard's apply span
                # names the member traces it commits for, so any one
                # member's trace finds the shared upstream commit
                members_tc = {str(w): [c.trace_id, c.span_id]
                              for w, c in tcs.items()}
                # ONE upstream round trip: apply the merged tree and
                # bring the post-apply snapshot back — it answers the
                # whole group's pulls for this round
                with self._ulock:
                    params = self._client.push_pull(
                        merged, members=members or None,
                        members_tc=members_tc or None)
                    version = self._client.version
                kv, _ = keymod.flatten_with_keys(params)
                r["kv"] = {k: np.ascontiguousarray(np.asarray(v))
                           for k, v in kv.items()}
                r["version"] = version
                # freshness birth for the round snapshot: the merged
                # apply JUST committed upstream and these bytes are its
                # post-apply state, so the round is born here, now —
                # stamped in THIS process, members age it monotonically
                r["b"] = freshness.birth_record()
        except BaseException as e:  # surfaced at every parked member
            r["error"] = e
        if r["error"] is None:
            self.transport.record_agg_round(len(r["tokens"]))
            # publish the snapshot BEFORE the round-done transition:
            # _rounds_done is written only by this thread, so a puller
            # that races the gap sees a snapshot round AHEAD of its rid
            # (>= is what it checks) instead of launching the redundant
            # upstream fetch the coalescing exists to eliminate
            with self._pcv:
                self._pull_snap = {"round": self._rounds_done + 1,
                                   "kv": r["kv"],
                                   "version": r["version"],
                                   "b": r["b"]}
                self._pcv.notify_all()
        with self._rcv:
            self._rounds_done += 1
            ordinal = self._rounds_done
            r["state"] = "done"
            self._rcv.notify_all()
        if r["error"] is None:
            # invalidation-on-apply, aggregator edition: the group's
            # committed round supersedes every cached member READ reply
            self._invalidate_reads()
        logging.getLogger(__name__).debug(
            "aggregator group %d flushed round %d (%d member(s), "
            "%.1fms)%s", self.group, ordinal, len(r["tokens"]),
            (time.perf_counter() - t0) * 1e3,
            f" FAILED: {r['error']!r}" if r["error"] else "")

    def _agg_push(self, worker: int, tree: Dict[str, np.ndarray],
                  extra: dict) -> dict:
        """Stage one member's push into the current round and park until
        the merged upstream flush commits; returns the finished round."""
        if sorted(tree) != self._sorted_keys:
            raise KeyError("push keys do not match the registered tree")
        t0 = time.perf_counter()
        token = (extra.get("pnonce"), extra.get("pseq"))
        # the serve span opened by _dispatch is current on THIS thread;
        # its context is what the flusher's merge span parents to
        ctx = obs.tracer().current()
        with self._rcv:
            while True:
                if self._draining:
                    raise RuntimeError(
                        "aggregator is draining; push refused")
                r = self._round
                if r["state"] == "filling" and worker not in r["members"]:
                    break
                if r["state"] == "filling":
                    # this member is a round ahead of its group: force
                    # the staged round out so one member's pushes can
                    # never interleave within a merged apply
                    r["state"] = "flush"
                    self._rcv.notify_all()
                self._rcv.wait(0.05)
            r["members"][worker] = tree
            r["tokens"][worker] = token
            if ctx is not None:
                r["tcs"][worker] = ctx
            if r["deadline"] is None:
                r["deadline"] = time.monotonic() + self._flush_timeout
            if len(r["members"]) >= self.group_size:
                r["state"] = "flush"
                self._rcv.notify_all()
            # park until the flusher commits the round upstream. Counted
            # like a checkpoint-pause park so stop()'s drain never burns
            # its grace on barrier waiters (they wake into refusal).
            self._pause_wait_begin()
            try:
                while r["state"] != "done":
                    if self._draining:
                        raise RuntimeError(
                            "aggregator is draining; push refused")
                    self._rcv.wait(0.1)
            finally:
                self._pause_wait_end()
        if r["error"] is not None:
            raise RuntimeError(
                f"merged upstream push failed: {r['error']!r}")
        self.transport.record_agg_hold(time.perf_counter() - t0)
        return r

    # -- coalesced pulls -------------------------------------------------------

    def _coalesced_pull(self) -> dict:
        """The group's shared snapshot for the CURRENT round: served from
        the last merged flush when fresh, else ONE upstream wire fetch —
        concurrent readers wait on the same fetch instead of fanning N
        identical pulls over the cross-host path.

        The wire fetch is a ``read_all`` (README "Read path"), not a
        pull: a coalesced fetch between flushes is a serving read, so it
        rides the shard's native zero-upcall cache and its replica set —
        and, crucially, it needs no ``_ulock`` (dedicated read channels,
        never the flusher's framed stream), so a read-mostly member no
        longer waits out a merged flush to refresh its snapshot. The
        upstream DC stale snapshot stays pinned to the last flush's
        push_pull — which is the snapshot the group's grads were
        computed against when rounds are flowing; a mid-round coalesced
        read deliberately does not move it."""
        while True:
            with self._rcv:
                rid = self._rounds_done
            with self._pcv:
                snap = self._pull_snap
                if snap is not None and snap["round"] >= rid:
                    return snap
                if self._pull_fetching:
                    self._pcv.wait(0.1)
                    continue
                self._pull_fetching = True
            try:
                # AS-SERVED version, atomic with the bytes: the known
                # self._client.version can run ahead of a bounded-stale
                # replica read (or a flush decoding acks mid-read), and
                # a snapshot stamped newer than its bytes would park
                # stale rows in members' version-keyed caches. The
                # stamped read also brings the OLDEST constituent
                # shard's birth, so the group's age chain never loses
                # the upstream hop.
                params, version, birth = self._client.read_all_stamped()
                with self._pcv:
                    prev = self._pull_snap
                if prev is not None \
                        and int(prev["version"]) == int(version):
                    # upstream unchanged since the held snapshot (the
                    # client's conditional read proved it with a
                    # NOT_MODIFIED handshake): re-stamp the round and
                    # keep the bytes — no re-flatten, no tree copy.
                    # The birth DOES refresh (an NM revalidation proves
                    # the held bytes are still the newest version — the
                    # reply's stamp is that version's, so age keeps
                    # flowing even while the upstream sits idle).
                    snap = {"round": rid, "kv": prev["kv"],
                            "version": int(version),
                            "b": birth if birth is not None
                            else prev.get("b")}
                else:
                    kv, _ = keymod.flatten_with_keys(params)
                    snap = {"round": rid,
                            "kv": {k: np.ascontiguousarray(np.asarray(v))
                                   for k, v in kv.items()},
                            "version": version, "b": birth}
            except BaseException:
                with self._pcv:
                    self._pull_fetching = False
                    self._pcv.notify_all()
                raise
            with self._pcv:
                self._pull_fetching = False
                cur = self._pull_snap
                if cur is None or cur["round"] <= snap["round"]:
                    self._pull_snap = snap
                self._pcv.notify_all()
                return self._pull_snap

    def _read_payload(self, extra=None) -> bytes:
        """Member READs (README "Read path") serve the group's coalesced
        snapshot — one upstream fetch per round however many members
        read — and publish into the native read cache: the generation is
        captured BEFORE the fetch, so a merged round committing mid-read
        refuses the stale publish at the floor. A conditional READ
        (``extra["cond"]``) at or past the snapshot's version gets a
        NOT_MODIFIED stamp instead of the tree."""
        gen = self._read_gen_snapshot()
        snap = self._coalesced_pull()
        birth = snap.get("b")
        bext = dict(birth) if birth is not None else {}
        cond = None
        if isinstance(extra, dict) and extra.get("cond") is not None:
            cond = int(extra["cond"])
        if cond is not None and int(snap["version"]) <= cond:
            reply = tv.encode(tv.NOT_MODIFIED, 0, None,
                              extra={"version": int(snap["version"]),
                                     **bext})
            self._note_read_snapshot(gen, int(snap["version"]))
            self.transport.record_read_served()
            self.transport.record_read_not_modified()
            self._note_serve_age(birth, tier="agg")
            return reply
        reply = tv.encode(tv.OK, 0, snap["kv"],
                          extra={"version": snap["version"], **bext})
        self._note_read_snapshot(gen, int(snap["version"]))
        self.transport.record_read_served()
        self._note_serve_age(birth, tier="agg")
        return reply

    def _read_version(self):
        return self._client.version

    def _params_reply(self, worker: int, snap: dict) -> bytes:
        if self.writev:
            return tv.encode_parts(tv.OK, worker, snap["kv"],
                                   extra={"version": snap["version"]})
        return tv.encode(tv.OK, worker, snap["kv"],
                         extra={"version": snap["version"]})

    # -- protocol --------------------------------------------------------------

    def _dispatch_traced(self, kind: int, worker: int, tensors,
                         extra) -> bytes:
        # no primary/backup gate: an aggregator serves its group directly
        # (REPLICA_STATE still answers so clock probes and ps_top work)
        if kind == tv.REPLICA_STATE:
            return tv.encode(tv.OK, worker, None, extra=self.replica_state())
        return self._handle(kind, worker, tensors, extra)

    def _handle(self, kind: int, worker: int, tensors, extra) -> bytes:
        if kind == tv.HELLO:
            return tv.encode(tv.OK, worker, None, extra={
                "keys": self._key_order,
                "version": self._client.version,
                "num_workers": self._client.num_workers,
                "shard": None,
                "num_shards": None,
                "epoch": self.epoch,
                "role": self.role,
                "table_epoch": self.table_epoch,
            })
        elif kind == tv.PULL:
            return self._params_reply(worker, self._coalesced_pull())
        elif kind == tv.READ:
            return self._read_payload(extra)
        elif kind == tv.PUSH:
            tree = self._decode_member_push(tensors, extra)
            r = self._agg_push(worker, tree, extra)
            return tv.encode(tv.OK, worker, None,
                             extra={"version": r["version"]})
        elif kind == tv.PUSH_PULL:
            tree = self._decode_member_push(tensors, extra)
            r = self._agg_push(worker, tree, extra)
            return self._params_reply(
                worker, {"kv": r["kv"], "version": r["version"]})
        elif kind == tv.BUCKET_PUSH:
            return self._bucket_push(worker, tensors, extra)
        elif kind == tv.BUCKET_PULL:
            return self._bucket_pull(worker, extra)
        elif kind == tv.STATS:
            out = {
                "version": self._client.version,
                "rounds": self._rounds_done,
                "group_size": self.group_size,
                "metrics": self.transport.metrics_snapshot(),
                "upstream": {
                    "bytes_pushed": self._client.bytes_pushed,
                    "bytes_pulled": self._client.bytes_pulled,
                },
            }
            out.update(self.replica_state())
            return tv.encode(tv.OK, worker, None, extra=out)
        return tv.encode(tv.ERR, worker, None,
                         extra={"error": f"bad kind {kind} (aggregators "
                                         f"serve the data plane only)"})

    def _decode_member_push(self, tensors, extra) -> Dict[str, np.ndarray]:
        # no defensive copy: a serial frame's views stay valid for the
        # whole round — the serve thread parks in _agg_push until the
        # flush is done, and its request buffer is only released after
        # the reply. _do_flush reads the views exactly once (the merged
        # accumulator owns its memory) and drops them before the
        # upstream push.
        return decode_tree(dict(tensors), extra.get("enc"),
                           stats=self.transport)

    def _bucket_push(self, worker: int, tensors, extra) -> bytes:
        """Member half of a multi-bucket push: incomplete epochs only
        stage (plain ack); the completing bucket joins the round and
        parks for the merged commit — the member observes exactly the
        shard protocol's reply shapes."""
        tree = self._stage_bucket_push(
            worker, int(extra["bucket"]), int(extra["nbuckets"]),
            int(extra["epoch"]), tensors["raw"], extra["slices"],
            nonce=extra.get("nonce"),
        )
        if tree is None:
            return tv.encode(tv.OK, worker, None,
                             extra={"staged": int(extra["bucket"])})  # pslint: disable=PSL203 -- debug-visibility ack field, same contract as AsyncPSService._bucket_push: names the staged bucket for packet-level triage
        tree = decode_tree(tree, extra.get("enc"), stats=self.transport)
        r = self._agg_push(worker, tree, extra)
        return tv.encode(tv.OK, worker, None, extra={
            "version": r["version"], "committed": True,
        })

    def _bucket_pull(self, worker: int, extra) -> bytes:
        """Bucketed pull over the coalesced snapshot: bucket 0 binds this
        worker's epoch to the group snapshot (ONE upstream fetch however
        many members ask); buckets 1..n-1 slice the cached copy."""
        epoch, b = int(extra["epoch"]), int(extra["bucket"])
        if b == 0:
            bb = int(extra.get("bucket_bytes") or DEFAULT_BUCKET_BYTES)
            snap = self._coalesced_pull()
            plan = BucketPlan.from_arrays(snap["kv"], bb,
                                          order=self._key_order)
            with self._stage_lock:
                if plan.nbuckets > 1:
                    self._pull_cache[worker] = {
                        "epoch": epoch, "host": snap["kv"], "plan": plan,
                        "version": snap["version"],
                        "left": set(range(1, plan.nbuckets)),
                    }
                else:
                    self._pull_cache.pop(worker, None)
            enc_fn = plan.bucket_encoder(self.writev)
            return enc_fn(tv.OK, worker, snap["kv"], 0, extra={
                "epoch": epoch, "version": snap["version"], "enc": [],
            })
        with self._stage_lock:
            entry = self._pull_cache.get(worker)
            if (entry is None or entry["epoch"] != epoch
                    or b not in entry["left"]):
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": f"no cached pull snapshot for worker "
                             f"{worker} epoch {epoch} bucket {b}",
                })
            entry["left"].discard(b)
            if not entry["left"]:
                self._pull_cache.pop(worker, None)
        enc_fn = entry["plan"].bucket_encoder(self.writev)
        return enc_fn(tv.OK, worker, entry["host"], b,
                      extra={"epoch": epoch, "version": entry["version"],
                             "enc": []})

    # -- lifecycle -------------------------------------------------------------

    def _set_draining(self) -> None:
        with self._rcv:
            self._draining = True
            self._rcv.notify_all()  # barrier waiters wake into refusal

    def stop(self, grace: float = 10.0) -> None:
        super().stop(grace=grace)
        with self._rcv:
            self._stopped = True
            self._rcv.notify_all()
        self._flusher.join(timeout=5)
        try:
            self._client.close()
        except Exception:
            pass  # a dead upstream must not block the local teardown

    def kill(self) -> None:
        """SIGKILL-equivalent for the failure drills: sever the group's
        connections NOW. In-flight rounds die unacked — exactly the
        window the constituent-token ledger covers when members degrade
        to the flat path and replay."""
        super().kill()
        with self._rcv:
            self._stopped = True
            self._draining = True
            self._rcv.notify_all()
        self._flusher.join(timeout=5)
        try:
            self._client.close()
        except Exception:
            pass


def serve_aggregator(uri: Optional[str], params_like,
                     group_size: Optional[int] = None,
                     **kw) -> AggregatorService:
    """Start a host group's aggregator (README "Two-tier aggregation").

    The launcher-shaped entry: one per host, ``group_size`` = the host's
    worker count (PS_AGG_GROUP_SIZE), ``uri`` = the shard fleet (or
    ``coordinator=`` for elastic membership — the aggregator then
    registers under this host's name and the group's workers discover it
    from the table). Returns the running service (``.port``,
    ``.stop()``)."""
    return AggregatorService(uri, params_like, group_size=group_size, **kw)
