"""Shared server-engine pieces (single source of truth for both backends).

The local backend's semantics are the spec the mesh backend must match
(asserted by tests/test_async_tpu.py); keeping the DC apply and the
introspection read in one place guarantees a fix to one cannot silently
break that parity.
"""

from __future__ import annotations

import jax
import optax

from ps_tpu.optim.dc import delay_compensate


def make_jit_dc_apply_tree(opt: optax.GradientTransformation):
    """Fused whole-tree async apply: ONE XLA dispatch per push_all.

    The per-key loop unrolls at trace time into a single program (the
    bucketing pass SURVEY.md §3 row 11 reserves for the async host path —
    XLA fuses the per-key DC corrections and updates instead of the host
    dispatching one apply per key). Numerically identical to the per-key
    sequence: keys are independent under per-tensor optimizers, asserted by
    tests/test_async_stress.py.

    ``fn(params, states, grads, stales, lam) -> (params, states)`` over
    ``{key: ...}`` dicts with per-key optimizer states.
    """

    def _apply_dc_tree(params, states, grads, stales, lam):
        new_p, new_s = {}, {}
        for k in params:  # unrolled at trace time
            g = delay_compensate(grads[k], params[k], stales[k], lam)
            updates, s = opt.update(g, states[k], params[k])
            new_p[k] = optax.apply_updates(params[k], updates)
            new_s[k] = s
        return new_p, new_s

    return jax.jit(_apply_dc_tree, static_argnums=(4,))


class PeekMixin:
    """Side-effect-free key read for introspection (KVStore.params()):
    never records async pull snapshots or checks aggregation state."""

    def peek(self, key: str) -> jax.Array:
        if key not in self._params:
            raise KeyError(f"unregistered key {key!r}")
        return self._params[key]


class AsyncStagingMixin:
    """Per-key async pushes stage per WORKER and commit as one fused tree
    apply when that worker's tree completes (SURVEY.md §3 row 11 bucketing:
    a logical push commits as a unit). This makes an N-key per-key push
    sequence cost ONE XLA dispatch instead of N (VERDICT r2 weak #7), and —
    because staging is per worker — the version bump and staleness sample
    are attributed to the worker that actually completed a tree, never to
    whichever worker happened to push last under interleaving (ADVICE r2).

    Semantics note: keys of a partially-pushed tree are unapplied until the
    tree completes; a concurrent pull observes the pre-commit parameters
    (previously each key applied immediately). Final post-tree state is
    numerically identical — keys are independent under per-tensor
    optimizers.

    Engine contract: ``self._staged_async`` dict exists, ``self._params`` is
    the registered key set, caller holds the engine lock, and
    ``self._commit_tree(grads_kv, worker)`` performs the fused apply.
    """

    def _stage_async_push(self, key, grad, worker) -> None:
        staged = self._staged_async.setdefault(worker, {})
        if key in staged:
            raise RuntimeError(
                f"worker {worker} pushed key {key!r} twice before completing "
                f"a tree — per-key async pushes commit at tree granularity"
            )
        staged[key] = grad
        if len(staged) == len(self._params):
            del self._staged_async[worker]
            self._commit_tree(staged, worker)

    def _check_staged_async(self) -> None:
        """Checkpoint guard: staged-but-uncommitted grads would be lost."""
        pending = {w: sorted(kv) for w, kv in self._staged_async.items() if kv}
        if pending:
            raise RuntimeError(
                f"cannot checkpoint mid-push: workers {sorted(pending)} have "
                f"staged but uncommitted per-key async pushes"
            )
