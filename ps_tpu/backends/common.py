"""Shared server-engine pieces (single source of truth for both backends).

The local backend's semantics are the spec the mesh backend must match
(asserted by tests/test_async_tpu.py); keeping the DC apply and the
introspection read in one place guarantees a fix to one cannot silently
break that parity.
"""

from __future__ import annotations

import jax
import optax

from ps_tpu.optim.dc import delay_compensate


def make_jit_dc_apply(opt: optax.GradientTransformation):
    """Jitted per-key async apply: DC-ASGD correction then optimizer update.

    ``fn(param, state, grad, stale_param, lam) -> (param, state)`` with lam
    static (SURVEY.md §4d: g̃ = g + λ·g⊙g⊙(w_now − w_stale))."""

    def _apply_dc(param, state, grad, stale_param, lam):
        g = delay_compensate(grad, param, stale_param, lam)
        updates, new_state = opt.update(g, state, param)
        return optax.apply_updates(param, updates), new_state

    return jax.jit(_apply_dc, static_argnums=(4,))


def make_jit_dc_apply_tree(opt: optax.GradientTransformation):
    """Fused whole-tree async apply: ONE XLA dispatch per push_all.

    The per-key loop unrolls at trace time into a single program (the
    bucketing pass SURVEY.md §3 row 11 reserves for the async host path —
    XLA fuses the per-key DC corrections and updates instead of the host
    dispatching one apply per key). Numerically identical to the per-key
    sequence: keys are independent under per-tensor optimizers, asserted by
    tests/test_async_stress.py.

    ``fn(params, states, grads, stales, lam) -> (params, states)`` over
    ``{key: ...}`` dicts with per-key optimizer states.
    """

    def _apply_dc_tree(params, states, grads, stales, lam):
        new_p, new_s = {}, {}
        for k in params:  # unrolled at trace time
            g = delay_compensate(grads[k], params[k], stales[k], lam)
            updates, s = opt.update(g, states[k], params[k])
            new_p[k] = optax.apply_updates(params[k], updates)
            new_s[k] = s
        return new_p, new_s

    return jax.jit(_apply_dc_tree, static_argnums=(4,))


class PeekMixin:
    """Side-effect-free key read for introspection (KVStore.params()):
    never records async pull snapshots or checks aggregation state."""

    def peek(self, key: str) -> jax.Array:
        if key not in self._params:
            raise KeyError(f"unregistered key {key!r}")
        return self._params[key]
