"""Shared server-engine pieces (single source of truth for both backends).

The local backend's semantics are the spec the mesh backend must match
(asserted by tests/test_async_tpu.py); keeping the DC apply and the
introspection read in one place guarantees a fix to one cannot silently
break that parity.
"""

from __future__ import annotations

import jax
import optax

from ps_tpu.optim.dc import delay_compensate


def make_jit_dc_apply_tree(opt: optax.GradientTransformation):
    """Fused whole-tree async apply: ONE XLA dispatch per push_all.

    The per-key loop unrolls at trace time into a single program (the
    bucketing pass SURVEY.md §3 row 11 reserves for the async host path —
    XLA fuses the per-key DC corrections and updates instead of the host
    dispatching one apply per key). Numerically identical to the per-key
    sequence: keys are independent under per-tensor optimizers, asserted by
    tests/test_async_stress.py.

    ``fn(params, states, grads, stales, lam) -> (params, states)`` over
    ``{key: ...}`` dicts with per-key optimizer states.
    """

    def _apply_dc_tree(params, states, grads, stales, lam):
        new_p, new_s = {}, {}
        for k in params:  # unrolled at trace time
            g = delay_compensate(grads[k], params[k], stales[k], lam)
            updates, s = opt.update(g, states[k], params[k])
            new_p[k] = optax.apply_updates(params[k], updates)
            new_s[k] = s
        return new_p, new_s

    return jax.jit(_apply_dc_tree, static_argnums=(4,))


class PeekMixin:
    """Side-effect-free key read for introspection (KVStore.params()):
    never records async pull snapshots or checks aggregation state."""

    def peek(self, key: str) -> jax.Array:
        if key not in self._params:
            raise KeyError(f"unregistered key {key!r}")
        return self._params[key]


class AsyncStagingMixin:
    """Per-key async pushes stage per WORKER and commit as one fused tree
    apply when that worker's tree completes (SURVEY.md §3 row 11 bucketing:
    a logical push commits as a unit). This makes an N-key per-key push
    sequence cost ONE XLA dispatch instead of N (VERDICT r2 weak #7), and —
    because staging is per worker — the version bump and staleness sample
    are attributed to the worker that actually completed a tree, never to
    whichever worker happened to push last under interleaving (ADVICE r2).

    Liveness: a worker that pushes only a SUBSET of keys commits that
    partial tree the moment it pulls (the pull marks the end of its push
    phase in the PS cycle), so per-key callers that never touch every key
    still make progress — one dispatch per push-pull cycle. Keys are
    independent under per-tensor optimizers, so a partial commit is
    numerically the same as the old immediate per-key applies.

    Engine contract: ``self._staged_async``/``self._params``/``self._state``/
    ``self._stale`` dicts, ``self._jit_apply_dc_tree``, ``self.dc_lambda``,
    ``self.apply_count``, ``self.staleness_hist``, ``self._version`` exist;
    the caller holds the engine lock. Engines may override
    ``_commit_tree_accounting`` for extra per-commit counters.
    """

    def _stage_async_push(self, key, grad, worker) -> None:
        staged = self._staged_async.setdefault(worker, {})
        if key in staged:
            raise RuntimeError(
                f"worker {worker} pushed key {key!r} twice before committing "
                f"— per-key async pushes commit when the full tree is pushed "
                f"or at this worker's next pull (partial tree)"
            )
        staged[key] = grad
        if len(staged) == len(self._params):
            del self._staged_async[worker]
            self._commit_tree(staged, worker)

    def _flush_staged(self, worker) -> None:
        """Commit this worker's staged partial tree, if any (call at the top
        of every async pull, lock held)."""
        staged = self._staged_async.pop(worker, None)
        if staged:
            self._commit_tree(staged, worker)

    def _commit_tree(self, grads_kv, worker) -> None:
        """ONE fused DC apply of a (possibly partial) tree — lock held."""
        sub_p = {k: self._params[k] for k in grads_kv}
        sub_s = {k: self._state[k] for k in grads_kv}
        stales = {
            k: self._stale.get((worker, k), self._params[k]) for k in grads_kv
        }
        new_p, new_s = self._jit_apply_dc_tree(
            sub_p, sub_s, grads_kv, stales, self.dc_lambda
        )
        self._params.update(new_p)
        self._state.update(new_s)
        for k in grads_kv:
            self.apply_count[k] += 1
        self.staleness_hist[self.staleness(worker)] += 1
        self._version += 1
        self._commit_tree_accounting(grads_kv)

    def _commit_tree_accounting(self, grads_kv) -> None:
        """Engine hook: extra counters per committed tree (default none)."""

    def _check_staged_async(self) -> None:
        """Checkpoint guard: staged-but-uncommitted grads would be lost."""
        pending = {w: sorted(kv) for w, kv in self._staged_async.items() if kv}
        if pending:
            raise RuntimeError(
                f"cannot checkpoint mid-push: workers {sorted(pending)} have "
                f"staged but uncommitted per-key async pushes"
            )
