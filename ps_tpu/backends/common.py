"""Shared server-engine pieces (single source of truth for both backends).

The local backend's semantics are the spec the mesh backend must match
(asserted by tests/test_async_tpu.py); keeping the DC apply and the
introspection read in one place guarantees a fix to one cannot silently
break that parity.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import optax

from ps_tpu import obs
from ps_tpu.control import tensor_van as tv
from ps_tpu.optim.dc import delay_compensate
from ps_tpu.utils.metrics import TransportStats

class ServerFailureError(RuntimeError):
    """A remote PS server died mid-job (its connection failed).

    ``server`` (when known) is the failed server's index into the worker's
    address list — what the failover loop re-routes."""

    def __init__(self, message: str, server: Optional[int] = None):
        super().__init__(message)
        self.server = server


class TableMovedError(RuntimeError):
    """The shard TABLE moved under this worker (a live rebalance migrated
    keys between shards — ps_tpu/elastic). Typed apart from
    :class:`ServerFailureError` because the remedy differs: the server is
    alive and healthy, only the key→shard assignment changed, so the
    worker must re-fetch the table from its coordinator and re-route —
    cycling the shard's replica set (the primary-died remedy) would just
    find the same refusal at every member.

    ``table_epoch`` is the refusing server's table epoch: the worker
    waits for a FETCHED table past its own before retrying, so a refusal
    raced against the coordinator's publish converges instead of
    spinning."""

    def __init__(self, message: str, server: Optional[int] = None,
                 table_epoch: int = 0):
        super().__init__(message)
        self.server = server
        self.table_epoch = int(table_epoch)


class BackupNotServing(Exception):
    """A replica answered HELLO but is an unpromoted backup — retryable
    (the failover loop waits out the promotion)."""


class ReplicaRejected(Exception):
    """A replica answered HELLO but failed validation (stale epoch /
    mismatched topology) — skip it, keep cycling the set."""


def parse_replica_uri(uri: str):
    """``"h0:p0|b0:q0,h1:p1|b1:q1"`` → ``(primaries, replica_sets)``.

    Commas separate shards (as everywhere); ``|`` separates the members of
    one shard's replica set, preferred (primary) first. A plain
    ``host:port`` list parses to singleton sets — no failover."""
    primaries, sets = [], []
    for part in uri.split(","):
        cands = []
        for member in part.strip().split("|"):
            host, port = member.strip().rsplit(":", 1)
            cands.append((host, int(port)))
        primaries.append(cands[0])
        sets.append(cands)
    return primaries, sets


class _OpScope:
    """The per-op observability scope :meth:`BucketedTransportMixin._op`
    returns — a plain slotted object, not a generator contextmanager, so
    the unsampled hot path allocates one small object and nothing else."""

    __slots__ = ("_transport", "_name", "_sp", "_t0")

    def __init__(self, transport, name: str, sp):
        self._transport = transport
        self._name = name
        self._sp = sp

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._sp.__enter__()
        return self._sp

    def __exit__(self, *exc):
        try:
            self._sp.__exit__(*exc)
        finally:
            self._transport.record_op(
                self._name, time.perf_counter() - self._t0)
        return False


#: Default fusion-bucket size for the pipelined transport. ~4 MiB is the
#: ps-lite/BytePS sweet spot: large enough that per-message overhead (json
#: meta, syscalls) is noise, small enough that many buckets are in flight
#: per tree and the pipeline has something to overlap.
DEFAULT_BUCKET_BYTES = 4 << 20

#: Worker-id floor for aggregator identities (ps_tpu/backends/aggregator):
#: an aggregator pushes its group's MERGED gradient to the shards under a
#: synthetic worker id — group index offset past this base — so its
#: per-key dedup tokens and DC staleness bookkeeping never collide with a
#: real worker's slot (real ids live in [0, num_workers); the engines'
#: range check admits ids at or past this base explicitly).
AGG_WORKER_BASE = 1 << 20

#: Default drain_to deadline (checkpoint coordinators produce it on the
#: wire; servers fall back to it for hand-rolled frames). One constant so
#: the dense/sparse coordinators and both server sides cannot drift.
DRAIN_TO_TIMEOUT_S = 30.0

# one bucket slice: (key, dtype_str, shape, lo, hi) — byte range [lo, hi)
# within the key's contiguous row-major buffer
Slice = Tuple[str, str, list, int, int]


def payload_nbytes(payload) -> int:
    """Wire payload size of a frame in either form: a contiguous
    bytes/bytearray, or the zero-copy ``(header, chunks)`` parts tuple."""
    if isinstance(payload, tuple):
        header, chunks = payload
        return len(header) + sum(len(c) for c in chunks)
    return len(payload)


def send_payload(ch, payload) -> None:
    """Send either payload form on ``ch`` (vectored for parts)."""
    if isinstance(payload, tuple):
        ch.send_parts(*payload)
    else:
        ch.send(payload)


def request_payload(ch, payload):
    """``ch.request`` for either payload form; returns the reply frame."""
    if isinstance(payload, tuple):
        return ch.request_parts(*payload)
    return ch.request(payload)


class BucketPlan:
    """Slice a flat ``{key: tensor}`` payload into fixed-size fusion buckets.

    Keys are packed greedily in transport order (sorted — for slash-joined
    layer paths that is front-of-model first, which is the order the next
    step's forward needs them). A tensor larger than ``bucket_bytes`` is
    split across consecutive buckets; small tensors fuse into one bucket.
    Every bucket except the last holds exactly ``bucket_bytes`` payload
    bytes, so striping buckets round-robin over a connection pool balances
    it by construction.

    The encoded frame (:meth:`encode_bucket`) is self-describing: its
    ``extra["slices"]`` table carries (key, dtype, shape, lo, hi) per
    slice, so the receiving side reassembles with :class:`BucketAssembler`
    without any prior shape knowledge — worker and server never need to
    agree on a plan out of band.
    """

    def __init__(self, specs: Sequence[Tuple[str, str, list, int]],
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES):
        """``specs``: ``(key, dtype_str, shape, nbytes)`` in transport order."""
        self.bucket_bytes = max(int(bucket_bytes), 1)
        buckets: List[List[Slice]] = []
        cur: List[Slice] = []
        fill = 0
        for key, dt, shape, nbytes in specs:
            shape = list(shape)
            if nbytes == 0:
                # zero-size tensors still travel (the key must appear)
                cur.append((key, dt, shape, 0, 0))
                continue
            off = 0
            while off < nbytes:
                if fill >= self.bucket_bytes:
                    buckets.append(cur)
                    cur, fill = [], 0
                take = min(nbytes - off, self.bucket_bytes - fill)
                cur.append((key, dt, shape, off, off + take))
                off += take
                fill += take
        buckets.append(cur)  # last (possibly empty for an empty payload)
        self.buckets = buckets
        self.total_bytes = sum(n for _, _, _, n in specs)

    @classmethod
    def from_arrays(cls, arrays: Dict[str, np.ndarray],
                    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                    order: Optional[Sequence[str]] = None) -> "BucketPlan":
        keys = list(order) if order is not None else sorted(arrays)
        specs = []
        for k in keys:
            a = np.asarray(arrays[k])
            specs.append((k, a.dtype.str, list(a.shape), a.nbytes))
        return cls(specs, bucket_bytes)

    @property
    def nbuckets(self) -> int:
        return len(self.buckets)

    def _bucket_chunks_meta(self, arrays: Dict[str, np.ndarray], b: int,
                            extra: Optional[dict]):
        chunks = []
        slices = self.buckets[b]
        for key, _, _, lo, hi in slices:
            a = np.ascontiguousarray(np.asarray(arrays[key]))
            chunks.append(memoryview(a.reshape(-1)).cast("B")[lo:hi])
        meta = {**(extra or {}),
                "bucket": b, "nbuckets": self.nbuckets,
                "slices": [[k, dt, shape, lo, hi]
                           for k, dt, shape, lo, hi in slices]}
        return chunks, meta

    def encode_bucket(self, kind: int, worker: int,
                      arrays: Dict[str, np.ndarray], b: int,
                      extra: Optional[dict] = None) -> bytearray:
        """Frame bucket ``b``: each slice's bytes are a ``memoryview`` of
        the live tensor, copied exactly once into the frame
        (:func:`~ps_tpu.control.tensor_van.encode_chunks`)."""
        chunks, meta = self._bucket_chunks_meta(arrays, b, extra)
        return tv.encode_chunks(kind, worker, chunks, meta)

    def encode_bucket_parts(self, kind: int, worker: int,
                            arrays: Dict[str, np.ndarray], b: int,
                            extra: Optional[dict] = None):
        """Zero-copy form of :meth:`encode_bucket`: ``(header, chunks)``
        with the slice views passed through UNstaged — the channel's
        vectored send (or the shm ring write) is the only copy the bucket's
        bytes ever see. The views pin their tensors until sent."""
        chunks, meta = self._bucket_chunks_meta(arrays, b, extra)
        return tv.encode_chunks_parts(kind, worker, chunks, meta)

    def bucket_encoder(self, writev: bool):
        """The ONE lane-selection point for bucket frames: zero-copy parts
        when ``writev`` is on, the staged legacy frame otherwise. Every
        sender resolves through here so the rule cannot drift per site."""
        return self.encode_bucket_parts if writev else self.encode_bucket


class BucketAssembler:
    """Reassemble a multi-bucket payload; a torn epoch is never observable.

    Buckets may arrive in any order (they are striped over a connection
    pool). Every slice carries the push epoch it belongs to; a slice from a
    different epoch is refused (the per-key epoch tag — a straggler bucket
    of an aborted push can never contaminate a later tree), a duplicate
    bucket is refused, and :meth:`finish` refuses any key whose byte
    coverage is incomplete. Only when all ``nbuckets`` buckets of ONE epoch
    have landed does :meth:`add` report completion — the caller applies the
    assembled tree atomically, so readers observe whole pushes or nothing.
    """

    def __init__(self, epoch: int, nbuckets: int):
        self.epoch = int(epoch)
        self.nbuckets = int(nbuckets)
        self._seen: set = set()
        self._flat: Dict[str, np.ndarray] = {}    # key -> uint8 buffer
        self._meta: Dict[str, Tuple[str, list, int]] = {}
        self._filled: Dict[str, int] = {}
        self._key_epoch: Dict[str, int] = {}

    def add(self, bucket: int, raw, slices, epoch: Optional[int] = None
            ) -> bool:
        """Stage one bucket; returns True when the epoch is complete."""
        if epoch is not None and int(epoch) != self.epoch:
            raise RuntimeError(
                f"bucket of epoch {epoch} offered to assembler of epoch "
                f"{self.epoch} — torn multi-bucket push refused"
            )
        b = int(bucket)
        if not (0 <= b < self.nbuckets):
            raise RuntimeError(f"bucket {b} out of range 0..{self.nbuckets-1}")
        if b in self._seen:
            raise RuntimeError(f"duplicate bucket {b} for epoch {self.epoch}")
        raw = np.frombuffer(raw, np.uint8) if not isinstance(raw, np.ndarray) \
            else raw.reshape(-1).view(np.uint8)
        off = 0
        for key, dt, shape, lo, hi in slices:
            if key not in self._flat:
                nbytes = (int(np.prod(shape, dtype=np.int64))
                          * np.dtype(dt).itemsize)
                self._flat[key] = np.empty(nbytes, np.uint8)
                self._meta[key] = (dt, list(shape), nbytes)
                self._filled[key] = 0
                self._key_epoch[key] = self.epoch
            n = hi - lo
            self._flat[key][lo:hi] = raw[off:off + n]
            self._filled[key] += n
            off += n
        self._seen.add(b)
        return len(self._seen) == self.nbuckets

    def finish(self) -> Dict[str, np.ndarray]:
        """The assembled ``{key: tensor}`` tree (buffers owned by the
        assembler's own allocations — safe to hold past frame lifetimes)."""
        if len(self._seen) != self.nbuckets:
            raise RuntimeError(
                f"epoch {self.epoch} incomplete: {len(self._seen)}/"
                f"{self.nbuckets} buckets"
            )
        out = {}
        for key, (dt, shape, nbytes) in self._meta.items():
            if self._filled[key] != nbytes:
                raise RuntimeError(
                    f"key {key!r} torn: {self._filled[key]}/{nbytes} bytes "
                    f"in epoch {self.epoch}"
                )
            out[key] = self._flat[key].view(np.dtype(dt)).reshape(shape)
        return out


class ChannelPump:
    """One persistent transport connection + its dedicated sender thread.

    The background half of the pipelined transport: callers ``submit``
    encoded frames and immediately get a Future for the reply; the pump
    thread drains the pending queue over its own
    :class:`~ps_tpu.control.tensor_van.Channel` (one driving thread per
    channel, as the van requires). Striping a plan's buckets round-robin
    over a pool of pumps gives per-server send/recv parallelism — the
    native sends release the GIL, so pumps genuinely overlap.

    The pending queue is a PRIORITY queue (ByteScheduler-style): each
    submit carries a small integer priority — lower drains first — and
    ties break on the enqueue sequence number, so equal-priority traffic
    stays exactly FIFO and the drain order is fully deterministic. Bucket
    senders pass the bucket index (front-of-model first, i.e. reverse of
    backprop completion order), so when a backlog forms, the tail
    layers' buckets stop serializing in front of the bytes the next
    step's forward needs first. All-default submits reproduce the
    legacy FIFO pump bit for bit.
    """

    def __init__(self, ch, on_io: Optional[Callable] = None):
        import concurrent.futures  # noqa: F401  (Future class used below)

        self._ch = ch
        self._on_io = on_io  # (bytes_out, bytes_in, seconds) per request
        self._cv = threading.Condition()
        self._heap: list = []   # (priority, seq, payload, fut)
        self._seq = 0
        self._closed = False
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def submit(self, payload, priority: int = 0):
        import concurrent.futures

        fut = concurrent.futures.Future()
        with self._cv:
            if self._closed:
                # fail fast instead of queueing behind a dead thread — a
                # caller racing close() (e.g. a background cycle during
                # reconnect) gets a connection-shaped error, never a
                # forever-pending future
                fut.set_exception(tv.VanError("pump closed"))
                return fut
            self._seq += 1
            # the seq tie-break also guarantees (payload, fut) are never
            # compared by heapq
            heapq.heappush(self._heap,
                           (int(priority), self._seq, payload, fut))
            self._cv.notify()
        return fut

    def _loop(self) -> None:
        import time

        while True:
            with self._cv:
                while not self._heap and not self._closed:
                    self._cv.wait()
                if not self._heap:
                    return  # closed AND drained — same contract as the
                    # old stop sentinel: everything queued before close()
                    # still goes out
                _, _, payload, fut = heapq.heappop(self._heap)
            if not fut.set_running_or_notify_cancel():
                continue
            t0 = time.perf_counter()
            try:
                # parts tuples ride the vectored/shm zero-copy send;
                # contiguous frames keep the legacy path
                reply = request_payload(self._ch, payload)
            except BaseException as e:  # surfaced at the caller's wait
                fut.set_exception(e)
                continue
            dt = time.perf_counter() - t0
            if self._on_io is not None:
                try:
                    self._on_io(payload_nbytes(payload), len(reply), dt)
                except Exception:
                    pass  # accounting must never fail the transport
            fut.set_result(reply)

    def close(self) -> None:
        """Stop the thread (after the pending queue drains) and close the
        channel. Requests that slipped in behind the close are failed,
        never left as forever-pending futures."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._t.join(timeout=10)
        with self._cv:
            leftovers, self._heap = self._heap, []
        for _, _, _, fut in leftovers:
            fut.set_exception(tv.VanError("pump closed"))
        self._ch.close()


class BucketedTransportMixin:
    """Worker-side plumbing of the bucketed/pipelined transport, shared by
    the dense and sparse remote workers: pump-pool lifecycle, byte/timing
    accounting, background-handle bookkeeping, and the flush barrier.

    Contract: the concrete worker sets ``_addrs``, ``_bytes_lock``,
    ``bytes_pushed``/``bytes_pulled`` and calls :meth:`_init_transport`
    during its init, then :meth:`_open_pumps` once its channels are
    validated; it may override ``_failure_noun`` for error messages.
    """

    _failure_noun = "PS server"

    def _init_transport(self, bucket_bytes: Optional[int],
                        pool_size: Optional[int],
                        compress=None, writev: Optional[bool] = None,
                        shm: Optional[bool] = None,
                        shm_bytes: Optional[int] = None,
                        bucket_priority: Optional[bool] = None) -> None:
        import os
        import uuid

        from ps_tpu.config import env_flag, env_int
        from ps_tpu.control.shm_lane import DEFAULT_SHM_BYTES

        # <= 0 selects the serial transport, matching the PS_BUCKET_BYTES=0
        # convention everywhere (a literal 0 must never mean 1-byte buckets)
        self.bucket_bytes = (None if bucket_bytes is None
                             or int(bucket_bytes) <= 0 else int(bucket_bytes))
        # transport lanes (None = the PS_WRITEV / PS_SHM env defaults):
        # writev sends frames as kernel scatter-gather iovecs of the live
        # tensors (no staging bytearray); shm negotiates the same-host
        # shared-memory ring lane per connection, falling back to TCP
        # whenever negotiation fails
        self.writev = (env_flag("PS_WRITEV", True)
                       if writev is None else bool(writev))
        self.shm = env_flag("PS_SHM", False) if shm is None else bool(shm)
        # priority bucket scheduling (ByteScheduler-style): bucket flushes
        # carry their bucket index as the pump priority — front-of-model
        # buckets drain a backlog first, so the tail layers' grads stop
        # blocking the bytes the next step's forward needs. Off = every
        # submit at priority 0 = the legacy FIFO drain, bit for bit.
        self.bucket_priority = (env_flag("PS_BUCKET_PRIORITY", True)
                                if bucket_priority is None
                                else bool(bucket_priority))
        # validated service-level read (pslint PSL406): Config's >=64KiB
        # ring floor applies here too — an env value below it would
        # break the ring's wrap-sentinel framing math, not just be slow
        self.shm_bytes = (env_int("PS_SHM_BYTES", DEFAULT_SHM_BYTES,
                                  lo=1 << 16)
                          if shm_bytes is None else int(shm_bytes))
        # incarnation nonce, sent with every push bucket: a restarted (or
        # reconnected) worker reuses epoch NUMBERS from zero, so the server
        # must never complete a staged epoch of a dead incarnation with
        # buckets from a new one — the nonce makes the two distinguishable.
        # The (nonce, push-seq) pair is also the dedup token: servers skip
        # a push whose seq they already applied for this incarnation, so a
        # push replayed at a promoted replica lands exactly once.
        self._transport_nonce = uuid.uuid4().hex[:12]
        # per-worker push sequence (one per push/push_pull operation, the
        # same number on every shard's message of that operation): the seq
        # half of the dedup token, and — with the fanout set the sparse
        # worker attaches — what the sparse checkpoint drain compares
        # across shards
        self._push_seq = 0
        self.pool_size = max(int(pool_size), 1) if pool_size is not None \
            else (2 if self.bucket_bytes is not None else 1)
        self.transport = TransportStats()
        # reusable receive buffers for the hot pull path (frames whose
        # lifetime this layer controls: pump replies are consumed —
        # decoded + copied out — before the next borrow can alias them)
        self._recv_pool = tv.RecvBufferPool(stats=self.transport)
        self._push_epoch = 0
        self._pull_epoch = 0
        self._pumps: Dict[int, List[ChannelPump]] = {}
        self._bg_pool = None                    # background cycle orchestrator
        self._pending_cycles: List = []         # unobserved background handles
        # gradient compression (ps_tpu/compress): normalized spec dict or
        # None; the compressor holds the per-key policy AND the topk
        # error-feedback residuals, so it must survive reconnects (it is
        # part of _saved_transport_state)
        from ps_tpu.compress import CompressPolicy, GradCompressor, resolve_spec

        self.compress = resolve_spec(compress)
        if self.compress is not None and "seed" not in self.compress:
            # decorrelate int8 stochastic rounding across workers: with a
            # shared default seed every worker would draw the SAME uniform
            # sequence each step, so quantization errors add coherently and
            # the server-side average keeps full single-worker noise
            # variance instead of variance/N
            self.compress = dict(self.compress,
                                 seed=int(getattr(self, "worker", 0)))
        policy = CompressPolicy.from_spec(self.compress)
        self._compressor = (GradCompressor(policy, stats=self.transport)
                            if policy is not None else None)

    def _op(self, name: str, **args) -> "_OpScope":
        """One logical transport op's observability envelope: a root
        trace span (sampled per ``trace_sample`` — the NOOP singleton
        otherwise) AND an always-on latency histogram sample. Use::

            with self._op("push") as sp:
                ...  # sp.wire() propagates the context, None unsampled

        The span/histogram cover the op end to end, failover retries
        included — the latency a training loop actually feels.

        A nested hop — an op issued while a traced request is being
        SERVED on this thread (the aggregator's merged upstream flush,
        its coalesced pull) — parents to the open span instead of
        rooting a new trace: the worker→aggregator→shard chain stays ONE
        trace, and the aggregator's client ops never mint phantom
        \"steps\". Training threads have no open span, so ordinary
        worker ops root exactly as before."""
        parent = obs.tracer().current()
        sp = obs.tracer().span(name, cat="worker", parent=parent)
        if sp:
            sp.set(worker=getattr(self, "worker", 0), **args)
        return _OpScope(self.transport, name, sp)

    @staticmethod
    def _tc_extra(extra: Optional[dict], sp) -> Optional[dict]:
        """Merge a span's wire context into a frame's ``extra`` (returns
        ``extra`` unchanged — possibly None — when the op is unsampled,
        so untraced frames are byte-identical to the pre-obs wire)."""
        wire = sp.wire() if sp else None
        if wire is None:
            return extra
        out = dict(extra or {})
        out[obs.WIRE_KEY] = wire
        return out

    def _bucket_submit_priority(self, b: int) -> int:
        """The pump priority for bucket ``b`` of a plan: the bucket index
        itself (front-of-model first — plans pack keys in sorted order)
        when priority scheduling is on, else a constant 0 (pure FIFO, the
        parity baseline the scheduling tests diff against)."""
        return int(b) if self.bucket_priority else 0

    def _encode_push_tree(self, arrays: Dict[str, np.ndarray]
                          ) -> Tuple[Dict[str, np.ndarray], List[str]]:
        """Apply the compression policy to one server's push payload;
        returns the wire tree and the packed-key list for the header."""
        if self._compressor is None:
            return arrays, []
        return self._compressor.encode_tree(arrays)

    def _pull_compress_spec(self) -> Optional[dict]:
        """The codec spec pulls ask the server to apply to the return path
        (None unless the spec opts in with ``pull: true``). Error-feedback
        state lives at the SENDER, so pull compression is stateless by
        construction — topk would silently drop mass forever and is
        refused at connect time."""
        if not self.compress or not self.compress.get("pull"):
            return None
        return {k: v for k, v in self.compress.items() if k != "pull"}

    def _maybe_upgrade(self, ch):
        """Offer the peer the shared-memory lane for ``ch`` when the
        worker's ``shm`` knob is on; any negotiation failure keeps the
        plain TCP channel (identical semantics, slower bytes)."""
        if not self.shm:
            return ch
        from ps_tpu.control import shm_lane

        up = shm_lane.try_upgrade(ch, getattr(self, "worker", 0),
                                  self.shm_bytes, stats=self.transport)
        up.pool = getattr(ch, "pool", None)
        return up

    def _dial_transport_channel(self, host, port):
        """One data-plane connection: dialed, accounted (per-lane stats +
        receive pool), and shm-upgraded when negotiation succeeds."""
        ch = tv.Channel.connect(host, port)
        ch.stats = self.transport
        ch.pool = self._recv_pool
        try:
            return self._maybe_upgrade(ch)
        except tv.VanError:
            ch.close()
            raise

    def _open_pumps(self, indices) -> None:
        """Dial ``pool_size`` extra transport connections per server; the
        main channels stay free for control traffic (stats, checkpoints)."""
        for i in indices:
            host, port = self._addrs[i]
            # registered before filled so a failed dial mid-pool leaves
            # the already-opened pumps reachable by _close_transport
            self._pumps[i] = pumps = []
            for _ in range(self.pool_size):
                pumps.append(ChannelPump(
                    self._dial_transport_channel(host, port),
                    on_io=self._on_pump_io))

    def _release_frame(self, frame) -> None:
        """Return a fully-consumed reply frame's buffer to the receive
        pool (no-op for frames the pool did not issue)."""
        self._recv_pool.ret(frame)

    def _on_pump_io(self, sent: int, received: int, seconds: float) -> None:
        with self._bytes_lock:
            self.bytes_pushed += sent
            self.bytes_pulled += received
        self.transport.record_bucket(sent + received, seconds)

    def _close_transport(self) -> None:
        """Tear down pumps + orchestrator; safe on a partial construction."""
        if getattr(self, "_bg_pool", None) is not None:
            self._bg_pool.shutdown(wait=False)
            self._bg_pool = None
        for pumps in getattr(self, "_pumps", {}).values():
            for p in pumps:
                p.close()
        self._pumps = {}

    def _bg_executor(self):
        """The (lazily created) single background thread that runs whole
        transport cycles — ONE thread, so cycles serialize per worker and
        the per-worker push/pull order the staleness bound rests on is
        exactly the serial order."""
        if self._bg_pool is None:
            import concurrent.futures

            self._bg_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ps-transport"
            )
        return self._bg_pool

    def _bucket_reply(self, i: int, fut):
        """Resolve one pump future, mapping channel death to the same typed
        failure the serial path raises."""
        try:
            return fut.result()
        except tv.VanError as e:
            host, port = self._addrs[i]
            raise ServerFailureError(
                f"{self._failure_noun} {i} ({host}:{port}) failed "
                f"mid-job: {e}", server=i
            ) from e

    # -- replica sets & live failover (ps_tpu/replica, worker half) -----------

    def _init_failover(self, replica_sets, failover_timeout) -> None:
        """Record each shard's replica set (preferred/primary first) and
        the budget for riding out a promotion. Call after ``_addrs`` is
        set, before dialing."""
        import os

        n = len(self._addrs)
        if replica_sets is None:
            replica_sets = [[tuple(a)] for a in self._addrs]
        if len(replica_sets) != n:
            raise ValueError(
                f"replica_sets names {len(replica_sets)} shards but the "
                f"worker dialed {n}"
            )
        self._replica_sets = [[tuple(a) for a in s] for s in replica_sets]
        for i, s in enumerate(self._replica_sets):
            if tuple(self._addrs[i]) not in s:
                raise ValueError(
                    f"server {i}'s address {self._addrs[i]} is not in its "
                    f"replica set {s}"
                )
        if failover_timeout is None:
            from ps_tpu.config import env_float

            # validated service-level read (pslint PSL406); a negative
            # horizon would make every failover fail instantly
            failover_timeout = env_float("PS_FAILOVER_TIMEOUT_MS",
                                         10_000.0, lo=0.0) / 1e3
        self.failover_timeout = float(failover_timeout)
        self._epochs = [0] * n  # shard-table epochs, learned from HELLO

    def _next_push_seq(self) -> int:
        self._push_seq += 1
        return self._push_seq

    def _reply_error(self, i: int, extra: dict) -> BaseException:
        """The typed error for an ERR reply mid-stream: a 'not serving'
        refusal (an unpromoted backup, a zombie fenced mid-commit) maps to
        the same retryable failure a dead connection raises — the failover
        loop re-routes and replays; a 'moved' refusal (the shard table
        changed under a live rebalance) maps to the table-refresh path;
        anything else is a real application error and surfaces as-is."""
        host, port = self._addrs[i]
        if extra.get("moved"):
            return TableMovedError(
                f"{self._failure_noun} {i} ({host}:{port}) refused: "
                f"{extra.get('error')}", server=i,
                table_epoch=int(extra.get("table_epoch") or 0))
        if extra.get("backup"):
            return ServerFailureError(
                f"{self._failure_noun} {i} ({host}:{port}) is not "
                f"serving: {extra.get('error')}", server=i)
        return RuntimeError(f"server {i} error: {extra.get('error')}")

    def _hello(self, ch) -> dict:
        """One HELLO round trip; typed outcomes for the failover loop."""
        kind, _, _, extra = tv.decode(
            ch.request(tv.encode(tv.HELLO, self.worker, None))
        )
        if kind != tv.OK:
            if extra.get("backup"):
                raise BackupNotServing(extra.get("error"))
            raise ReplicaRejected(f"HELLO refused: {extra.get('error')}")
        return extra

    def _validate_failover_hello(self, i: int, extra: dict) -> Optional[str]:
        """Subclass hook: check a promoted replica's HELLO against what
        the worker validated at connect time (error string, or None)."""
        return None

    def _cycle_replica_set(self, i: int, deadline: float,
                           skip_current: bool = False, validate=None,
                           cause: Optional[BaseException] = None):
        """THE replica-set dial loop (shared by connect-time ``_hello_any``
        and mid-job ``_failover`` so retry/backoff/typed-outcome handling
        cannot drift between them): cycle server ``i``'s candidates until
        one answers HELLO as a serving primary and passes ``validate``
        (unpromoted backups and rejected members keep the loop going), or
        the deadline passes. Returns ``(channel, hello_extra, addr)``; the
        channel is stats-accounted but NOT pooled or shm-upgraded (main
        channels never attach the recv pool — their replies are consumed,
        not returned)."""
        import time

        cands = self._replica_sets[i]
        k = cands.index(tuple(self._addrs[i])) \
            if tuple(self._addrs[i]) in cands else 0
        if skip_current:
            k += 1
        last: Optional[BaseException] = cause
        while True:
            host, port = cands[k % len(cands)]
            k += 1
            try:
                ch = tv.Channel.connect(host, port, timeout_ms=2000,
                                        retries=2, max_wait_s=0.5)
                ch.stats = self.transport
                try:
                    extra = self._hello(ch)
                    if validate is not None:
                        err = validate(extra)
                        if err is not None:
                            raise ReplicaRejected(err)
                except BaseException:
                    ch.close()
                    raise
                return ch, extra, (host, port)
            except (BackupNotServing, ReplicaRejected, tv.VanError,
                    OSError) as e:
                last = e
            if time.monotonic() >= deadline:
                err = ServerFailureError(
                    f"no member of {self._failure_noun} {i}'s replica set "
                    f"{cands} is serving before the failover deadline: "
                    f"{last}", server=i)
                if cause is not None:
                    raise err from cause
                raise err
            time.sleep(0.05)

    def _hello_any(self, i: int):
        """Connect-time dial of server ``i``: its preferred address, or —
        when a replica set is configured — the first member that answers
        HELLO as a serving primary (an unpromoted backup keeps the loop
        cycling within the failover window, so a worker can join a shard
        mid-promotion). Returns ``(channel, hello_extra)``."""
        import time

        cands = getattr(self, "_replica_sets",
                        [[tuple(a)] for a in self._addrs])[i]
        if len(cands) == 1:
            host, port = cands[0]
            ch = tv.Channel.connect(host, port)
            ch.stats = self.transport
            try:
                return ch, self._hello(ch)
            except (BackupNotServing, ReplicaRejected) as e:
                ch.close()
                raise ServerFailureError(
                    f"{self._failure_noun} {i} ({host}:{port}) refused "
                    f"HELLO: {e}", server=i) from e
        deadline = time.monotonic() + self.failover_timeout
        ch, extra, addr = self._cycle_replica_set(i, deadline)
        self._addrs[i] = addr
        return ch, extra

    def _failover(self, i: int, cause: BaseException,
                  deadline: float) -> None:
        """Re-route shard ``i`` to a serving replica: tear down the dead
        transport, cycle the replica set (waiting out an in-flight
        promotion), refuse stale epochs (a zombie old primary must not win
        the race), revalidate the topology, and rebuild pumps. Raises the
        typed failure when nothing serves before ``deadline``."""
        import logging
        import time

        t0 = time.monotonic()
        logging.getLogger(__name__).warning(
            "%s %d (%s:%d) failed; trying its replica set (%d member(s))",
            self._failure_noun, i, *self._addrs[i],
            len(self._replica_sets[i]),
        )
        for p in self._pumps.pop(i, []):
            p.close()
        try:
            self._chs[i].close()
        except Exception:
            pass

        def validate(extra):
            epoch = int(extra.get("epoch") or 0)
            if epoch < self._epochs[i]:
                return (f"stale shard epoch {epoch} < {self._epochs[i]} "
                        f"(zombie old primary?)")
            return self._validate_failover_hello(i, extra)

        # start at the NEXT member: the preferred address just failed
        ch, extra, addr = self._cycle_replica_set(
            i, deadline, skip_current=True, validate=validate, cause=cause)
        try:
            ch = self._maybe_upgrade(ch)
        except tv.VanError as e:
            # the candidate died DURING shm negotiation (a mere refusal
            # falls back to TCP inside try_upgrade): treat it like any
            # dead candidate — the caller's retry loop fails over again
            # within the same deadline
            ch.close()
            raise ServerFailureError(
                f"{self._failure_noun} {i} died during lane negotiation: "
                f"{e}", server=i) from e
        self._chs[i] = ch
        self._addrs[i] = addr
        self._epochs[i] = int(extra.get("epoch") or 0)
        if self.bucket_bytes is not None:
            self._open_pumps([i])
        dt = time.monotonic() - t0
        self.transport.record_failover(dt)
        obs.record_event("failover", shard=i, addr=f"{addr[0]}:{addr[1]}",
                         epoch=self._epochs[i], seconds=round(dt, 4),
                         cause=repr(cause))
        logging.getLogger(__name__).warning(
            "%s %d re-routed to %s:%d (epoch %d) in %.2fs",
            self._failure_noun, i, *addr, self._epochs[i], dt,
        )

    def _on_table_moved(self, err: TableMovedError,
                        deadline: float) -> None:
        """Hook: refresh the shard table and re-route (elastic workers
        override). The default — a worker with no coordinator — cannot
        recover: the topology it was launched with is simply wrong now."""
        raise TableMovedError(
            f"{err} — this worker has no coordinator configured "
            f"(connect with coordinator=... / PS_COORD_URI for elastic "
            f"membership), so it cannot re-fetch the shard table",
            server=err.server, table_epoch=err.table_epoch) from err

    def _on_server_lost(self, err: ServerFailureError,
                        deadline: float) -> None:
        """Hook: a shard failed with NO replica left to cycle to — the
        last chance before the op surfaces the failure. Elastic workers
        override it to re-discover the fleet from their coordinator (a
        replacement member may have taken the dead shard's slot over);
        the default surfaces the failure unchanged."""
        raise err

    def _with_failover(self, fn):
        """Run one transport operation; on a typed server failure, fail
        the shard over to a replica — or, on a stale-table refusal,
        re-fetch the shard table from the coordinator and re-route — and
        retry the WHOLE operation. Safe because operations are
        idempotent: pulls are reads, and every push carries its (nonce,
        seq) dedup token — shards that already applied it (directly, via
        a dead primary's replication stream, or via a migrated key
        range's transferred tokens) ack without re-applying, so the retry
        is exactly-once everywhere. The total window (re-routes included,
        across every shard the retry trips over) is bounded by
        ``failover_timeout``."""
        import time

        try:
            return fn()
        except (ServerFailureError, TableMovedError) as e:
            err = e
        deadline = time.monotonic() + self.failover_timeout
        while True:
            if isinstance(err, TableMovedError):
                # "table moved" ≠ "primary died": the shard is healthy,
                # the ASSIGNMENT changed — re-fetch and re-split instead
                # of cycling its replica set
                self._on_table_moved(err, deadline)
            else:
                i = getattr(err, "server", None)
                if i is None or len(self._replica_sets[i]) <= 1:
                    # no replica to cycle to: the hook's last chance
                    # (elastic workers re-discover the fleet; the
                    # default raises err)
                    self._on_server_lost(err, deadline)
                else:
                    try:
                        self._failover(i, err, deadline)
                    except ServerFailureError as e:
                        # a candidate died mid-adoption (e.g. during lane
                        # negotiation): keep cycling within the SAME
                        # deadline; a deadline-expired failure propagates
                        if time.monotonic() >= deadline:
                            raise
                        err = e
                        continue
            try:
                return fn()
            except (ServerFailureError, TableMovedError) as e:
                if time.monotonic() >= deadline:
                    raise
                err = e

    def _track_pending(self, pending) -> None:
        """Register a background handle for flush(). Handles that resolved
        cleanly — or whose failure was already delivered through a wait() —
        are pruned here, so a long overlap run does not pin one params tree
        per step and a failure surfaces exactly once; failed-but-unobserved
        handles are kept for flush() to surface."""
        self._pending_cycles = [
            c for c in self._pending_cycles
            if not c.done() or (c._exc is not None
                                and not getattr(c, "_observed", False))
        ]
        self._pending_cycles.append(pending)

    def flush(self) -> None:
        """Barrier: wait until every background cycle has fully landed
        (pushes applied server-side AND any pulls merged), re-raising the
        first failure. After flush() the worker is in exactly the state a
        serial caller would be in — this is what preserves sync-SGD
        semantics for trainers that overlap."""
        cycles, self._pending_cycles = self._pending_cycles, []
        err = None
        for c in cycles:
            if getattr(c, "_observed", False):
                continue  # this failure was already delivered via wait()
            try:
                c.wait()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = err or e
        if err is not None:
            raise err

    def _saved_transport_state(self) -> tuple:
        """Snapshot the identity that must survive a reconnect: cumulative
        wire counters, transport stats, the push/pull epoch streams, and
        the compressor (its topk error-feedback residuals are unsent
        gradient mass — dropping them on a re-dial would lose updates)."""
        return (self.bytes_pushed, self.bytes_pulled, self.collective_bytes,
                self.transport, self._push_epoch, self._pull_epoch,
                self._compressor)

    def _restore_transport_state(self, saved: tuple) -> None:
        (self.bytes_pushed, self.bytes_pulled, self.collective_bytes,
         self.transport, self._push_epoch, self._pull_epoch,
         self._compressor) = saved
        if self._compressor is not None:
            self._compressor.stats = self.transport
        # the re-dial built fresh accounting sinks against the NEW stats
        # object; re-point them at the restored one so lane/pool counters
        # stay continuous across a reconnect
        self._recv_pool.stats = self.transport

        def repoint(ch):
            while ch is not None:
                if getattr(ch, "stats", None) is not None:
                    ch.stats = self.transport
                ch = getattr(ch, "_ch", None)  # shm lane wraps the TCP ch

        for pumps in self._pumps.values():
            for p in pumps:
                repoint(p._ch)
        for ch in getattr(self, "_chs", []):
            repoint(ch)


def make_jit_dc_apply_tree(opt: optax.GradientTransformation):
    """Fused whole-tree async apply: ONE XLA dispatch per push_all.

    The per-key loop unrolls at trace time into a single program (the
    bucketing pass SURVEY.md §3 row 11 reserves for the async host path —
    XLA fuses the per-key DC corrections and updates instead of the host
    dispatching one apply per key). Numerically identical to the per-key
    sequence: keys are independent under per-tensor optimizers, asserted by
    tests/test_async_stress.py.

    ``fn(params, states, grads, stales, lam) -> (params, states)`` over
    ``{key: ...}`` dicts with per-key optimizer states.
    """

    def _apply_dc_tree(params, states, grads, stales, lam):
        new_p, new_s = {}, {}
        for k in params:  # unrolled at trace time
            g = delay_compensate(grads[k], params[k], stales[k], lam)
            updates, s = opt.update(g, states[k], params[k])
            new_p[k] = optax.apply_updates(params[k], updates)
            new_s[k] = s
        return new_p, new_s

    return jax.jit(_apply_dc_tree, static_argnums=(4,))


class PeekMixin:
    """Side-effect-free key read for introspection (KVStore.params()):
    never records async pull snapshots or checks aggregation state."""

    def peek(self, key: str) -> jax.Array:
        if key not in self._params:
            raise KeyError(f"unregistered key {key!r}")
        return self._params[key]


class AsyncStagingMixin:
    """Per-key async pushes stage per WORKER and commit as one fused tree
    apply when that worker's tree completes (SURVEY.md §3 row 11 bucketing:
    a logical push commits as a unit). This makes an N-key per-key push
    sequence cost ONE XLA dispatch instead of N (VERDICT r2 weak #7), and —
    because staging is per worker — the version bump and staleness sample
    are attributed to the worker that actually completed a tree, never to
    whichever worker happened to push last under interleaving (ADVICE r2).

    Liveness: a worker that pushes only a SUBSET of keys commits that
    partial tree the moment it pulls (the pull marks the end of its push
    phase in the PS cycle), so per-key callers that never touch every key
    still make progress — one dispatch per push-pull cycle. Keys are
    independent under per-tensor optimizers, so a partial commit is
    numerically the same as the old immediate per-key applies.

    Engine contract: ``self._staged_async``/``self._params``/``self._state``/
    ``self._stale`` dicts, ``self._jit_apply_dc_tree``, ``self.dc_lambda``,
    ``self.apply_count``, ``self.staleness_hist``, ``self._version`` exist;
    the caller holds the engine lock. Engines may override
    ``_commit_tree_accounting`` for extra per-commit counters.
    """

    def _stage_async_push(self, key, grad, worker) -> None:
        staged = self._staged_async.setdefault(worker, {})
        if key in staged:
            raise RuntimeError(
                f"worker {worker} pushed key {key!r} twice before committing "
                f"— per-key async pushes commit when the full tree is pushed "
                f"or at this worker's next pull (partial tree)"
            )
        staged[key] = grad
        if len(staged) == len(self._params):
            del self._staged_async[worker]
            self._commit_tree(staged, worker)

    def _flush_staged(self, worker) -> None:
        """Commit this worker's staged partial tree, if any (call at the top
        of every async pull, lock held)."""
        staged = self._staged_async.pop(worker, None)
        if staged:
            self._commit_tree(staged, worker)

    def _commit_tree(self, grads_kv, worker) -> None:
        """ONE fused DC apply of a (possibly partial) tree — lock held."""
        sub_p = {k: self._params[k] for k in grads_kv}
        sub_s = {k: self._state[k] for k in grads_kv}
        stales = {
            k: self._stale.get((worker, k), self._params[k]) for k in grads_kv
        }
        new_p, new_s = self._jit_apply_dc_tree(
            sub_p, sub_s, grads_kv, stales, self.dc_lambda
        )
        self._params.update(new_p)
        self._state.update(new_s)
        for k in grads_kv:
            self.apply_count[k] += 1
        self.staleness_hist[self.staleness(worker)] += 1
        self._version += 1
        self._commit_tree_accounting(grads_kv)

    def _commit_tree_accounting(self, grads_kv) -> None:
        """Engine hook: extra counters per committed tree (default none)."""

    def _check_staged_async(self) -> None:
        """Checkpoint guard: staged-but-uncommitted grads would be lost."""
        pending = {w: sorted(kv) for w, kv in self._staged_async.items() if kv}
        if pending:
            raise RuntimeError(
                f"cannot checkpoint mid-push: workers {sorted(pending)} have "
                f"staged but uncommitted per-key async pushes"
            )
