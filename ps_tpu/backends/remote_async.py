"""Cross-process async PS: N server processes, workers elsewhere.

This is the reference's actual async deployment shape (SURVEY.md §4d: the
server applies each worker's stale gradient immediately; workers are
separate, unsynchronized NODES — not host threads). The sync path collapses
into SPMD collectives; async cannot, by design, so it runs host-side:

- each SERVER process owns the key range :func:`ps_tpu.kv.keys.shard_for_key`
  assigns it (SURVEY.md §3 row 4: "range/hash partition of parameter keys
  across N servers") as an async ``KVStore`` (``AsyncTpuServer`` engine —
  params + per-key state on ITS mesh, DC-ASGD applies, tree-granularity
  version vector over ITS subtree) and serves it over the native van's TCP
  layer (:class:`AsyncPSService`). :func:`shard_tree` carves the owned
  subtree out of the full model;
- each WORKER process runs :class:`RemoteAsyncWorker`: pull params from
  every owner, compute gradients on its OWN jax devices, push each owner its
  subtree — one concurrent ``PUSH_PULL`` round per cycle (one round trip per
  server, in flight simultaneously). Staleness is real cross-process
  staleness, tracked PER SERVER: each server's version counts whole-subtree
  applies to its own range, and the DC correction at server s uses the τ
  between this worker's last pull from s and its push to s. A dead server
  surfaces as a typed :class:`ServerFailureError` at the worker.

Parity contract (tests/test_remote_async.py, tests/test_multiserver_async.py):
each server records its apply order; replaying that exact (worker, grads)
sequence through in-process ``AsyncTpuServer`` engines — one per key range —
yields bit-identical parameters — the wire and the partition change nothing
about the math.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ps_tpu import obs
from ps_tpu.obs import freshness
from ps_tpu.backends.common import (
    DEFAULT_BUCKET_BYTES,
    DRAIN_TO_TIMEOUT_S,
    BucketAssembler,
    BucketedTransportMixin,
    BucketPlan,
    ServerFailureError,
    TableMovedError,
    parse_replica_uri,
    payload_nbytes,
    request_payload,
)
from ps_tpu.backends.van_service import (
    StaleTableError,
    VanService,
    log_tail,
    make_history_log,
    resolve_ckpt_dir,
)
from ps_tpu.compress import CompressPolicy, GradCompressor, decode_tree
from ps_tpu.control import tensor_van as tv
from ps_tpu.kv import keys as keymod
from ps_tpu.utils.metrics import TransportStats

__all__ = [
    "AsyncPSService", "RemoteAsyncWorker", "ServerFailureError",
    "serve_async", "connect_async", "shard_tree", "PendingCycle",
]


def shard_tree(params_like, shard: int, num_shards: int) -> Dict[str, Any]:
    """The flat ``{key: leaf}`` subtree that server ``shard`` of
    ``num_shards`` owns under the :func:`~ps_tpu.kv.keys.shard_for_key` hash
    partition.

    A flat dict of slash-joined key strings is itself a valid pytree whose
    flatten reproduces the same keys, so a server process can pass the
    returned dict straight to ``KVStore.init`` and own exactly its range.
    """
    kv, _ = keymod.flatten_with_keys(params_like)
    return {k: v for k, v in kv.items()
            if keymod.shard_for_key(k, num_shards) == shard}


class AsyncPSService(VanService):
    """Serve an async KVStore to remote workers over the tensor van.

    Accept/serve/drain machinery (and the stop() guarantees) live in
    :class:`~ps_tpu.backends.van_service.VanService`; this class is the
    protocol: HELLO/PULL/PUSH/PUSH_PULL/STATS over the async engine.

    Args:
      store: an initialized async-mode KVStore (the server engine).
      port: TCP port (0 = ephemeral; read :attr:`port`).
      bind: listen address. Defaults to loopback — the endpoint is
        unauthenticated, so exposing it pod-wide ("0.0.0.0") is an explicit
        opt-in, mirroring ``Config.resolved_heartbeat_bind``.
      shard/num_shards: this server's position in an N-server key partition
        (None = the classic single-server topology). When set, the store's
        keys are validated against the ``shard_for_key`` assignment at
        construction and advertised to workers in the HELLO reply so a
        misconfigured topology fails loudly at connect time.
      ckpt_root: when set, CHECKPOINT saves resolve the client-supplied dir
        UNDER this root (absolute paths and ``..`` escapes refused) — the
        unauthenticated endpoint can then never write outside it. None
        keeps the legacy client-names-the-path behavior (loopback only).
    """

    def __init__(self, store, port: int = 0, bind: str = "127.0.0.1",
                 shard: Optional[int] = None,
                 num_shards: Optional[int] = None,
                 ckpt_root: Optional[str] = None,
                 writev: Optional[bool] = None,
                 shm: Optional[bool] = None,
                 backup: bool = False,
                 record_full_history: bool = False,
                 history: int = 4096,
                 coordinator=None,
                 advertise_host: str = "127.0.0.1",
                 native_loop: Optional[bool] = None,
                 loop_threads: Optional[int] = None):
        engine = store._engine
        if getattr(engine, "mode", "sync") != "async":
            raise ValueError("AsyncPSService requires an async-mode KVStore")
        if (shard is None) != (num_shards is None):
            raise ValueError("pass shard and num_shards together")
        if coordinator is not None and num_shards is not None:
            raise ValueError(
                "pass either shard/num_shards (static hash topology) or "
                "coordinator (elastic membership), not both — under a "
                "coordinator the shard table owns the assignment"
            )
        self.shard, self.num_shards = shard, num_shards
        self._store = store
        self._engine = engine
        self._key_order = list(store._key_order)
        # freshness plane (README "Online serving & freshness"): the
        # birth stamp of the currently servable version — re-stamped
        # under the engine lock by every state change that makes new
        # bytes servable (applies, replica-stream applies, migration
        # cutovers). It rides every READ reply as committed STATE (never
        # a serve-time clock, which would break the byte-deterministic
        # reply contract the native cache needs), so each serving tier
        # can report age = now - birth. Never-applied state has NO birth
        # (None): its age is undefined, and two services constructed
        # over the same state must encode byte-identical replies.
        self._birth: Optional[dict] = None
        if num_shards is not None:
            misplaced = [k for k in self._key_order
                         if keymod.shard_for_key(k, num_shards) != shard]
            if misplaced:
                raise ValueError(
                    f"store holds keys not owned by shard {shard}/"
                    f"{num_shards}: {misplaced[:3]} — init the server's "
                    f"store with shard_tree(params, shard, num_shards)"
                )
        # set under the engine lock by _set_draining(); checked under the
        # same lock by the push path, so "no push is applied after stop()
        # returns" holds even if a serve thread outlives the join (e.g.
        # blocked in a jit compile inside the engine apply)
        self._draining = False
        # checkpoint pause: while True, pushes BLOCK (not refuse), except
        # the ones a drain_to round admits — see _checkpoint for the
        # cross-shard-atomicity protocol these implement
        self._paused = False
        self._pause_cond = threading.Condition(engine._lock)
        # checkpoint ownership token bookkeeping lives in VanService
        # (_ckpt_issue_token / _ckpt_token_error): pause hands out a token;
        # drain_to/save/resume must present it, so two concurrent
        # checkpoint_all coordinators cannot interleave
        self._ckpt_root = ckpt_root
        # bucketed-pull snapshot cache: worker -> one pulled tree awaiting
        # its remaining bucket requests (per-bucket frames encoded lazily
        # on the serve thread that asks — pool channels parallelize the
        # encode)
        self._pull_cache: Dict[int, dict] = {}
        self._applied: Dict[int, int] = {}   # per-worker applied pushes
        self._drain_targets: Dict[int, int] = {}
        # exactly-once under failover replay AND across rebalance
        # handoffs: worker -> {key: (nonce, seq)} of the last applied
        # dedup-tagged push PER KEY. Per key, not per worker, because one
        # logical push fans out sub-pushes carrying the SAME seq to many
        # shards and a live rebalance can merge ranges: after a move, one
        # replayed (nonce, seq) can be already-applied for this shard's
        # own keys yet never-applied for the adopted ones — a scalar
        # token would either lose the adopted keys' gradient (false
        # dedup) or double-apply the others. Tokens MIGRATE with their
        # keys (MIGRATE_COMMIT) and replicate with each push entry, so
        # promoted backups and move recipients suppress exactly the
        # replays their donors would have.
        self._applied_pseq: Dict[int, Dict[str, tuple]] = {}
        self._log_lock = threading.Lock()
        # worker id per committed tree, in order — a bounded ring by
        # default (a long-lived server must not hold O(applies) memory);
        # record_full_history=True keeps everything for the replay-parity
        # tests
        self.apply_log = make_history_log(record_full_history, history)
        # ordered (op, worker) history — "pull" records matter because
        # the DC apply depends on WHAT each worker last pulled; replaying
        # this log through a threaded engine reproduces params bit-for-bit
        self.event_log = make_history_log(record_full_history, history)
        # elastic membership (ps_tpu/elastic): _elastic flips the key-set
        # mismatch refusal from a hard KeyError to the typed, retry-able
        # StaleTableError (workers re-fetch the table and re-route).
        # _migrating is the double-write set of an in-flight outbound
        # move; _moved_keys remembers what migrated away (and at which
        # table epoch); _migrate_in stages an inbound move's rows until
        # its MIGRATE_COMMIT installs them atomically.
        self._elastic = coordinator is not None
        self._coordinator = coordinator
        self._coord_member = None
        self._migrating: frozenset = frozenset()
        self._migrate_session = None
        self._moved_keys: Dict[str, int] = {}
        self._migrate_in: Optional[dict] = None
        self._migrate_committed: Optional[dict] = None  # last cutover,
        # for idempotent re-asked MIGRATE_COMMITs (lost-reply ambiguity)
        self._migrate_out_done: Optional[dict] = None  # last committed
        # outbound move — same ambiguity, coordinator->donor hop
        # starts accepting: state ready
        super().__init__(port=port, bind=bind, writev=writev, shm=shm,
                         backup=backup, native_loop=native_loop,
                         loop_threads=loop_threads)
        if coordinator is not None and not backup:
            # register AFTER the listener is up (the advertised URI needs
            # the bound port); backups join the table only when promoted
            # into service — their replica set is already in the URI
            self._join_coordinator(advertise_host)

    def _join_coordinator(self, advertise_host: str) -> None:
        from ps_tpu.elastic.member import CoordinatorMember

        key_bytes = {k: int(self._engine._params[k].nbytes)
                     for k in self._key_order}

        last = {"t": time.monotonic(), "req": self._req_counter.value,
                "applies": self.apply_log.total}

        def report_extra() -> dict:
            # windowed rates from the counters the service already keeps:
            # applies/s is the push rate, (requests - applies)/s is a fair
            # stand-in for the read rate — no new bookkeeping on the hot
            # path just to feed the coordinator
            now = time.monotonic()
            req, applies = self._req_counter.value, self.apply_log.total
            dt = max(now - last["t"], 1e-6)
            push_qps = (applies - last["applies"]) / dt
            pull_qps = max(req - last["req"] - (applies - last["applies"]),
                           0) / dt
            last.update(t=now, req=req, applies=applies)
            # under the engine lock: a migration cutover mutates the
            # params dict mid-iteration otherwise (the reporter thread
            # racing adopt/evict would silently drop this report)
            with self._engine._lock:
                nkeys = len(self._key_order)
                nbytes = sum(int(v.nbytes)
                             for v in self._engine._params.values())
            out = {
                "keys": nkeys,
                "nbytes": nbytes,
                "push_qps": round(push_qps, 2),
                "pull_qps": round(pull_qps, 2),
            }
            # replication health rides the load report: the autopilot's
            # re-seed rule keys off a DEGRADED stream (backup died) or a
            # promoted survivor serving without redundancy
            s = self._backup_session
            if s is not None or self.promote_reason is not None:
                out["repl"] = {
                    "attached": bool(s is not None and not s.degraded),
                    "degraded": bool(s is not None and s.degraded),
                    "promoted": self.promote_reason is not None,
                }
            return out

        # fleet telemetry (README "Fleet telemetry"): delta-encoded metric
        # snapshots piggyback on the load reports — THIS service's own
        # TransportStats (apply/ack histograms, dedup/stale counters) plus
        # its apply counter, never the process-global registry, so several
        # in-process services each report their own numbers
        from ps_tpu.config import env_flag
        from ps_tpu.obs.collector import collect_telemetry

        telemetry = None
        if env_flag("PS_TELEMETRY", True):
            def telemetry() -> dict:
                return collect_telemetry(self.transport, counters={
                    "ps_applies_total": lambda: self.apply_log.total,
                })

        self._coord_member = CoordinatorMember(
            self._coordinator, f"{advertise_host}:{self.port}",
            key_bytes, kind="dense", report=report_extra,
            telemetry=telemetry)
        self.table_epoch = self._coord_member.table.epoch

    # -- server internals -----------------------------------------------------

    def _params_payload(self, worker: int) -> bytes:
        # engine lock makes snapshot+version+log-append atomic (torn-read
        # hazard, and the event log must mirror true engine order). Only the
        # REFERENCE snapshot happens under the lock: jax arrays are
        # immutable and the engine replaces (never mutates) them on apply,
        # so the host conversion + frame encode — the expensive part at
        # BERT-size trees, measured in tools/bench_van.py — runs outside,
        # letting other workers' applies/pulls proceed concurrently.
        with self._engine._lock:
            kv = self._engine.pull_tree(worker=worker)
            version = self._engine.version
            with self._log_lock:
                self.event_log.append(["pull", worker])
            # pulls replicate too: the DC apply depends on what each worker
            # last pulled, so the backup's _stale bookkeeping must follow
            rseq = self._replicate("pull", worker)  # pslint: disable=PSL101 -- deliberate backpressure: a full ack window MUST stall commits under the apply lock (that IS the bounded-lag contract), and stall_timeout degrades a corpse instead of wedging
        self._await_replication(rseq)
        host = {k: np.asarray(v) for k, v in kv.items()}
        if self.writev:
            # vectored reply: the host tensors are sent as live views
            # (pinned by the parts), never staged into a frame bytearray
            return tv.encode_parts(tv.OK, worker, host,
                                   extra={"version": version})
        return tv.encode(tv.OK, worker, host, extra={"version": version})

    def _read_payload(self) -> bytes:
        """Serve one READ (README "Read path"): a side-effect-free,
        version-stamped snapshot of this shard's whole subtree. Unlike
        PULL there is NO event-log record, NO replication entry, and NO
        per-worker DC stale snapshot — a read is an observation, not a
        training-protocol step — which is exactly what makes the reply a
        pure function of committed state: byte-identical requests get
        byte-identical replies (fixed worker id 0, contiguous encode),
        so the native loop can answer repeats from its read cache with
        zero upcalls. The publish generation is captured UNDER the engine
        lock with the snapshot; an apply racing the publish refuses it at
        the native floor (invalidation-on-apply)."""
        with self._engine._lock:
            kv = {k: self._engine._params[k] for k in self._key_order}
            version = self._engine.version
            birth = dict(self._birth) if self._birth is not None else None
            gen = self._read_gen_snapshot()
        host = {k: np.asarray(v) for k, v in kv.items()}
        reply = tv.encode(tv.OK, 0, host, extra={"version": version,
                                                 **(birth or {})})
        self._note_read_snapshot(gen, version)
        self.transport.record_read_served()
        self._note_serve_age(birth)
        return reply

    def _read_cond_reply(self, extra) -> bytes:
        """Conditional READ front end (README "Read path"): when the
        caller's known version (``extra["cond"]``) is current, a tiny
        NOT_MODIFIED version stamp replaces the whole-subtree payload —
        the steady-state revalidation of a read-mostly deployment.
        Anything else (no cond, or a changed tree) delegates to
        :meth:`_read_payload` unchanged. Deterministic like the full
        path (fixed worker id 0), so byte-identical conditional requests
        stay servable from the native read cache; the version floor the
        native tier checks is exactly this comparison, compiled into the
        cached entry at publish time."""
        cond = None
        if isinstance(extra, dict) and extra.get("cond") is not None:
            cond = int(extra["cond"])
        if cond is not None:
            with self._engine._lock:
                version = self._engine.version
                birth = dict(self._birth) if self._birth is not None else None
                gen = self._read_gen_snapshot()
            if version <= cond:
                # the NOT_MODIFIED stamp carries the birth too: a hot
                # cached row must report TRUE freshness on every
                # revalidation, not the age it had when first fetched
                reply = tv.encode(tv.NOT_MODIFIED, 0, None,
                                  extra={"version": version, **(birth or {})})
                self._note_read_snapshot(gen, version)
                self.transport.record_read_served()
                self.transport.record_read_not_modified()
                self._note_serve_age(birth)
                return reply
        return self._read_payload()

    def _read_version(self):
        return self._engine.version

    def _apply_push(self, worker: int, grads: Dict[str, np.ndarray],
                    copy: bool = True,
                    extra: Optional[dict] = None) -> Tuple[Optional[int], bool]:
        """Apply one whole-tree push; returns ``(replication_seq, dedup)``.

        ``extra``'s optional ``pseq``/``pnonce`` are the worker's dedup
        token: a (nonce, seq) at or below the last applied one is a replay
        — an in-flight push whose reply died with the old primary, resent
        at this (possibly promoted) server — and is acked WITHOUT applying,
        so failover retries are exactly-once. The dedup check runs BEFORE
        the key-range check: a replay of a push this shard applied before
        a rebalance moved some of its keys away must be acked (the moved
        state already carries it), not refused."""
        extra = extra or {}
        pseq = extra.get("pseq")
        pnonce = extra.get("pnonce")
        if copy:
            # copy out of the recv buffer: the engine may keep references
            # beyond this frame's lifetime (bucket-assembled trees already
            # own their buffers and skip this)
            grads = {k: np.array(v) for k, v in grads.items()}
        # span-phase tagging for the per-step breakdown (ps_tpu/obs/
        # breakdown.py): the apply — lock wait included, contention IS
        # apply-path latency — gets an always-on histogram sample
        # (ps_server_apply_seconds, the straggler detector's default
        # signal) and, when the request is traced, a server_apply child
        # span under the dispatch span. Dedup replays and refusals are
        # NOT applies and record nothing.
        t_apply = time.perf_counter()
        apply_s = None
        apply_span = obs.tracer().child("server_apply", cat="server")
        if extra.get("members_tc"):
            # a merged push: the constituents' trace contexts ride beside
            # their dedup tokens — naming them on the apply span lets any
            # ONE member's trace find the shared upstream commit
            apply_span.set(members_tc=extra["members_tc"])
        with apply_span, self._engine._lock:
            while (self._paused and not self._draining
                   and not self._admit_while_paused(worker)):
                self._pause_wait_begin()
                try:
                    self._pause_cond.wait()  # checkpoint snapshot in flight
                finally:
                    self._pause_wait_end()
            if self._draining:
                raise RuntimeError("server is draining; push refused")
            # every validation below runs AFTER any pause park: the wait
            # releases the engine lock, so dedup/ledger/key-range state
            # may have moved while this push was parked (e.g. a degraded
            # member's flat replay settling a constituent of a parked
            # merged push) — a verdict computed before the park would be
            # stale, which is exactly a double-apply window
            fresh = grads
            # the native admission stamp proves the loop classified this
            # frame strictly fresh at a generation no apply has superseded
            # (checked HERE, under the lock and after any park): the
            # per-key dedup scan would find nothing, so skip it. A stale
            # or absent stamp takes the full scan — never a double apply.
            if pseq is not None and not self._admit_fresh_hint():
                fresh = self._dedup_fresh(worker, pnonce, int(pseq), grads)
                if not fresh:
                    # every key already carries this (nonce, seq): the
                    # replay of a fully-applied push — ack, never touch
                    # the engine
                    self.transport.record_dedup_hit()
                    return None, True
            members = extra.get("members")
            if members:
                # merged push vs its constituents' own flat replays: a
                # group that degraded mid-round races its dead
                # aggregator's in-flight merged push. First writer wins
                # per member: if every constituent's contribution is
                # already covered by its own recorded token, the merged
                # push is a pure replay (ack, never apply); a PARTIAL
                # overlap cannot be subtracted from a summed tree, so it
                # is refused loudly rather than silently double-applied.
                verdict = self._check_members(members, fresh)
                if verdict == "dedup":
                    self.transport.record_dedup_hit()
                    return None, True
            # under the lock (and after the park): a migration cutover
            # flips _key_order under this same lock, so the check and
            # the apply see ONE table
            self._check_push_keys(grads)
            if len(fresh) == len(grads):
                self._engine.push_tree(fresh, worker=worker)
            else:
                # a replay straddling a range move: this shard's own keys
                # already applied this (nonce, seq) — only the adopted
                # keys' sub-update is still owed. Apply exactly those.
                self.transport.record_dedup_hit()
                self._engine.push_subtree(fresh, worker=worker)
            # invalidation-on-apply (README "Read path"): cached READ
            # replies now describe a superseded version — drop them and
            # refuse any in-flight publish of the pre-apply snapshot
            self._invalidate_reads()
            self._birth = freshness.birth_record()
            apply_s = time.perf_counter() - t_apply
            self._applied[worker] = self._applied.get(worker, 0) + 1
            if pseq is not None:
                toks = self._applied_pseq.setdefault(worker, {})
                for k in fresh:
                    toks[k] = (pnonce, int(pseq))
            # hierarchical aggregation (backends/aggregator.py): a merged
            # push carries its CONSTITUENT members' own dedup tokens next
            # to the aggregator's derived one. Recording both keeps the
            # ledger exactly-once across the handoff in either direction:
            # a member that degrades to the flat path and replays a push
            # its dead aggregator already forwarded dedups against its own
            # recorded token, and an aggregator-side failover replay of
            # the merged push dedups against the derived token — the two
            # live under different worker ids, so neither evicts the other.
            self._record_members(extra.get("members"), fresh)
            # republish the settled ledger rows this apply advanced — the
            # pushing worker's and, for a merged push, every constituent
            # member's — plus the fresh replay-ack template, to the native
            # admission mirror at the post-apply generation (the
            # _invalidate_reads above bumped it)
            self._admit_publish(worker,
                                *[int(w) for w in
                                  (extra.get("members") or {})])
            self._pause_cond.notify_all()  # a drain_to waiter may be watching
            with self._log_lock:
                self.apply_log.append(worker)
                self.event_log.append(["push", worker])
            # double-write: a commit touching keys mid-migration re-streams
            # their post-apply rows, so the recipient converges on the live
            # state (later rows supersede earlier ones)
            if self._migrating:
                self._publish_migrating(self._migrating.intersection(fresh))
            # replicate the post-decode host tree (it owns its buffers by
            # now) — exactly the applied subset, carrying the dedup token,
            # so a promoted backup suppresses the same replays its primary
            # would have. A straddling replay's PARTIAL apply ships as the
            # distinct "push_sub" op: the backup must mirror the subset
            # apply, not refuse it as a torn whole-tree push.
            rseq = self._replicate(  # pslint: disable=PSL101 -- deliberate backpressure: a full ack window MUST stall commits under the apply lock (that IS the bounded-lag contract), and stall_timeout degrades a corpse instead of wedging
                "push" if len(fresh) == len(self._key_order)
                else "push_sub",
                worker, fresh, {"pseq": pseq, "pnonce": pnonce,
                                "members": extra.get("members"),
                                "birth": self._birth["birth"]})
        if apply_s is not None:
            self.transport.record_apply(apply_s)
            # push->first-servable on the primary: the lock is released
            # and the invalidation floor raised — a READ serves the new
            # version from here on (ps_freshness_lag_seconds)
            self.transport.record_fresh_lag(time.perf_counter() - t_apply)
        return rseq, False

    @staticmethod
    def _token_settled(cur, nonce, seq: int) -> bool:
        """THE ledger predicate, shared by dedup classification
        (:meth:`_dedup_fresh`, :meth:`_check_members`) and recording
        (:meth:`_record_members`) so the exactly-once semantics cannot
        drift between them: a recorded token at/past (nonce, seq) —
        same-nonce comparison only, a new nonce is a new incarnation
        whose seqs restart — means that push already carries this key."""
        return cur is not None and cur[0] == nonce and int(seq) <= cur[1]

    def _record_members(self, members, fresh) -> None:
        """Record a merged push's constituent (worker, nonce, seq) tokens
        for every key it applied (engine lock held). ``members`` is the
        aggregator's ``{worker_str: [nonce, seq]}`` map, None/empty on
        ordinary pushes. The ledger only ever advances: a member that
        already applied a LATER flat push (it degraded and moved on)
        must not have its token moved backward — that would re-open
        dedup for a seq the engine already holds."""
        for w_str, t in (members or {}).items():
            toks = self._applied_pseq.setdefault(int(w_str), {})
            for k in fresh:
                if self._token_settled(toks.get(k), t[0], t[1]):
                    continue
                toks[k] = (t[0], int(t[1]))

    def _check_members(self, members, fresh) -> str:
        """Classify a merged push against its constituents' recorded
        tokens (engine lock held): "apply" (no constituent applied —
        the normal case), "dedup" (EVERY constituent's token is already
        at/past its merged entry on every key — the whole merged push is
        a replay of individually-settled state), or raise (a partial
        overlap: some member's gradient is already in the engine via its
        own flat replay, and a summed tree cannot be partially applied —
        refusing loudly is the only exactly-once answer)."""
        stale = total = 0
        for w_str, t in members.items():
            toks = self._applied_pseq.get(int(w_str)) or {}
            for k in fresh:
                total += 1
                if self._token_settled(toks.get(k), t[0], t[1]):
                    stale += 1
        if stale == 0:
            return "apply"
        if stale == total:
            return "dedup"
        raise RuntimeError(
            "merged push refused: some of its constituent pushes were "
            "already applied individually (the group degraded mid-round "
            "and replayed flat) — a summed tree cannot be partially "
            "applied; the remaining members' flat replays settle the "
            "round exactly-once"
        )

    def _dedup_fresh(self, worker: int, pnonce, pseq: int,
                     grads: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Split a dedup-tagged push into the keys still OWED an apply
        (engine lock held): a key whose last applied token is at or past
        (pnonce, pseq) already carries this push — applied here directly,
        via a dead primary's replication stream, or via a migrated row's
        transferred token. Same-nonce comparison only: a new nonce is a
        new worker incarnation whose seqs restart."""
        toks = self._applied_pseq.get(worker)
        if not toks:
            return grads
        fresh = {}
        for k, v in grads.items():
            if self._token_settled(toks.get(k), pnonce, pseq):
                continue
            fresh[k] = v
        return fresh

    # -- zero-upcall push plane (README "Push path") ---------------------------

    def _admit_kind(self):
        # whole-tree PUSH only: PUSH_PULL replies with params (no
        # template can pre-encode them) and bucket frames are staged
        return tv.PUSH

    def _admit_entry(self, worker: int):
        """This worker's per-key token map folded to one (nonce, lo, hi)
        ledger row — publishable only when EVERY served key carries a
        token under ONE nonce (lo = min seq, hi = max seq): a replay
        at/below lo is settled on every key (the pump would pure-ack
        it), above hi is strictly fresh on every key. A partial or
        mixed-nonce map returns None and the worker's frames punt — the
        straddling-replay subtree apply stays pump-only."""
        toks = self._applied_pseq.get(worker)
        order = self._key_order
        if not toks or not order:
            return None
        nonce = None
        lo = hi = 0
        for k in order:
            t = toks.get(k)
            if t is None or not isinstance(t[0], str):
                return None
            if nonce is None:
                nonce, lo, hi = t[0], int(t[1]), int(t[1])
            elif t[0] != nonce:
                return None
            else:
                s = int(t[1])
                lo = min(lo, s)
                hi = max(hi, s)
        return nonce, lo, hi

    def _admit_ack_bytes(self):
        # byte-for-byte the pump's pure-replay ack (worker id patched by
        # the loop): current engine version, dedup flag set
        return tv.encode(tv.OK, 0, None, extra={
            "version": self._engine.version, "dedup": True,
        })

    def _check_push_keys(self, grads) -> None:
        """Key-range validation (engine lock held). On an elastic service
        a mismatch means the WORKER's table is stale — keys moved shards
        under it — so the refusal is the typed, retry-able
        :class:`~ps_tpu.backends.van_service.StaleTableError` (re-fetch
        and re-route), never a job-killing KeyError."""
        if sorted(grads) == sorted(self._key_order):
            return
        if self._elastic:
            wrong = sorted(set(grads) ^ set(self._key_order))
            moved = [k for k in wrong if k in self._moved_keys]
            raise StaleTableError(
                f"push keys do not match this shard's key range (table "
                f"epoch {self.table_epoch}): "
                + (f"{moved[:3]} moved to another shard"
                   if moved else f"{wrong[:3]} differ")
            )
        raise KeyError("push keys do not match the registered tree")

    def _publish_migrating(self, touched) -> None:
        """Stream the just-committed state of still-migrating keys to the
        recipient (engine lock held — row order IS engine order)."""
        from ps_tpu.elastic.migrate import encode_row

        s = self._migrate_session
        if not touched or s is None or s.degraded:
            return  # a degraded stream aborts the move; nothing to feed
        rows = self._engine.export_keys(touched)
        for k in sorted(rows):
            r = rows[k]
            tensors, meta = encode_row(k, r["param"], r["state"],
                                       r["stale"], r["apply_count"])
            s.publish_row(k, tensors, meta)  # pslint: disable=PSL101 -- deliberate backpressure, same contract as replication: a full migration window MUST stall commits of moving keys (bounded-lag catch-up), and stall_timeout degrades-then-aborts a stalled recipient instead of wedging the shard

    def _admit_while_paused(self, worker: int) -> bool:
        """Under pause, admit exactly the pushes a drain_to round asked
        for: this worker still lags its cross-shard target."""
        return (self._applied.get(worker, 0)
                < self._drain_targets.get(worker, 0))

    def _decode_push(self, tensors, extra) -> Dict[str, np.ndarray]:
        """Serial-path twin of the bucket decode: unpack codec-compressed
        keys (``extra["enc"]``) before aggregation."""
        enc = extra.get("enc")
        if not enc:
            return tensors
        return decode_tree(dict(tensors), enc, stats=self.transport)

    # -- bucketed transport (server half) -------------------------------------

    def _bucket_push(self, worker: int, tensors, extra) -> bytes:
        """One bucket of a multi-bucket push. Incomplete epochs only stage
        (ack reply); the completing bucket applies the WHOLE assembled tree
        atomically under the engine lock — a torn push is never observable,
        and the commit reply carries the advanced version. Codec-packed
        keys (``extra["enc"]``, same list on every bucket of the epoch)
        are decoded here, after assembly and before aggregation."""
        tree = self._stage_bucket_push(
            worker, int(extra["bucket"]), int(extra["nbuckets"]),
            int(extra["epoch"]), tensors["raw"], extra["slices"],
            nonce=extra.get("nonce"),
        )
        if tree is None:
            return tv.encode(tv.OK, worker, None,
                             extra={"staged": int(extra["bucket"])})  # pslint: disable=PSL203 -- debug-visibility ack field: names the staged bucket on the wire for packet-level triage; workers need only the OK
        tree = decode_tree(tree, extra.get("enc"), stats=self.transport)
        rseq, dedup = self._apply_push(worker, tree, copy=False, extra=extra)
        self._await_replication(rseq)
        return tv.encode(tv.OK, worker, None, extra={
            "version": self._engine.version, "committed": True,
            "dedup": dedup,  # pslint: disable=PSL203 -- exactly-once visibility: asserted by the tests/test_replica.py replay drills; workers treat a dedup'd ack like any other
        })

    def _bucket_pull(self, worker: int, extra) -> bytes:
        """Bucketed pull. Bucket 0 takes ONE atomic engine snapshot (same
        lock discipline and event-log record as a serial PULL) and replies
        with the front-of-model slices immediately; buckets 1..n-1 read the
        cached snapshot, each encoded on its own serve thread — the pool
        parallelizes the host-conversion + frame-encode that the serial
        path runs end-to-end."""
        epoch, b = int(extra["epoch"]), int(extra["bucket"])
        if b == 0:
            bb = int(extra.get("bucket_bytes") or DEFAULT_BUCKET_BYTES)
            with self._engine._lock:
                kv = self._engine.pull_tree(worker=worker)
                version = self._engine.version
                # a migration cutover replaces _key_order under this lock:
                # snapshot the transport order WITH the tree it describes
                key_order = list(self._key_order)
                with self._log_lock:
                    self.event_log.append(["pull", worker])
                rseq = self._replicate("pull", worker)  # pslint: disable=PSL101 -- deliberate backpressure: a full ack window MUST stall commits under the apply lock (that IS the bounded-lag contract), and stall_timeout degrades a corpse instead of wedging
            self._await_replication(rseq)
            # contiguous host conversion ONCE; per-bucket encodes then slice
            # it zero-copy (jax arrays convert contiguous, but be explicit)
            host = {k: np.ascontiguousarray(np.asarray(v))
                    for k, v in kv.items()}
            # return-path compression, negotiated per request: the worker
            # names the codec spec; the server applies the same per-key
            # policy it would and labels the packed keys in every reply
            # bucket's header. Stateless codecs only (checked worker-side).
            enc: List[str] = []
            spec = extra.get("compress")
            if spec:
                # fresh, decorrelated quantization noise per (worker,
                # pull epoch): rebuilding the codec from a FIXED seed would
                # replay the same uniform draw every pull, turning
                # stochastic rounding into a persistent position-dependent
                # bias that never averages away
                spec = dict(spec)
                spec["seed"] = ((int(spec.get("seed", 0)) * 1000003
                                 + worker * 9176 + epoch) & 0x7FFFFFFF)
                comp = GradCompressor(CompressPolicy.from_spec(spec),
                                      stats=self.transport)
                host, enc = comp.encode_tree(host)
                host = {k: np.ascontiguousarray(v)
                        for k, v in host.items()}
            plan = BucketPlan.from_arrays(host, bb, order=key_order)
            with self._stage_lock:
                if plan.nbuckets > 1:
                    self._pull_cache[worker] = {
                        "epoch": epoch, "host": host, "plan": plan,
                        "version": version, "enc": enc,
                        "left": set(range(1, plan.nbuckets)),
                    }
                else:
                    self._pull_cache.pop(worker, None)
            # vectored reply: the snapshot's live views go straight to the
            # send (writev iovecs, or one shm-ring write) — the reply's
            # tensor bytes are never staged into a frame bytearray.
            # `host` outlives the send: the views pin it, and multi-bucket
            # snapshots sit in _pull_cache anyway.
            enc_fn = plan.bucket_encoder(self.writev)
            return enc_fn(tv.OK, worker, host, 0, extra={
                "epoch": epoch, "version": version, "enc": enc,
            })
        with self._stage_lock:
            entry = self._pull_cache.get(worker)
            if (entry is None or entry["epoch"] != epoch
                    or b not in entry["left"]):
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": f"no cached pull snapshot for worker {worker} "
                             f"epoch {epoch} bucket {b}",
                })
            entry["left"].discard(b)
            if not entry["left"]:
                self._pull_cache.pop(worker, None)
        enc_fn = entry["plan"].bucket_encoder(self.writev)
        return enc_fn(
            tv.OK, worker, entry["host"], b,
            extra={"epoch": epoch, "version": entry["version"],
                   "enc": entry["enc"]},
        )

    def _handle(self, kind: int, worker: int, tensors, extra) -> bytes:
        if kind == tv.HELLO:
            return tv.encode(tv.OK, worker, None, extra={
                "keys": self._key_order,
                "version": self._engine.version,
                "num_workers": self._engine.num_workers,
                "shard": self.shard,
                "num_shards": self.num_shards,
                "epoch": self.epoch,
                "role": self.role,
                "table_epoch": self.table_epoch,
            })
        elif kind == tv.PULL:
            return self._params_payload(worker)
        elif kind == tv.READ:
            return self._read_cond_reply(extra)
        elif kind == tv.PUSH:
            rseq, dedup = self._apply_push(
                worker, self._decode_push(tensors, extra), extra=extra)
            self._await_replication(rseq)
            return tv.encode(tv.OK, worker, None, extra={
                "version": self._engine.version, "dedup": dedup,
            })
        elif kind == tv.PUSH_PULL:
            self._apply_push(worker, self._decode_push(tensors, extra),
                             extra=extra)
            # no separate ack wait: the pull record below is a LATER log
            # entry, and the reply already waits on it (FIFO acks)
            return self._params_payload(worker)
        elif kind == tv.BUCKET_PUSH:
            return self._bucket_push(worker, tensors, extra)
        elif kind == tv.BUCKET_PULL:
            return self._bucket_pull(worker, extra)
        elif kind == tv.STATS:
            with self._log_lock:
                # a bounded TAIL plus the true total — never the unbounded
                # list: a 10⁶-apply server must not ship multi-MB stats
                # frames (or hold them; the log itself is a ring unless
                # record_full_history opted in)
                log = log_tail(self.apply_log)
                log_total = self.apply_log.total
            out = {
                "version": self._engine.version,
                "staleness_hist": {
                    str(t): n for t, n in
                    self._engine.staleness_hist.items()
                },
                "apply_log": log,
                "apply_log_total": log_total,
                "worker_version": {
                    str(w): v for w, v in
                    self._engine._worker_version.items()
                },
                # stale-epoch staging drops, observable fleet-wide instead
                # of only in server stderr (codec-PR satellite)
                "stale_epochs": self.transport.stale_epochs,
                "stale_epoch_buckets": self.transport.stale_epoch_buckets,
                # the extended STATS frame (ps_tpu/obs): rate gauges plus
                # p50/p99/p999 latency distributions — what ps_top renders
                "metrics": self.transport.metrics_snapshot(),
            }
            out.update(self.replica_state())
            if self._elastic:
                out["table_epoch"] = self.table_epoch
                out["keys_moved"] = len(self._moved_keys)
            return tv.encode(tv.OK, worker, None, extra=out)
        elif kind == tv.CHECKPOINT:
            return self._checkpoint(worker, extra)
        elif kind == tv.MIGRATE_OUT:
            return self._migrate_out(worker, extra)
        elif kind == tv.MIGRATE_BEGIN:
            return self._migrate_begin(worker, extra)
        elif kind == tv.MIGRATE_ROW:
            return self._migrate_row(worker, tensors, extra)
        elif kind == tv.MIGRATE_COMMIT:
            return self._migrate_commit(worker, extra)
        elif kind == tv.MIGRATE_ABORT:
            return self._migrate_abort(worker)
        elif kind == tv.RESEED:
            return self._reseed_backup(worker, extra)
        return tv.encode(tv.ERR, worker, None,
                         extra={"error": f"bad kind {kind}"})

    def _checkpoint(self, worker: int, extra: dict) -> bytes:
        """Coordinated multi-server checkpoint (SURVEY.md §6: server state
        survives restarts), driven by :meth:`RemoteAsyncWorker.
        checkpoint_all` in three phases so the snapshot is CROSS-SHARD
        atomic: 'pause' blocks new applies on every server, 'save' writes
        this server's shard to ``<dir>/shard<i>`` (``<dir>`` unsharded),
        'resume' releases the applies. Pausing first means no worker's
        push can be applied by one shard after its save and by another
        before it — the state on disk is a point every shard agrees on.
        The save holds the engine lock (pulls also mutate engine
        bookkeeping — the per-worker stale snapshots and version vector —
        so an unlocked save could tear them), which stalls this server's
        traffic for the write's duration: the price of an atomic snapshot
        point, paid once per checkpoint cadence. The endpoint writes paths
        on the server host and is unauthenticated — ``ckpt_root`` confines
        its filesystem reach, and ``bind`` defaults to loopback.

        Ownership: ``pause`` hands the coordinator a token; every later
        phase must present it. A second coordinator's pause while one is
        outstanding is refused, and a resume/save without the live token is
        refused — so concurrent ``checkpoint_all`` calls serialize instead
        of silently tearing each other's snapshots. Recovery: if a
        coordinator dies between pause and resume, an operator (or
        supervisor) sends ``phase="resume", force=True`` — the one
        deliberate override of the token, so a lost token can never wedge
        the fleet permanently. (A service ``stop()`` also unwedges: its
        draining flag wakes paused pushes into refusal.)"""
        import os

        phase = extra.get("phase", "save")
        if phase == "pause":
            with self._engine._lock:
                token = self._ckpt_issue_token()
                if token is None:
                    return tv.encode(tv.ERR, worker, None,
                                     extra={"error": self._ckpt_busy_error()})
                self._paused = True
                # paused: every push must reach the pump and PARK there
                # (cross-shard snapshot atomicity) — drop the native
                # admission mirror until resume reseeds it
                self._admit_drop()
                applied = {str(w): n for w, n in self._applied.items()}
            return tv.encode(tv.OK, worker, None, extra={
                "version": self._engine.version, "applied": applied,
                "token": token,
            })
        if phase == "resume" and extra.get("force"):
            # operator escape hatch: recover a fleet whose coordinator died
            # holding the token (documented above); never used by the
            # normal checkpoint_all protocol
            with self._engine._lock:
                self._paused = False
                self._ckpt_clear_token()
                self._admit_sync(locked=True)  # pause over: reseed
                self._pause_cond.notify_all()
            return tv.encode(tv.OK, worker, None,
                             extra={"version": self._engine.version,
                                    "forced": True})  # pslint: disable=PSL203 -- operator-recovery receipt: marks the reply of a force-resume so drills/operators can tell it from a normal resume
        err = self._ckpt_token_error(phase, extra)
        if err is not None:
            # covers both a foreign coordinator racing a live checkpoint
            # (wrong/absent token) and a straggler phase after resume
            return tv.encode(tv.ERR, worker, None, extra={"error": err})
        if phase == "drain_to":
            # admit blocked/in-flight pushes until every worker reaches its
            # cross-shard target, then report back. TCP delivery of an
            # already-fanned-out push is guaranteed, so the wait terminates;
            # the deadline guards a worker that died mid-fanout, and a
            # concurrent stop() aborts the wait (draining refuses pushes,
            # so the targets can never be reached once it is set).
            import time as _time

            targets = {int(w): int(n) for w, n in extra["targets"].items()}
            deadline = _time.monotonic() + float(
                extra.get("timeout", DRAIN_TO_TIMEOUT_S))
            with self._engine._lock:
                self._drain_targets = targets
                self._pause_cond.notify_all()
                while any(self._applied.get(w, 0) < n
                          for w, n in targets.items()):
                    left = deadline - _time.monotonic()
                    if left <= 0 or self._draining:
                        self._drain_targets = {}
                        return tv.encode(tv.ERR, worker, None, extra={
                            "error": ("drain_to aborted: server draining"
                                      if self._draining else
                                      "drain_to timed out: a worker's "
                                      "in-flight push never arrived"),
                        })
                    self._pause_cond.wait(left)
                self._drain_targets = {}
            return tv.encode(tv.OK, worker, None,
                             extra={"version": self._engine.version})
        if phase == "resume":
            with self._engine._lock:
                self._paused = False
                self._ckpt_clear_token()
                self._admit_sync(locked=True)  # pause over: reseed the
                # admission mirror from the drained ledger
                self._pause_cond.notify_all()
            return tv.encode(tv.OK, worker, None,
                             extra={"version": self._engine.version})
        base = resolve_ckpt_dir(self._ckpt_root, extra["dir"])
        path = (base if self.num_shards is None
                else os.path.join(base, f"shard{self.shard}"))
        with self._engine._lock:
            self._store.save(path)
            version = self._engine.version
        return tv.encode(tv.OK, worker, None,
                         extra={"version": version, "path": path})  # pslint: disable=PSL203 -- save receipt: echoes the resolved server-side path (ckpt_root may have rewritten it) for operators reading the reply in drills/logs

    # -- live key-range migration (ps_tpu/elastic) ----------------------------

    def _migrate_out(self, worker: int, extra: dict) -> bytes:
        """DONOR: stream ``extra["keys"]`` to the target shard and cut
        over (the coordinator's MIGRATE_OUT command; this serve thread —
        the coordinator's connection — drives the whole move while the
        other serve threads keep taking worker traffic).

        Three phases: (1) snapshot rows are published UNDER the apply
        lock, atomically with arming the double-write set, so row order
        is engine order from the first row; (2) live catch-up outside the
        lock — traffic flows, commits touching moving keys re-publish
        them; (3) a bounded stop-and-copy cutover: freeze applies, drain
        the residual window, MIGRATE_COMMIT (the recipient starts
        serving), evict, release. Failure before the commit aborts with
        the donor intact."""
        from ps_tpu.backends.common import parse_replica_uri
        from ps_tpu.elastic.migrate import (
            MigrationError,
            MigrationSession,
            encode_row,
        )

        keys = sorted(str(k) for k in extra["keys"])
        target = str(extra["target"])
        new_epoch = int(extra["table_epoch"])
        # idempotent re-ask: the coordinator repeats MIGRATE_OUT when the
        # reply died on the wire — if this exact move already committed
        # here, ack with the recorded receipt instead of re-running (the
        # keys are gone; a re-run would only confuse the recipient). The
        # receipt is valid ONLY while the keys are still gone: once a
        # later rebalance moves them back, an identical move request is
        # a genuinely new move, not a replay.
        done = self._migrate_out_done
        if (done is not None and done["keys"] == keys
                and done["target"] == target
                and not any(k in self._key_order for k in keys)):
            return tv.encode(tv.OK, worker, None, extra=done["reply"])
        engine = self._engine
        if not hasattr(engine, "export_keys"):
            raise RuntimeError(
                "this service's engine does not support live key "
                "migration (needs export_keys/adopt_key/evict_keys)"
            )
        if not keys:
            raise ValueError("MIGRATE_OUT with no keys")
        repl = self._backup_session
        if repl is not None and not repl.degraded:
            raise RuntimeError(
                "this shard is replicating to a backup — a live key "
                "migration would drift the replica stream's key range; "
                "detach the backup, move, then re-seed and re-attach it"
            )
        host, port = parse_replica_uri(target)[0][0]
        t0 = time.monotonic()
        begin = {"kind": "dense", "keys": keys,
                 "num_workers": engine.num_workers,
                 "table_epoch": new_epoch}
        # window sized so the full snapshot enqueues without blocking the
        # apply lock — backpressure is for the DOUBLE-WRITE phase
        session = MigrationSession(host, port, begin, stats=self.transport,
                                   window=max(64, 2 * len(keys)))
        committed = False
        try:
            with engine._lock:
                if self._migrating:
                    raise RuntimeError(
                        "a migration is already in flight at this shard")
                missing = [k for k in keys if k not in self._key_order]
                if missing:
                    raise KeyError(
                        f"donor does not own {missing[:3]} — the "
                        f"coordinator's table is ahead of this shard")
                rows = engine.export_keys(keys)
                for k in keys:
                    r = rows[k]
                    tensors, meta = encode_row(k, r["param"], r["state"],
                                               r["stale"],
                                               r["apply_count"])
                    session.publish_row(k, tensors, meta)  # pslint: disable=PSL101 -- the snapshot MUST enqueue under the apply lock (atomic with arming the double-write set, so row order is engine order); the window is sized to the snapshot so this never blocks
                self._migrating = frozenset(keys)
                self._migrate_session = session
            # phase 2: live catch-up — the lock is free, traffic flows
            if not session.wait_drained():
                raise MigrationError(
                    f"recipient never caught up: {session.log.death_reason}")
            # phase 3: bounded stop-and-copy. Holding the apply lock
            # across the residual drain + one commit round trip IS the
            # design: it is the worker-visible p99 disturbance the
            # rebalance bench bounds, and it is what makes the cutover
            # atomic (no push can land between the last row and the
            # ownership flip).
            with engine._lock:
                if not session.wait_drained():  # pslint: disable=PSL101 -- the cutover freeze: residual-window drain under the apply lock is the bounded stop-and-copy (stall_timeout aborts a stalled recipient instead of wedging the shard)
                    raise MigrationError(
                        "recipient stalled during the cutover freeze")
                session.quiesce()
                gone = set(keys)
                # per-KEY dedup tokens travel with their keys: the moved
                # rows' apply history is what the recipient needs to ack
                # a replayed pre-move push without re-applying — and
                # nothing else (this shard's remaining keys keep their
                # tokens here)
                tokens = {}
                for w, toks in self._applied_pseq.items():
                    moved = {k: [t[0], t[1]] for k, t in toks.items()
                             if k in gone}
                    if moved:
                        tokens[str(w)] = moved
                applied = {str(w): n for w, n in self._applied.items()}
                session.commit({  # pslint: disable=PSL101 -- the cutover commit round trip must be atomic with the ownership flip the lock protects (connect/stall timeouts bound it); releasing the lock first would let a push land at the donor AFTER the recipient started serving
                    "table_epoch": new_epoch, "tokens": tokens,
                    "applied": applied, "keys": keys,
                })
                engine.evict_keys(keys)
                self._invalidate_reads()  # the moved range left this shard:
                # a cached whole-subtree reply would still include it
                self._birth = freshness.birth_record()  # servable bytes
                # changed shape: the stamp must not predate the cutover
                # only NOW does this shard refuse the moved range
                # retryably: an aborted move must leave a static
                # deployment's hard key-mismatch diagnosis untouched
                self._elastic = True
                # the moved keys' authoritative tokens now live at the
                # recipient; a leftover here would go stale and merge
                # wrongly if the keys ever move back
                for toks in self._applied_pseq.values():
                    for k in gone.intersection(toks):
                        del toks[k]
                self._key_order = [k for k in self._key_order
                                   if k not in gone]
                now_moved = dict(self._moved_keys)
                now_moved.update({k: new_epoch for k in keys})
                self._moved_keys = now_moved
                self.table_epoch = max(self.table_epoch, new_epoch)
                # the key range (and the per-key token folds over it)
                # changed shape: structural reseed, still under the
                # cutover's lock hold so no frame sees a half-moved mirror
                self._admit_sync(locked=True)
                committed = True
        finally:
            with engine._lock:
                self._migrating = frozenset()
                self._migrate_session = None
            if committed:
                session.close()
            else:
                session.abort()
        dt = time.monotonic() - t0
        logging.getLogger(__name__).info(
            "migrated %d key(s) to %s in %.2fs (%d row(s), %.1f MB, "
            "table epoch %d)", len(keys), target, dt, session.rows_sent,
            session.bytes_sent / 1e6, new_epoch,
        )
        extra = {
            "keys": keys, "rows": session.rows_sent,
            "bytes": session.bytes_sent, "seconds": round(dt, 4),
            "table_epoch": new_epoch,
        }
        self._migrate_out_done = {"keys": keys, "target": target,
                                  "reply": extra}
        return tv.encode(tv.OK, worker, None, extra=extra)

    def _migrate_begin(self, worker: int, extra: dict) -> bytes:
        """RECIPIENT: open the intake — validate the declared range and
        stage it; rows only touch the engine at MIGRATE_COMMIT."""
        if not hasattr(self._engine, "adopt_key"):
            return tv.encode(tv.ERR, worker, None, extra={
                "error": "this service's engine cannot adopt migrated "
                         "keys"})
        if extra.get("kind") != "dense":
            return tv.encode(tv.ERR, worker, None, extra={
                "error": f"migration stream kind {extra.get('kind')!r} "
                         f"does not match this dense service"})
        repl = self._backup_session
        if repl is not None and not repl.degraded:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": "this shard is replicating to a backup — "
                         "adopting keys would drift the replica "
                         "stream's key range"})
        keys = set(str(k) for k in extra.get("keys") or [])
        if not keys:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": "MIGRATE_BEGIN with no keys"})
        nw = extra.get("num_workers")
        if nw is not None and int(nw) != self._engine.num_workers:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": f"donor says num_workers={nw}, this service "
                         f"runs {self._engine.num_workers}"})
        overlap = keys & set(self._key_order)
        if overlap:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": f"this shard already owns {sorted(overlap)[:3]}"})
        with self._stage_lock:
            if self._migrate_in is not None:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": "a migration intake is already staged here"})
            self._migrate_in = {"keys": keys, "rows": {}, "seq": 0}
        return tv.encode(tv.OK, worker, None, extra={"applied_seq": 0})

    def _migrate_row(self, worker: int, tensors, extra) -> bytes:
        """RECIPIENT: stage one sequenced row (later rows for a key
        supersede earlier — the donor's double-write catch-up)."""
        from ps_tpu.elastic.migrate import decode_row

        seq = int(extra["seq"])
        # decode (multi-MB array copies) OUTSIDE _stage_lock: the
        # recipient is a LIVE serving shard and every worker's bucket
        # staging serializes on that lock — only the seq check and the
        # dict store need it (rows arrive from one sender thread anyway)
        row = decode_row(tensors, extra)
        with self._stage_lock:
            stage = self._migrate_in
            if stage is None:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": "MIGRATE_ROW before MIGRATE_BEGIN"})
            if seq != stage["seq"] + 1:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": f"migration gap: expected seq "
                             f"{stage['seq'] + 1}, got {seq}"})
            if row["key"] not in stage["keys"]:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": f"row for {row['key']!r} outside the "
                             f"declared range"})
            stage["rows"][row["key"]] = row
            stage["seq"] = seq
        return tv.encode(tv.OK, worker, None, extra={"applied_seq": seq})

    def _migrate_commit(self, worker: int, extra: dict) -> bytes:
        """RECIPIENT: the cutover — install every staged row into the
        engine, extend the served key range, and merge the donor's dedup
        tokens (exactly-once across the handoff: a push the donor applied
        and the worker replays here is acked without re-applying), all
        under ONE apply-lock hold."""
        with self._stage_lock:
            stage = self._migrate_in
        if stage is None:
            # idempotent replay: the donor re-asks when the commit REPLY
            # died on the wire — if this exact range already committed
            # here, ack again instead of letting the donor "abort" a
            # move the recipient is already serving (dual ownership)
            asked = sorted(str(k) for k in (extra.get("keys") or []))
            done = self._migrate_committed
            if asked and done is not None and asked == done["keys"]:
                return tv.encode(tv.OK, worker, None, extra={
                    "keys": done["keys"],
                    "table_epoch": done["table_epoch"],
                })
            return tv.encode(tv.ERR, worker, None, extra={
                "error": "MIGRATE_COMMIT without a staged intake"})
        missing = sorted(stage["keys"] - set(stage["rows"]))
        if missing:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": f"commit refused: keys never streamed "
                         f"{missing[:3]}"})
        new_epoch = int(extra.get("table_epoch", 0))
        with self._engine._lock:
            for k in sorted(stage["rows"]):
                r = stage["rows"][k]
                self._engine.adopt_key(k, r["param"], r["state"],
                                       r["stale"], r["apply_count"])
            self._key_order = sorted(self._key_order
                                     + sorted(stage["rows"]))
            for w_str, toks in (extra.get("tokens") or {}).items():
                w = int(w_str)
                mine = self._applied_pseq.setdefault(w, {})
                for k, t in toks.items():
                    # unconditional per-key replace: the donor owned the
                    # key, so its token IS the key's whole apply history
                    mine[k] = (t[0], int(t[1]))
            for w_str, n in (extra.get("applied") or {}).items():
                w = int(w_str)
                self._applied[w] = max(self._applied.get(w, 0), int(n))
            self.table_epoch = max(self.table_epoch, new_epoch)
            self._invalidate_reads()  # the served subtree just grew
            self._birth = freshness.birth_record()
            # serving adopted keys means refusing their OLD routing
            # retryably from now on (and remembering the commit so a
            # re-asked MIGRATE_COMMIT acks instead of "aborting" it)
            self._elastic = True
            # the key range grew and the donor's tokens merged in: the
            # per-worker ledger folds are stale — structural reseed
            self._admit_sync(locked=True)
        with self._stage_lock:
            self._migrate_in = None
            self._migrate_committed = {
                "keys": sorted(stage["rows"]),
                "table_epoch": self.table_epoch,
            }
        logging.getLogger(__name__).info(
            "adopted %d migrated key(s) (table epoch %d); now serving "
            "%d key(s)", len(stage["rows"]), self.table_epoch,
            len(self._key_order),
        )
        return tv.encode(tv.OK, worker, None, extra={
            "keys": sorted(stage["rows"]), "table_epoch": self.table_epoch,
        })

    def _migrate_abort(self, worker: int) -> bytes:
        """RECIPIENT: discard the staged range (the donor keeps serving;
        nothing here ever reached the engine)."""
        with self._stage_lock:
            self._migrate_in = None
        return tv.encode(tv.OK, worker, None)

    def _set_draining(self) -> None:
        with self._engine._lock:
            self._draining = True
            self._pause_cond.notify_all()  # paused pushes wake into refusal
        self._invalidate_reads()  # no native hit may outlive the drain
        self._admit_drop()  # nor any native push ack/refusal: the pump's
        # draining refusal is the only correct answer now

    def stop(self, grace: float = 10.0) -> None:
        m = self._coord_member
        if m is not None:
            m.close(goodbye=True)  # clean leave: the membership view
            # shows 'left', never an eventual 'dead'
        super().stop(grace=grace)

    def kill(self) -> None:
        m = self._coord_member
        if m is not None:
            m.close(goodbye=False)  # SIGKILL-equivalent: beats just stop
        super().kill()

    # -- shard replication hooks (ps_tpu/replica) -----------------------------

    def _reseed_backup(self, worker: int, extra: dict) -> bytes:
        """RESEED (coordinator/operator → this PRIMARY): restore
        redundancy after a failover or backup death consumed the replica
        stream. Quiesce applies — the engine lock is re-entrant, so the
        export, the one-frame ``REPLICA_SEED`` install at the spare, and
        the re-attach are ONE hold: the spare receives EXACTLY the state
        point the new stream continues from (the same quiesce contract
        :meth:`attach_backup` documents, driven by a machine). Ships
        every row (param + optimizer state + stale snapshots), the
        engine meta, and the per-key exactly-once ledger — promotion off
        the re-seeded backup dedups a replay exactly like the original
        pair would have."""
        from ps_tpu.elastic.migrate import encode_row

        spare = str(extra.get("spare") or "")
        if ":" not in spare:
            return tv.encode(tv.ERR, worker, None, extra={
                "error": "reseed needs spare \"host:port\""})
        if self.role != "primary":
            return tv.encode(tv.ERR, worker, None, extra={
                "error": f"only a primary re-seeds (role={self.role})"})
        shost, sport = spare.rsplit(":", 1)
        t0 = time.monotonic()
        with self._engine._lock:
            old = self._backup_session
            if old is not None and not old.degraded:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": "a live backup session is already attached"})
            tensors: Dict[str, np.ndarray] = {}
            rows_extra = []
            rows = self._engine.export_keys(self._key_order)
            for i, k in enumerate(self._key_order):
                r = rows[k]
                t, e = encode_row(k, r["param"], r["state"], r["stale"],
                                  r["apply_count"])
                for name, arr in t.items():
                    tensors[f"{i}/{name}"] = np.asarray(arr)
                rows_extra.append(e)
            frame = tv.encode(tv.REPLICA_SEED, 0, tensors, extra={
                "kind": "dense",
                "keys": self._key_order,
                "shard": self.shard, "num_shards": self.num_shards,
                "rows": rows_extra,
                "meta": self._engine._checkpoint_meta(),
                "applied": {str(w): int(n)
                            for w, n in self._applied.items()},
                "tokens": {str(w): {k: [tk[0], int(tk[1])]
                                    for k, tk in toks.items()}
                           for w, toks in self._applied_pseq.items()},
            })
            nbytes = len(frame)
            ch = tv.Channel.connect(shost, int(sport))  # pslint: disable=PSL101 -- deliberate quiesce: the seed frame MUST ship while applies are frozen (the spare installs the exact state point the re-attached stream continues from); a dead spare fails the connect, not the primary
            try:
                k2, _, _, rep = tv.decode(ch.request(frame))  # pslint: disable=PSL101 -- same quiesce hold as the connect above; bounded by the channel timeout
            finally:
                ch.close()
            if k2 != tv.OK:
                return tv.encode(tv.ERR, worker, None, extra={
                    "error": f"spare refused seed: {rep.get('error')}"})
            self.attach_backup(shost, int(sport),  # pslint: disable=PSL101 -- same quiesce hold: the REPLICA_HELLO must validate against EXACTLY the state point the seed installed, so no apply may land between seed and attach (the lock is re-entrant by design)
                               ack=str(extra.get("ack", "sync")))
        dt = time.monotonic() - t0
        obs.record_event("reseed", spare=spare, keys=len(rows_extra),
                         bytes=nbytes, seconds=round(dt, 4))
        logging.getLogger(__name__).warning(
            "re-seeded backup at %s: %d key(s), %.1f MB in %.2fs "
            "(redundancy restored)", spare, len(rows_extra),
            nbytes / 1e6, dt)
        return tv.encode(tv.OK, worker, None, extra={
            "keys": len(rows_extra), "bytes": nbytes,
            "seconds": round(dt, 4)})

    def _replica_seed(self, worker: int, tensors, extra):
        """REPLICA_SEED (re-seeding primary → this EMPTY backup):
        install the shipped state point wholesale — rows, engine meta,
        and the exactly-once ledger — so the REPLICA_HELLO that follows
        validates against an exact copy. Refused once a stream is
        attached: a seed is how a spare BECOMES a backup, never a way to
        rewrite a live one."""
        from ps_tpu.elastic.migrate import decode_row

        if extra.get("kind") != "dense":
            return (f"seed kind {extra.get('kind')!r} does not match "
                    f"this dense service")
        meta = dict(extra.get("meta") or {})
        if int(meta.get("num_workers", self._engine.num_workers)) \
                != self._engine.num_workers:
            return (f"seed is for num_workers={meta.get('num_workers')}, "
                    f"this spare runs {self._engine.num_workers} — "
                    f"staleness semantics would differ")
        per: Dict[int, dict] = {}
        for name, v in (tensors or {}).items():
            i, _, rest = name.partition("/")
            per.setdefault(int(i), {})[rest] = v
        rows_extra = list(extra.get("rows") or [])
        with self._engine._lock:
            if self.role != "backup":
                return f"only a backup accepts a seed (role={self.role})"
            if self._replica_attached:
                return ("seed refused: a replication stream is already "
                        "attached")
            booted = sorted(self._engine._params)
            if booted:
                # whatever this spare booted with is placeholder state;
                # the seed IS the state point
                self._engine.evict_keys(booted)
            keys = []
            for i, re_ in enumerate(rows_extra):
                row = decode_row(per.get(i, {}), re_)
                self._engine.adopt_key(row["key"], row["param"],
                                      row["state"], row["stale"],
                                      row["apply_count"])
                keys.append(row["key"])
            self._key_order = sorted(keys)
            self.shard = extra.get("shard")
            self.num_shards = extra.get("num_shards")
            self._engine._load_checkpoint_meta(meta)
            self._applied = {int(w): int(n) for w, n
                             in (extra.get("applied") or {}).items()}
            self._applied_pseq = {
                int(w): {k: (tk[0], int(tk[1])) for k, tk in toks.items()}
                for w, toks in (extra.get("tokens") or {}).items()}
            self._invalidate_reads()
            self._birth = freshness.birth_record()
            self._admit_sync(locked=True)
        obs.record_event("replica_seeded", keys=len(keys),
                         version=self._engine.version)
        logging.getLogger(__name__).info(
            "seeded as backup: %d key(s) at version %d", len(keys),
            self._engine.version)
        return None

    def _service_lock(self):
        return self._engine._lock

    def _replica_hello_extra(self) -> dict:
        return {
            "kind": "dense",
            "keys": self._key_order,
            "shard": self.shard,
            "num_shards": self.num_shards,
            "version": self._engine.version,
            "start_seq": 0,
        }

    def _replica_validate(self, extra: dict) -> Optional[str]:
        if extra.get("kind") != "dense":
            return (f"replication stream kind {extra.get('kind')!r} does "
                    f"not match this dense service")
        if sorted(extra.get("keys") or []) != sorted(self._key_order):
            return "primary and backup disagree on the key range"
        if (extra.get("shard"), extra.get("num_shards")) \
                != (self.shard, self.num_shards):
            return (f"primary is shard {extra.get('shard')}/"
                    f"{extra.get('num_shards')}, backup is shard "
                    f"{self.shard}/{self.num_shards}")
        if int(extra.get("version", -1)) != self._engine.version:
            return (f"state-point mismatch: primary at version "
                    f"{extra.get('version')}, backup at "
                    f"{self._engine.version} — a deltas-only stream cannot "
                    f"catch up past missed commits; start the pair from the "
                    f"same initial params or a common checkpoint")
        return None

    def _replica_apply(self, op: str, worker: int, tensors, extra) -> None:
        # engine lock HELD by the dispatcher: apply inline, never through
        # _apply_push (which re-acquires it)
        if op == "pull":
            self._engine.pull_tree(worker=worker)
            with self._log_lock:
                self.event_log.append(["pull", worker])
            return
        if op not in ("push", "push_sub"):
            raise ValueError(f"unknown replica op {op!r}")
        tree = decode_tree(dict(tensors), extra.get("enc"),
                           stats=self.transport)
        # own-memory copies: the entry's arrays view the request frame,
        # and the engine keeps references past its lifetime
        tree = {k: np.array(v) for k, v in tree.items()}
        if op == "push_sub":
            # the primary's PARTIAL apply (a replay straddling a range
            # move owed only its adopted keys): mirror exactly that
            # subset — the whole-tree check would refuse it as torn
            missing = [k for k in tree if k not in self._key_order]
            if missing:
                raise KeyError(
                    f"replica push_sub keys outside the tree: "
                    f"{missing[:3]}")
            self._engine.push_subtree(tree, worker=worker)
        else:
            if sorted(tree) != sorted(self._key_order):
                raise KeyError("replica push keys do not match the tree")
            self._engine.push_tree(tree, worker=worker)
        # a backup serves replica READs: its cached replies go stale on
        # every replicated apply exactly like a primary's on a commit
        self._invalidate_reads()
        # install the PRIMARY's birth from the stream meta (foreign: the
        # wall stamp crosses processes, the monotonic clock does not) so
        # replica-served reads report the true push->now age, not the
        # replication hop's arrival time
        b = extra.get("birth")
        self._birth = (freshness.foreign_record(float(b)) if b is not None
                       else freshness.birth_record())
        self._applied[worker] = self._applied.get(worker, 0) + 1
        if extra.get("pseq") is not None:
            toks = self._applied_pseq.setdefault(worker, {})
            for k in tree:
                toks[k] = (extra.get("pnonce"), int(extra["pseq"]))
        # merged pushes replicate their constituent tokens too, so a
        # promoted backup suppresses a degraded member's replay exactly
        # like its dead primary would have
        self._record_members(extra.get("members"), tree)
        with self._log_lock:
            self.apply_log.append(worker)
            self.event_log.append([op, worker])


def serve_async(store, port: int = 0, bind: str = "127.0.0.1",
                shard: Optional[int] = None,
                num_shards: Optional[int] = None,
                ckpt_root: Optional[str] = None,
                backup: bool = False,
                native_loop: Optional[bool] = None,
                loop_threads: Optional[int] = None) -> "AsyncPSService":
    """Expose an initialized async KVStore to remote worker processes.

    The top-level entry of the cross-process async deployment: each server
    process calls this after ``store.init(...)``; workers connect with
    :func:`connect_async`. Returns the running service (``.port`` for
    ephemeral binds, ``.stop()`` to drain). ``bind`` defaults to loopback;
    pass "0.0.0.0" explicitly for a multi-host job (the endpoint is
    unauthenticated).

    Single-server mode: ``store.init(params)`` with the full tree, no shard
    args. Multi-server mode (the reference's N-server topology): server
    ``s`` of ``N`` runs ``store.init(shard_tree(params, s, N))`` and
    ``serve_async(store, ..., shard=s, num_shards=N)``. ``ckpt_root``
    confines CHECKPOINT saves under a server-side root (recommended for
    any non-loopback bind).

    Replication (README "Replication & failover"): ``backup=True`` starts
    the service in backup role — it refuses worker traffic and follows the
    primary's REPLICA stream until promoted; the primary side calls
    ``svc.attach_backup(host, port, ack=...)`` before admitting workers."""
    return AsyncPSService(store, port=port, bind=bind,
                          shard=shard, num_shards=num_shards,
                          ckpt_root=ckpt_root, backup=backup,
                          native_loop=native_loop,
                          loop_threads=loop_threads)


def connect_async(uri: Optional[str], worker: int, params_like,
                  bucket_bytes: Optional[int] = None,
                  pool_size: Optional[int] = None,
                  compress=None, writev: Optional[bool] = None,
                  shm: Optional[bool] = None,
                  shm_bytes: Optional[int] = None,
                  failover_timeout: Optional[float] = None,
                  coordinator=None,
                  aggregator: Optional[str] = None,
                  read_staleness: Optional[int] = None,
                  pull_cache: Optional[bool] = None) -> "RemoteAsyncWorker":
    """Join a cross-process async job as worker ``worker``.

    ``uri`` is ``host:port`` of the :func:`serve_async` process, or a
    comma-separated list ``h0:p0,h1:p1,...`` naming every server of an
    N-server partition (also the form trainers read from
    ``PS_ASYNC_SERVER_URI``); ``params_like`` is a pytree with the model's
    parameter structure (used to validate the key partition against the
    servers and to rebuild pulled params).

    Replica sets (README "Replication & failover"): each shard's entry may
    list its replicas separated by ``|``, primary first —
    ``"h0:p0|b0:q0,h1:p1|b1:q1"``. On a primary's death the worker retries
    against the set (waiting out the backup's promotion, bounded by
    ``failover_timeout`` seconds, env PS_FAILOVER_TIMEOUT_MS) and its
    (nonce, seq)-tagged pushes apply exactly once at the new primary.

    ``bucket_bytes`` switches the data plane to the bucketed, pipelined
    transport (~4 MiB fusion buckets striped over ``pool_size`` persistent
    connections per server; enables :meth:`RemoteAsyncWorker.
    push_pull_async` compute/comm overlap). None keeps the serial
    one-frame-per-cycle transport.

    ``compress`` selects a gradient codec for the wire (``ps_tpu.compress``):
    a codec name (``"cast16"``/``"int8"``/``"topk"``) or a spec dict such as
    ``{"codec": "topk", "topk": 0.02, "min_bytes": 65536, "pull": True}``
    (the env spelling is PS_COMPRESS / PS_COMPRESS_TOPK /
    PS_COMPRESS_MIN_BYTES / PS_COMPRESS_PULL). None/"none" ships raw
    float32 — the previous behavior.

    Transport lanes (README "Transport lanes"): ``writev`` (default on,
    env PS_WRITEV) sends each frame's tensor bytes as kernel scatter-
    gather iovecs of the live arrays — no staging copy; ``shm`` (default
    off, env PS_SHM) negotiates a same-host shared-memory ring lane per
    connection at connect time — ``shm_bytes`` (env PS_SHM_BYTES) sizes
    each ring — falling back to TCP whenever the peer is another host,
    the segments cannot be created, or the server refuses.

    Elastic membership (README "Elastic membership"): pass
    ``coordinator="host:port"`` (env PS_COORD_URI) INSTEAD of ``uri`` —
    the worker fetches the authoritative shard table from the
    coordinator (waiting until every server registered and the table
    covers this model's keys), dials the shards it names, and
    re-fetches + re-routes whenever a live rebalance moves keys under
    it — no worker restart, no global pause.

    Hierarchical aggregation (README "Two-tier aggregation"): pass
    ``aggregator="host:port"`` to route this worker's whole data plane
    through its host group's :class:`~ps_tpu.backends.aggregator.
    AggregatorService` — same-host pushes pre-reduce locally and cross
    the host boundary ONCE per group, pulls coalesce to one wire fetch
    per group per version. With a coordinator, the aggregator for this
    worker's host is discovered from the membership table automatically
    (aggregators register per host); if the aggregator later dies the
    worker degrades to the flat worker→shard topology without a restart
    and with its dedup identity intact."""
    table = None
    discovered = False
    if coordinator is not None:
        from ps_tpu.elastic.member import fetch_table

        want, _ = keymod.flatten_with_keys(params_like)
        view: dict = {}
        table = fetch_table(coordinator, cover=want, view_out=view)
        addrs, replica_sets = table.addrs(), table.replica_sets()
        if aggregator is None:
            import socket

            # coordinator-assigned grouping: same-host workers share the
            # aggregator registered under this host's name (none =
            # flat); the map rode the fetch_table poll — no second
            # coordinator round trip
            aggregator = (view.get("aggregators") or {}).get(
                socket.gethostname())
            discovered = aggregator is not None
    elif uri is None:
        raise ValueError("connect_async needs a server uri or a "
                         "coordinator address")
    else:
        addrs, replica_sets = parse_replica_uri(uri)

    def dial(agg):
        return RemoteAsyncWorker.connect_many(
            addrs, worker, params_like, bucket_bytes=bucket_bytes,
            pool_size=pool_size, compress=compress, writev=writev,
            shm=shm, shm_bytes=shm_bytes, replica_sets=replica_sets,
            failover_timeout=failover_timeout, coordinator=coordinator,
            table=table, aggregator=agg, read_staleness=read_staleness,
            pull_cache=pull_cache)

    if discovered:
        # the registry keeps a crashed aggregator's entry until a
        # replacement registers (aggregators own no keys, so membership
        # never reaps them) — a NEW worker on that host must join flat
        # instead of failing its connect against a dead URI. The cheap
        # probe (short retry budget, NOT Channel.connect's default ~15s
        # patience) keeps a stale entry from stalling every join on the
        # host; the except still covers an aggregator dying between the
        # probe and the real dial.
        ahost, aport = str(aggregator).rsplit(":", 1)
        from ps_tpu.config import env_float

        # validated service-level read (pslint PSL406): the probe's
        # sleep budget — previously a hardcoded 0.2 s invisible to the
        # operators who tune join-time failover
        probe_wait = env_float("PS_AGG_PROBE_MAX_WAIT_MS", 200.0,
                               lo=0.0) / 1e3
        try:
            probe = tv.Channel.connect(ahost, int(aport),
                                       timeout_ms=1000, retries=2,
                                       max_wait_s=probe_wait)
            probe.close()
        except (tv.VanError, OSError) as e:
            logging.getLogger(__name__).warning(
                "discovered aggregator %s is not answering (%s) — "
                "joining flat", aggregator, e)
            return dial(None)
        try:
            return dial(aggregator)
        except (ServerFailureError, tv.VanError, OSError) as e:
            logging.getLogger(__name__).warning(
                "discovered aggregator %s is not serving (%s) — "
                "joining flat", aggregator, e)
            return dial(None)
    return dial(aggregator)


class CheckpointRoundError(RuntimeError):
    """A checkpoint phase was refused by at least one server. ``oks`` holds
    the extras of the servers that DID accept the phase — a failed pause
    still hands the coordinator the tokens it needs to resume them."""

    def __init__(self, message: str, oks: Dict[int, dict]):
        super().__init__(message)
        self.oks = oks


class CheckpointRoundsMixin:
    """One phase of the coordinated checkpoint protocol, fanned to every
    server — shared by the dense and sparse workers (both expose
    ``_fanout``/``_chs``/``worker``). Raises :class:`CheckpointRoundError`
    on any non-OK reply, naming the phase and server (and carrying the
    successful servers' extras so cleanup can still target them).

    ``per_server`` merges server-specific fields (the checkpoint ownership
    token each server handed out at pause) into that server's payload.
    """

    def _checkpoint_round(self, payload_extra: dict,
                          per_server: Optional[Dict[int, dict]] = None
                          ) -> Dict[int, dict]:
        payloads = {}
        for i in range(len(self._chs)):
            extra = dict(payload_extra)
            if per_server and i in per_server:
                extra.update(per_server[i])
            payloads[i] = tv.encode(tv.CHECKPOINT, self.worker, None,
                                    extra=extra)
        msgs = self._fanout(payloads)
        out, errs = {}, {}
        for i, msg in msgs.items():
            kind, _, _, extra = tv.decode(msg)
            if kind != tv.OK:
                errs[i] = extra.get("error")
            else:
                out[i] = extra
        if errs:
            i, err = sorted(errs.items())[0]
            raise CheckpointRoundError(
                f"server {i} checkpoint {payload_extra.get('phase')} "
                f"failed: {err}", out
            )
        return out

    def _ckpt_tokens(self, paused: Dict[int, dict]) -> Dict[int, dict]:
        """Per-server ``{"token": ...}`` payload merge from pause replies."""
        return {i: {"token": x["token"]} for i, x in paused.items()
                if "token" in x}

    def checkpoint_resume_force(self) -> None:
        """Operator recovery: force-resume every server after a coordinator
        died between pause and resume (the lost token would otherwise block
        all pushes indefinitely). The one deliberate token override —
        never call it while a live checkpoint_all is saving."""
        self._checkpoint_round({"phase": "resume", "force": True})


class PendingCycle:
    """Handle for one background push→pull transport cycle.

    Returned by :meth:`RemoteAsyncWorker.push_pull_async`: the caller keeps
    computing (the next batch's forward, data loading, logging) while the
    cycle's buckets move in the background; :meth:`wait` blocks until the
    fresh params are in and returns them — the time actually spent blocked
    is what the overlap-efficiency metric charges against transport time.
    """

    def __init__(self, stats: Optional[TransportStats] = None):
        self._evt = threading.Event()
        self._params = None
        self._exc: Optional[BaseException] = None
        self._observed = False  # failure delivered via wait() at least once
        self._stats = stats

    def _resolve(self, params) -> None:
        self._params = params
        self._evt.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._evt.set()

    def done(self) -> bool:
        return self._evt.is_set()

    def wait(self, timeout: Optional[float] = None):
        """Block until the cycle lands; returns the freshly pulled params
        (or re-raises the cycle's transport failure)."""
        t0 = time.perf_counter()
        # flush_wait phase tag (ps_tpu/obs/breakdown.py): when a traced
        # span is open on THIS thread the wait becomes its child; always
        # lands in the blocked_s histogram either way (record_blocked)
        with obs.tracer().child("flush_wait", cat="worker"):
            done = self._evt.wait(timeout)
        if not done:
            raise TimeoutError("transport cycle still in flight")
        if self._stats is not None:
            self._stats.record_blocked(time.perf_counter() - t0)
        if self._exc is not None:
            self._observed = True  # surfaced once; flush() won't re-raise it
            raise self._exc
        return self._params


class RemoteAsyncWorker(BucketedTransportMixin, CheckpointRoundsMixin):
    """A worker NODE of the cross-process async PS.

    Computes gradients on this process's own jax devices against the params
    it last pulled (stale by whatever other workers pushed since), and
    exchanges per-owner subtrees with every server in one concurrent round
    per cycle. ``version`` sums the per-server subtree versions (each server
    counts whole-subtree applies to its own key range); per-server values
    are in ``versions``. A failed server connection raises
    :class:`ServerFailureError` naming the server.

    Transport: with ``bucket_bytes=None`` (default) each cycle is one
    monolithic frame per server (the serial path). With ``bucket_bytes``
    set, payloads are sliced into fixed-size fusion buckets
    (:class:`~ps_tpu.backends.common.BucketPlan`) striped over
    ``pool_size`` persistent connections per server, push/pull become
    pipelined (:meth:`push_pull_async` runs the whole cycle in the
    background while the caller computes), and :meth:`flush` is the
    barrier that restores serial semantics on demand. Either way the
    server applies whole trees atomically and records the same per-worker
    event order, so the math — and the staleness bound — is identical.
    """

    _failure_noun = "async PS server"

    def __init__(self, host: str, port: int, worker: int, params_like,
                 bucket_bytes: Optional[int] = None,
                 pool_size: Optional[int] = None,
                 compress=None, writev: Optional[bool] = None,
                 shm: Optional[bool] = None,
                 shm_bytes: Optional[int] = None,
                 replica_sets=None,
                 failover_timeout: Optional[float] = None,
                 read_staleness: Optional[int] = None,
                 pull_cache: Optional[bool] = None):
        self._init_multi([(host, int(port))], worker, params_like,
                         bucket_bytes=bucket_bytes, pool_size=pool_size,
                         compress=compress, writev=writev, shm=shm,
                         shm_bytes=shm_bytes, replica_sets=replica_sets,
                         failover_timeout=failover_timeout,
                         read_staleness=read_staleness,
                         pull_cache=pull_cache)

    @classmethod
    def connect_many(cls, addrs: Sequence[Tuple[str, int]], worker: int,
                     params_like, bucket_bytes: Optional[int] = None,
                     pool_size: Optional[int] = None,
                     compress=None, writev: Optional[bool] = None,
                     shm: Optional[bool] = None,
                     shm_bytes: Optional[int] = None,
                     replica_sets=None,
                     failover_timeout: Optional[float] = None,
                     coordinator=None, table=None,
                     aggregator: Optional[str] = None,
                     agg_role: bool = False,
                     read_staleness: Optional[int] = None,
                     pull_cache: Optional[bool] = None
                     ) -> "RemoteAsyncWorker":
        self = cls.__new__(cls)
        self._init_multi(list(addrs), worker, params_like,
                         bucket_bytes=bucket_bytes, pool_size=pool_size,
                         compress=compress, writev=writev, shm=shm,
                         shm_bytes=shm_bytes, replica_sets=replica_sets,
                         failover_timeout=failover_timeout,
                         coordinator=coordinator, table=table,
                         aggregator=aggregator, agg_role=agg_role,
                         read_staleness=read_staleness,
                         pull_cache=pull_cache)
        return self

    def _init_multi(self, addrs: List[Tuple[str, int]], worker: int,
                    params_like, bucket_bytes: Optional[int] = None,
                    pool_size: Optional[int] = None,
                    compress=None, writev: Optional[bool] = None,
                    shm: Optional[bool] = None,
                    shm_bytes: Optional[int] = None,
                    replica_sets=None,
                    failover_timeout: Optional[float] = None,
                    coordinator=None, table=None,
                    aggregator: Optional[str] = None,
                    agg_role: bool = False,
                    read_staleness: Optional[int] = None,
                    pull_cache: Optional[bool] = None) -> None:
        self.worker = worker
        # hierarchical two-level aggregation (backends/aggregator.py):
        # with an aggregator URI this worker dials ONLY its host group's
        # aggregator — a 1-shard topology advertising the whole tree —
        # and remembers the flat shard topology so an aggregator death
        # degrades the group back to flat worker→shard routing without a
        # restart (and without a new dedup identity: the replayed push
        # must still be recognized by shards that applied its merged
        # form). agg_role marks the AGGREGATOR'S OWN upstream client,
        # whose synthetic id lives outside [0, num_workers).
        self._agg_fallback = None
        self._agg_uri = aggregator
        if aggregator is not None:
            self._agg_fallback = {
                "addrs": [tuple(a) for a in addrs],
                "replica_sets": replica_sets,
                "table": table,
            }
            ahost, aport = str(aggregator).rsplit(":", 1)
            addrs = [(ahost, int(aport))]
            replica_sets = None
            table = None  # routing goes through the aggregator now
        self._agg_role = bool(agg_role)
        # elastic membership (ps_tpu/elastic): with a coordinator, the
        # shard table drives addrs/replica-sets and a stale-table refusal
        # re-fetches it (_on_table_moved) instead of failing the job
        self._coord = coordinator
        self._table = table
        # reconnect() re-runs _init_multi on a live instance: retire the
        # old telemetry reporter before (maybe) starting a fresh one
        old_rep = getattr(self, "_tel_reporter", None)
        if old_rep is not None:
            old_rep.close()
        self._tel_reporter = None
        kv, self._treedef = keymod.flatten_with_keys(params_like)
        # placeholders, not the arrays: reconnect() only needs keys +
        # structure, and pinning a BERT-size initial tree for the worker's
        # lifetime would double its memory
        self._kv_like = {k: True for k in kv}
        self._key_order = sorted(kv)
        self._addrs = addrs
        n = len(addrs)
        self._chs: List[tv.Channel] = []
        self._owner: Dict[str, int] = {}  # key -> index into addrs
        self.versions: List[int] = [0] * n
        self.num_workers: Optional[int] = None
        # REAL wire bytes (request payloads out / reply frames in) — the one
        # deployment where "push/pull GB/s" is physical bytes on a socket,
        # not collective algebra. Same counter surface as KVStore so
        # TrainMetrics reports it unchanged (VERDICT r4 item 6).
        self.bytes_pushed = 0   # request bytes sent (grads + protocol)
        self.bytes_pulled = 0   # reply bytes received (params + protocol)
        self.collective_bytes = 0  # no ICI on the van path, by definition
        self._bytes_lock = threading.Lock()  # _fanout drives _request concurrently
        # bucketed transport config (None bucket_bytes = serial transport)
        self._init_transport(bucket_bytes, pool_size, compress=compress,
                             writev=writev, shm=shm, shm_bytes=shm_bytes)
        # replica sets per shard + the promotion-wait budget (no-op with
        # singleton sets — the legacy topology)
        self._init_failover(replica_sets, failover_timeout)
        self._init_read_path(read_staleness, pull_cache)
        if self.compress and self.compress.get("pull") \
                and self.compress.get("codec") == "topk":
            raise ValueError(
                "topk cannot compress the pull return path: its error-"
                "feedback residuals live at the sender, and a server has "
                "no per-worker residual state — dropped params mass would "
                "be lost forever. Use cast16/int8 for pull compression."
            )
        try:
            self._connect_and_validate(addrs, worker, kv)
        except Exception:
            # a failed constructor can't be close()d: don't leak the
            # channels (and server serve threads) connected so far
            for ch in self._chs:
                ch.close()
            raise
        self._params = None
        # servers that own at least one key — the only ones worth a round trip
        self._active = sorted(set(self._owner.values()))
        self._pool = None
        if len(self._active) > 1:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=len(self._active)
            )
        if self.bucket_bytes is not None:
            try:
                self._open_pumps(self._active)
            except Exception:
                self._close_transport()
                for ch in self._chs:
                    ch.close()
                raise
        if coordinator is not None:
            # fleet telemetry (README "Fleet telemetry"): the worker's
            # op/flush/wire latency histograms are the per-step
            # breakdown's WORKER phases — ship them to the coordinator
            # too (no registration, no heartbeat: telemetry only).
            # Strictly additive: any failure leaves the data plane alone.
            from ps_tpu.config import env_flag

            if env_flag("PS_TELEMETRY", True):
                try:
                    from ps_tpu.elastic.member import TelemetryReporter
                    from ps_tpu.obs.collector import collect_telemetry

                    self._tel_reporter = TelemetryReporter(
                        coordinator, f"worker:{worker}",
                        # bind the CURRENT stats object at call time: a
                        # reconnect restores/re-points self.transport
                        lambda: collect_telemetry(self.transport))
                except Exception:
                    logging.getLogger(__name__).debug(
                        "worker telemetry reporter failed to start",
                        exc_info=True)

    def _connect_and_validate(self, addrs, worker, kv) -> None:
        n = len(addrs)
        for i in range(n):
            # dials the preferred address — or, with a replica set, the
            # member currently serving as primary (a worker may join a
            # shard mid-promotion)
            ch, extra = self._hello_any(i)
            host, port = self._addrs[i]
            self._chs.append(ch)
            self._epochs[i] = int(extra.get("epoch") or 0)
            skeys = sorted(extra["keys"])
            ns = extra.get("num_shards")
            if ns is not None:
                # the server knows its place in a partition: hold it to it
                if int(ns) != n:
                    raise ValueError(
                        f"server {i} ({host}:{port}) is shard "
                        f"{extra['shard']}/{ns} but this worker dialed "
                        f"{n} server(s)"
                    )
                expected = sorted(
                    k for k in self._key_order
                    if keymod.shard_for_key(k, n) == int(extra["shard"])
                )
                if skeys != expected:
                    raise ValueError(
                        f"server {i} key range does not match the "
                        f"shard_for_key assignment for shard {extra['shard']}"
                    )
            for k in skeys:
                if k not in kv:
                    raise ValueError(
                        f"server {i} owns key {k!r} absent from this "
                        f"worker's params structure"
                    )
                if k in self._owner:
                    raise ValueError(
                        f"key {k!r} claimed by servers "
                        f"{self._owner[k]} and {i}"
                    )
                self._owner[k] = i
            self.versions[i] = int(extra["version"])
            # the JOB's worker count (data-sharding denominator) is the
            # servers' truth, not a local guess — and must agree across them
            nw = int(extra["num_workers"])
            if self.num_workers is None:
                self.num_workers = nw
            elif nw != self.num_workers:
                raise ValueError(
                    f"servers disagree on num_workers ({self.num_workers} "
                    f"vs {nw} at server {i})"
                )
            # the topology checked out: offer the same-host shm lane for
            # this (serial/control) channel — fallback keeps plain TCP
            self._chs[i] = self._maybe_upgrade(ch)
        missing = [k for k in self._key_order if k not in self._owner]
        if missing:
            raise ValueError(f"no server owns keys {missing[:3]}"
                             f"{'...' if len(missing) > 3 else ''}")
        if not self._agg_role and not (0 <= worker < self.num_workers):
            raise ValueError(
                f"worker id {worker} out of range for a "
                f"{self.num_workers}-worker job"
            )

    def _validate_failover_hello(self, i: int, extra: dict) -> Optional[str]:
        """A promoted replica must advertise exactly the key range the
        worker validated for this shard at connect time."""
        expected = sorted(k for k, o in self._owner.items() if o == i)
        if sorted(extra.get("keys") or []) != expected:
            return (f"replica of server {i} advertises a different key "
                    f"range than the shard the worker validated")
        nw = extra.get("num_workers")
        if nw is not None and self.num_workers is not None \
                and int(nw) != self.num_workers:
            return (f"replica of server {i} says num_workers={nw}, "
                    f"job runs {self.num_workers}")
        return None

    # -- elastic membership: table re-route (ps_tpu/elastic) ------------------

    def _on_table_moved(self, err, deadline: float) -> None:
        """A shard refused with "key range moved" (or a pull came back
        short): fetch a shard table NEWER than the one this worker routes
        by and rebuild the transport against it. Bounded by the same
        failover deadline as replica re-routes; converges because every
        committed move eventually publishes a strictly higher epoch."""
        from ps_tpu.elastic.member import fetch_table

        if self._coord is None:
            super()._on_table_moved(err, deadline)  # raises: no recovery
        min_epoch = self._table.epoch if self._table is not None else None
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise TableMovedError(
                    f"shard table never converged before the failover "
                    f"deadline: {err}",
                    table_epoch=getattr(err, "table_epoch", 0)) from err
            try:
                table = fetch_table(self._coord, cover=self._key_order,
                                    min_epoch=min_epoch,
                                    timeout=min(budget, 10.0))
            except TimeoutError:
                # the coordinator's publish can lag the shard's refusal;
                # keep polling — the budget check above (not this one
                # fetch's slice of it) is the real deadline, and the
                # typed TableMovedError is the only way out
                continue
            try:
                self._adopt_table(table)
                return
            except (ValueError, tv.VanError, ServerFailureError):
                # the fetched table can race a shard's own cutover (its
                # HELLO briefly disagrees): wait for a newer epoch — or
                # just let the shards settle — and try again
                min_epoch = table.epoch - 1
                time.sleep(0.05)

    def _adopt_table(self, table) -> None:
        """Rebuild the whole transport (channels, owner map, replica
        sets, pumps) against a new shard table, preserving transport
        identity — cumulative counters, epoch streams, compressor
        residuals, and the dedup nonce — exactly like ``reconnect()``.
        No worker restart: the op that hit the refusal retries against
        the new routing as soon as this returns."""
        old_epoch = self._table.epoch if self._table is not None else None
        obs.record_event("table_reroute", worker=self.worker,
                         old_epoch=old_epoch, epoch=table.epoch,
                         shards=len(table.shards))
        self.transport.record_table_reroute()
        saved = self._saved_transport_state()
        # a table re-route is NOT a new worker incarnation: the op that
        # hit the refusal replays with its original (nonce, seq) token
        # right after this, and the shards that already applied it must
        # still recognize the replay. _init_multi mints a fresh nonce and
        # resets the seq counter (correct for a real reconnect — that IS
        # a new incarnation); here both must survive, or the replay
        # double-applies (unknown nonce) and every later push false-dedups
        # (seq restarts below the server's token).
        nonce, push_seq = self._transport_nonce, self._push_seq
        self._close_transport()
        for ch in self._chs:
            ch.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        try:
            self._init_multi(
                table.addrs(), self.worker,
                keymod.unflatten(self._treedef, self._kv_like,
                                 self._key_order),
                bucket_bytes=self.bucket_bytes, pool_size=self.pool_size,
                compress=self.compress, writev=self.writev, shm=self.shm,
                shm_bytes=self.shm_bytes,
                replica_sets=table.replica_sets(),
                failover_timeout=self.failover_timeout,
                coordinator=self._coord, table=table,
                read_staleness=self.read_staleness,
                pull_cache=self.pull_cache)
        finally:
            self._restore_transport_state(saved)
            self._transport_nonce, self._push_seq = nonce, push_seq
        logging.getLogger(__name__).warning(
            "worker %d re-routed to shard table epoch %d (%d shard(s))",
            self.worker, table.epoch, len(table.shards),
        )

    # -- hierarchical aggregation: degrade to the flat path -------------------

    def _on_server_lost(self, err: ServerFailureError,
                        deadline: float) -> None:
        """A shard failed with no replica to cycle to. When that "shard"
        is this host group's AGGREGATOR, the group degrades to the flat
        worker→shard topology it remembers from connect time — the
        PR 4/7 re-route shape: typed failure, rebuild, retry the op. The
        retried push replays under its ORIGINAL (nonce, seq) token, and
        shards that already applied its merged form recorded this
        member's constituent token, so the replay is acked without
        re-applying — no ledger violation in either direction.

        An ELASTIC worker (coordinator-connected) re-discovers the fleet
        instead of failing: poll the coordinator's table and re-adopt it
        until the slot serves again — the member was wedged or refusing
        and recovered, or a replacement (an autopilot re-seed, a restart)
        took its slot over — bounded by the same failover deadline. The
        re-adoption preserves the dedup nonce and push seq, so the op
        that hit the failure replays exactly-once."""
        if getattr(self, "_agg_fallback", None) is not None:
            self._degrade_to_flat(err)
            return
        if self._coord is None:
            raise err
        from ps_tpu.elastic.member import fetch_table

        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise err
            # back off before each poll: a refusing member is usually
            # mid-promotion / mid-recovery, and the rebuild below is a
            # full transport re-dial — not a thing to spin on
            time.sleep(min(0.25, budget))
            try:
                table = fetch_table(self._coord, cover=self._key_order,
                                    timeout=min(budget, 10.0))
                self._adopt_table(table)
                return
            except (TimeoutError, ValueError, tv.VanError,
                    ServerFailureError):
                # the slot still refuses (or the fetched table raced a
                # cutover) — keep polling; the budget check above is the
                # only way out, and it surfaces the ORIGINAL failure
                continue

    def _degrade_to_flat(self, cause: BaseException) -> None:
        """Rebuild the whole transport against the remembered flat shard
        topology, preserving transport identity — cumulative counters,
        epoch streams, compressor residuals, and CRUCIALLY the dedup
        nonce + push seq (a degrade is not a new incarnation: the op that
        hit the failure replays with its original token right after
        this)."""
        fb = self._agg_fallback
        obs.record_event("agg_degrade", worker=self.worker,
                         shards=len(fb["addrs"]), cause=repr(cause))
        self.transport.record_agg_degrade()
        saved = self._saved_transport_state()
        nonce, push_seq = self._transport_nonce, self._push_seq
        self._close_transport()
        for ch in self._chs:
            ch.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        try:
            self._init_multi(
                fb["addrs"], self.worker,
                keymod.unflatten(self._treedef, self._kv_like,
                                 self._key_order),
                bucket_bytes=self.bucket_bytes, pool_size=self.pool_size,
                compress=self.compress, writev=self.writev, shm=self.shm,
                shm_bytes=self.shm_bytes,
                replica_sets=fb["replica_sets"],
                failover_timeout=self.failover_timeout,
                coordinator=self._coord, table=fb["table"],
                read_staleness=self.read_staleness,
                pull_cache=self.pull_cache)
        finally:
            self._restore_transport_state(saved)
            self._transport_nonce, self._push_seq = nonce, push_seq
        logging.getLogger(__name__).warning(
            "worker %d: aggregator lost (%s) — degraded to the flat "
            "worker→shard path (%d shard(s))",
            self.worker, cause, len(self._addrs),
        )

    @property
    def version(self) -> int:
        """Total whole-subtree applies across all servers (single-server:
        exactly the server's version)."""
        return sum(self.versions)

    # -- protocol -------------------------------------------------------------

    def _request(self, i: int, payload):
        try:
            reply = request_payload(self._chs[i], payload)
        except tv.VanError as e:
            host, port = self._addrs[i]
            raise ServerFailureError(
                f"async PS server {i} ({host}:{port}) failed mid-job: {e}",
                server=i
            ) from e
        with self._bytes_lock:
            self.bytes_pushed += payload_nbytes(payload)
            self.bytes_pulled += len(reply)
        return reply

    def _fanout(self, payloads: Dict[int, bytes]) -> Dict[int, memoryview]:
        """One concurrent round: each server its request, all in flight
        together (the point of the partition — N servers apply in parallel).

        Every future is waited before any error propagates — abandoning a
        still-running request would leave a pool thread driving a channel
        that a later call (stats/close/retry) drives again from this thread,
        tearing the framed stream."""
        if self._pool is None or len(payloads) == 1:
            return {i: self._request(i, p) for i, p in payloads.items()}
        import concurrent.futures

        futs = {i: self._pool.submit(self._request, i, p)
                for i, p in payloads.items()}
        concurrent.futures.wait(futs.values())
        return {i: f.result() for i, f in futs.items()}

    def _merge_params(self, msgs: Dict[int, memoryview]) -> Any:
        import jax.numpy as jnp

        kv = {}
        for i, msg in msgs.items():
            kind, _, tensors, extra = tv.decode(msg)
            if kind != tv.OK:
                raise self._reply_error(i, extra)
            self.versions[i] = int(extra["version"])
            for k, v in tensors.items():
                kv[k] = jnp.asarray(np.array(v))
        missing = [k for k in self._key_order if k not in kv]
        if missing:
            raise self._incomplete_pull(missing)
        self._params = keymod.unflatten(self._treedef, kv, self._key_order)
        return self._params

    def _incomplete_pull(self, missing) -> BaseException:
        """A pull round that covered every dialed shard still came back
        short: on an elastic worker that means keys moved to a shard this
        worker is not dialing yet — re-fetch the table and re-pull
        (reads are idempotent). Static workers surface it hard."""
        if self._coord is not None:
            return TableMovedError(
                f"pull returned no value for {missing[:3]} — the shard "
                f"table moved during the pull")
        return RuntimeError(f"pull returned no value for {missing[:3]}")

    def _host_grads(self, grads) -> Dict[str, np.ndarray]:
        """Flatten one gradient pytree to host arrays ONCE per logical
        push; the owner split happens per attempt (``_split_kv``) because
        a table re-route between retries changes the split."""
        kv, _ = keymod.flatten_with_keys(grads)
        return {k: np.asarray(v) for k, v in kv.items()}

    def _split_kv(self, kv: Dict[str, np.ndarray]
                  ) -> Dict[int, Dict[str, np.ndarray]]:
        out: Dict[int, Dict[str, np.ndarray]] = {i: {} for i in self._active}
        for k, v in kv.items():
            out[self._owner[k]][k] = v
        return out

    def _split_by_owner(self, grads) -> Dict[int, Dict[str, np.ndarray]]:
        return self._split_kv(self._host_grads(grads))

    def pull_all(self) -> Any:
        """Fetch current params (each server records this worker's snapshot
        of its subtree)."""
        with self._op("pull") as sp:
            if self.bucket_bytes is not None:
                self.flush()
                return self._with_failover(
                    lambda: self._merge_host_params(
                        self._pull_buckets(tc=sp.wire())))
            extra = self._tc_extra(None, sp)
            return self._with_failover(
                lambda: self._merge_params(self._fanout({
                    i: tv.encode(tv.PULL, self.worker, None, extra=extra)
                    for i in self._active
                })))

    # -- high-QPS read path (README "Read path") ------------------------------

    def _init_read_path(self, read_staleness, pull_cache) -> None:
        """Worker half of the layered read path: dedicated read channels
        spread over each shard's replica set (bounded staleness, primary
        fallback), a local parameter cache invalidated by observed
        version bumps, and coalescing of concurrent same-shard reads
        into ONE wire fetch (the aggregator's ``_coalesced_pull``
        discipline, generalized to every worker)."""
        from ps_tpu.config import env_flag, env_float, env_int

        self._close_read_path()  # reconnect() re-runs _init_multi
        # freshness plane (README "Online serving & freshness"): the
        # staleness bound in SECONDS served ages are judged against
        # (the within-bound share is ps_top's age% column), and one
        # ClockSync per shard toward its PRIMARY — births are stamped
        # there, so its clock is the one cross-process ages resolve
        # against. Fed for free by the version watcher's REPLICA_STATE
        # round trips (the reply already carries the server's "now").
        self.freshness_slo = env_float("PS_FRESHNESS_SLO", 0.5, lo=1e-3)
        self._read_clock: Dict[int, Any] = {}
        # bounded-staleness contract, measured in VERSIONS: a replica
        # whose reply trails the worker's last-known primary version by
        # more than this many versions is refused and the read falls
        # back toward the primary. 0 (default) = replicas serve only
        # what is provably current.
        self.read_staleness = (env_int("PS_READ_STALENESS", 0, lo=0)
                               if read_staleness is None
                               else max(int(read_staleness), 0))
        # worker-side parameter cache: repeat reads at an unchanged
        # version cost no wire round trip; version bumps ride every
        # reply this worker already decodes (push acks, pulls, stats)
        # plus the REPLICA_STATE probe on the heartbeat cadence.
        self.pull_cache = (env_flag("PS_PULL_CACHE", False)
                           if pull_cache is None else bool(pull_cache))
        # revalidating cache: once a shard snapshot exists, refresh it
        # with a CONDITIONAL read — the server answers NOT_MODIFIED
        # (stamp only) when nothing changed since the snapshot, so a
        # version-lag signal costs a handshake-sized reply instead of a
        # full refetch. Off = every cache miss is a full READ.
        self.read_conditional = env_flag("PS_READ_CONDITIONAL", True)
        self._read_cv = threading.Condition()
        # in-flight fetch records, one per shard: waiters hold the RECORD
        # and read the result out of it, so sharing needs no global
        # retention — with the cache off, a snapshot dies with its last
        # reader instead of pinning a second model copy per shard
        import itertools

        self._read_fetching: Dict[int, dict] = {}
        self._read_snaps: Dict[int, dict] = {}  # pull_cache=True only
        # dead-member cooldown: an address whose dial/request just failed
        # is skipped by the rotation for a beat instead of costing every
        # read its full connect budget (the primary is never skipped —
        # it is the fallback of last resort)
        self._read_bad: Dict[tuple, float] = {}
        self._read_pool = None  # lazy fan-out executor (multi-shard)
        # GIL-atomic rotation counter: read_all is documented for
        # concurrent callers, and a bare int read-modify-write would
        # lose increments and skew the replica-set rotation
        self._read_rr = itertools.count()
        self._read_chs: Dict[tuple, tv.Channel] = {}
        self._watch_chs: Dict[int, tv.Channel] = {}
        self._read_watch = None
        self._read_watch_stop = threading.Event()

    def _close_read_path(self) -> None:
        stop = getattr(self, "_read_watch_stop", None)
        if stop is not None:
            stop.set()
        pool = getattr(self, "_read_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
            self._read_pool = None
        watch = getattr(self, "_read_watch", None)
        if watch is not None:
            # join BEFORE closing the watch channels: a watcher
            # mid-iteration could otherwise dial and store a fresh
            # channel after the close swept the dict — a leaked live
            # connection (the watcher owns ITS dict, so even a stuck
            # join cannot make it write into a successor's)
            watch.join(timeout=5)
        for ch in list(getattr(self, "_read_chs", {}).values()):
            ch.close()
        for ch in list(getattr(self, "_watch_chs", {}).values()):
            ch.close()
        self._read_chs = {}
        self._watch_chs = {}
        self._read_watch = None

    def read_all(self) -> Any:
        """Side-effect-free read of the current params — the SERVING
        pull. Unlike :meth:`pull_all` it records no pull event at the
        server (no DC stale snapshot, no replication entry), may be
        answered by a backup replica within ``read_staleness`` versions
        of the primary, is served from the native read cache with zero
        upcalls on repeat, and coalesces with concurrent callers: while
        one thread's wire fetch for a shard is in flight, other readers
        wait on THAT fetch instead of fanning identical requests. Does
        not touch the training-path params (:meth:`pull_all`'s snapshot
        is unaffected)."""
        return self.read_all_versioned()[0]

    def read_all_versioned(self) -> Tuple[Any, int]:
        """:meth:`read_all` plus the summed AS-SERVED shard versions of
        the returned bytes. Distinct from :attr:`version` (the highest
        versions this worker has OBSERVED): a replica serving within
        the staleness bound, or a concurrent writer decoding a newer
        ack mid-read, can make ``version`` exceed what these bytes
        actually are — a re-publisher (the aggregator's coalesced
        snapshot) must stamp the served version, never the known one,
        or downstream caches park stale bytes under a fresh stamp."""
        tree, version, _ = self.read_all_stamped()
        return tree, version

    def read_all_stamped(self) -> Tuple[Any, int, Optional[dict]]:
        """:meth:`read_all_versioned` plus the OLDEST birth record among
        the served shard snapshots (None when no shard carried a stamp).
        The oldest wins for the same reason the served version does: a
        re-publisher (the aggregator's coalesced snapshot) must stamp
        the age of its WORST constituent, or downstream readers
        under-report the staleness of merged bytes."""
        import jax.numpy as jnp

        with self._op("read"):
            kv: Dict[str, Any] = {}
            version = 0
            births: List[dict] = []
            if len(self._active) > 1:
                # fan the per-shard reads out concurrently, like
                # pull_all's _fanout — a serving read must not pay K
                # sequential round trips on a K-shard topology (the
                # per-shard coalescing makes the duplicate work of
                # concurrent callers collapse anyway)
                import concurrent.futures

                pool = self._read_executor()
                futs = {i: pool.submit(self._read_shard, i)
                        for i in self._active}
                concurrent.futures.wait(futs.values())
                snaps = [(i, f.result()) for i, f in futs.items()]
            else:
                snaps = [(i, self._read_shard(i)) for i in self._active]
            for _, snap in snaps:
                kv.update(snap["kv"])
                version += int(snap["version"])
                if snap.get("b") is not None:
                    births.append(snap["b"])
            missing = [k for k in self._key_order if k not in kv]
            if missing:
                raise self._incomplete_pull(missing)
            tree = keymod.unflatten(
                self._treedef, {k: jnp.asarray(v) for k, v in kv.items()},
                self._key_order)
            birth = (min(births, key=lambda b: b["birth"])
                     if births else None)
            return tree, version, birth

    def _read_executor(self):
        if self._read_pool is None:
            import concurrent.futures

            self._read_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=len(self._active),
                thread_name_prefix="ps-read")
        return self._read_pool

    def _read_fresh_enough(self, version: int, i: int) -> bool:
        return self.versions[i] - int(version) <= self.read_staleness

    def _note_read_age(self, i: int, snap: dict, tier: str) -> None:
        """One serve's data age into ``ps_read_staleness_seconds``:
        ``now - birth`` resolved against shard ``i``'s ClockSync offset
        when the birth crossed a process boundary (same-process births
        use the monotonic clock; no offset falls back to wall — the
        source rides the sample either way, and negative ages clamp)."""
        b = snap.get("b")
        if b is None:
            return  # pre-freshness peer: no stamp, no sample
        cs = self._read_clock.get(i)
        off = cs.offset_us if cs is not None else None
        age, src, clamped = freshness.age_of(b, off)
        self.transport.record_read_age(age, src=src, tier=tier,
                                       bound=self.freshness_slo,
                                       clamped=clamped)

    def _read_shard(self, i: int) -> dict:
        """One shard's read snapshot: local cache when its version is
        within the staleness bound of the last-known server version,
        else ONE coalesced wire fetch. A waiter sharing another caller's
        fetch applies the SAME freshness predicate as the cache hit — an
        apply ack observed while the fetch was in flight means its
        pre-apply snapshot is stale for this reader, who loops and
        refetches instead of violating the bound."""
        self._ensure_version_watch()
        while True:
            with self._read_cv:
                snap = self._read_snaps.get(i)
                if (snap is not None and self.pull_cache
                        and self._read_fresh_enough(snap["version"], i)):
                    self.transport.record_read_cache(True)
                    self._note_read_age(i, snap, "cache")
                    return snap
                rec = self._read_fetching.get(i)
                if rec is not None:
                    self._read_cv.wait(0.05)
                    got = rec.get("snap") if rec.get("done") else None
                    if got is not None \
                            and self._read_fresh_enough(got["version"], i):
                        # coalesced: share the fetch this caller waited
                        # out instead of issuing another
                        self.transport.record_read_coalesced()
                        self._note_read_age(i, got, "cache")
                        return got
                    continue
                rec = {"done": False, "snap": None}
                self._read_fetching[i] = rec
                break
        try:
            snap = self._read_fetch(i)
            with self._read_cv:
                rec["snap"] = snap
                if self.pull_cache:
                    self._read_snaps[i] = snap
            self._note_read_age(i, snap, snap.get("tier") or "wire")
            return snap
        finally:
            with self._read_cv:
                rec["done"] = True
                self._read_fetching.pop(i, None)
                self._read_cv.notify_all()

    def _read_fetch(self, i: int) -> dict:
        """One wire READ for shard ``i``, spread across its replica set:
        members are tried in rotating order; a non-primary whose version
        exceeds the staleness bound is refused (counted as a fallback)
        and the rotation continues — the primary always qualifies, so a
        healthy shard can never fail the bound."""
        self.transport.record_read_cache(False)
        # revalidation: with a prior snapshot in hand, tell the server
        # what we already have — an unchanged target answers
        # NOT_MODIFIED (stamp only) and we keep our bytes
        snap0 = None
        if self.pull_cache and self.read_conditional:
            with self._read_cv:
                snap0 = self._read_snaps.get(i)
        if snap0 is not None:
            payload = tv.encode(tv.READ, 0, None,
                                extra={"cond": int(snap0["version"])})
        else:
            payload = tv.encode(tv.READ, 0, None)
        members = self._replica_sets[i]
        primary = tuple(self._addrs[i])
        start = next(self._read_rr)
        now = time.monotonic()
        order = [tuple(members[(start + j) % len(members)])
                 for j in range(len(members))]
        # skip members in their failure cooldown (a blackholed replica
        # must not cost its rotation share a connect budget per read);
        # the primary is always tried
        order = [a for a in order
                 if a == primary or self._read_bad.get(a, 0.0) <= now]
        last: Optional[BaseException] = None
        for addr in order:
            try:
                ch = self._read_channel(i, addr)
                reply = ch.request(payload)
                kind, _, tensors, extra = tv.decode(reply)
            except (tv.VanError, OSError) as e:
                self._drop_read_channel(i, addr)
                self._read_bad[addr] = time.monotonic() + 2.0
                last = e
                continue
            self._read_bad.pop(addr, None)
            if kind == tv.NOT_MODIFIED and snap0 is not None:
                # our snapshot is current as of the server's stamp; the
                # BYTES we hold are at the snapshot version, which is at
                # least the stamp (the server only answers NOT_MODIFIED
                # when its version <= cond) — so the snapshot version is
                # what the staleness predicate must judge
                version = max(int(extra["version"]), int(snap0["version"]))
                if addr != primary \
                        and not self._read_fresh_enough(version, i):
                    # a lagging replica's NOT_MODIFIED is refused exactly
                    # like a lagging full reply would be — and the GAP is
                    # recorded, not just the fact (the bound's margin)
                    self.transport.record_read_fallback()
                    self.transport.record_read_gap(
                        self.versions[i] - version)
                    last = RuntimeError(
                        f"replica {addr} NOT_MODIFIED at version "
                        f"{version} exceeds the staleness bound "
                        f"({self.versions[i]} known, "
                        f"{self.read_staleness} allowed)")
                    continue
                if version > self.versions[i]:
                    self.versions[i] = version
                self.transport.record_read_route(replica=addr != primary)
                # an NM revalidation must REFRESH the age: the stamp's
                # birth describes the version we already hold — falling
                # back to the snapshot's older birth would over-report
                # the age of perfectly current bytes
                birth = freshness.from_extra(extra) or snap0.get("b")
                return {"version": version, "kv": snap0["kv"],
                        "b": birth, "tier": "nm"}
            if kind != tv.OK:
                last = RuntimeError(str(extra.get("error")))
                continue
            version = int(extra["version"])
            if addr != primary and not self._read_fresh_enough(version, i):
                # replica too far behind the bound: fall back toward the
                # primary (it is later in — or next around — the rotation)
                self.transport.record_read_fallback()
                self.transport.record_read_gap(self.versions[i] - version)
                last = RuntimeError(
                    f"replica {addr} at version {version} exceeds the "
                    f"staleness bound ({self.versions[i]} known, "
                    f"{self.read_staleness} allowed)")
                continue
            # own-memory copies: the reply frame dies with this scope
            kv = {k: np.array(v) for k, v in tensors.items()}
            if version > self.versions[i]:
                self.versions[i] = version
            self.transport.record_read_route(replica=addr != primary)
            return {"version": version, "kv": kv,
                    "b": freshness.from_extra(extra),
                    "tier": "replica" if addr != primary else "wire"}
        raise ServerFailureError(
            f"read failed at every member of {self._failure_noun} {i}'s "
            f"replica set {members}: {last}", server=i)

    def _read_channel(self, i: int, addr) -> tv.Channel:
        ch = self._read_chs.get((i, addr))
        if ch is None:
            # short budget: a dead replica must cost this read
            # milliseconds, not Channel.connect's boot patience
            ch = tv.Channel.connect(addr[0], addr[1], timeout_ms=2000,
                                    retries=2, max_wait_s=0.5)
            ch.stats = self.transport
            self._read_chs[(i, addr)] = ch
        return ch

    def _drop_read_channel(self, i: int, addr) -> None:
        ch = self._read_chs.pop((i, addr), None)
        if ch is not None:
            ch.close()

    def _ensure_version_watch(self) -> None:
        """Start the version watcher once, lazily, and only when the
        parameter cache is on: it polls each shard's REPLICA_STATE —
        the cheapest round trip every role answers — on the heartbeat
        cadence, so a pure reader learns of version bumps (and its
        cache invalidates) without ever issuing a full pull."""
        if not self.pull_cache or self._read_watch is not None:
            return
        with self._read_cv:
            if self._read_watch is not None:
                return
            # the watcher binds ITS OWN stop event and channel dict: a
            # reconnect's _init_read_path installs fresh ones, so a
            # lingering old watcher can never store into the successor's
            t = threading.Thread(
                target=self._version_watch,
                args=(self._read_watch_stop, self._watch_chs),
                daemon=True, name="ps-read-watch")
            self._read_watch = t
        t.start()

    def _version_watch(self, stop, chs) -> None:
        from ps_tpu.config import env_int

        # the existing heartbeat cadence IS the watch cadence: version
        # bumps piggyback on the same rhythm the failure detector beats at
        interval = env_int("PS_HEARTBEAT_INTERVAL_MS", 100, lo=1) / 1e3
        payload = tv.encode(tv.REPLICA_STATE, 0, None)
        bad: Dict[int, float] = {}  # re-dial cooldown per shard: one
        # dead shard must not stall the healthy shards' version probes
        # behind its connect timeout every cycle
        while not stop.wait(interval):
            for i in list(self._active):
                if stop.is_set():
                    return
                ch = chs.get(i)
                if ch is None and bad.get(i, 0.0) > time.monotonic():
                    continue
                try:
                    if ch is None:
                        host, port = self._addrs[i]
                        ch = tv.Channel.connect(host, port,
                                                timeout_ms=2000, retries=1,
                                                max_wait_s=0.2)
                        chs[i] = ch
                    t0 = time.time()
                    reply = ch.request(payload)
                    t1 = time.time()
                    kind, _, _, extra = tv.decode(reply)
                    v = extra.get("version")
                    if kind == tv.OK and v is not None \
                            and int(v) > self.versions[i]:
                        self.versions[i] = int(v)
                    if kind == tv.OK and extra.get("now") is not None:
                        # clock discipline for cross-process ages: every
                        # watch tick doubles as an NTP-style piggyback
                        # probe toward the shard's primary (zero added
                        # round trips — the reply carries "now" already)
                        cs = self._read_clock.get(i)
                        if cs is None:
                            from ps_tpu.obs.clock import ClockSync

                            cs = self._read_clock[i] = ClockSync()
                        cs.observe(t0, t1, float(extra["now"]))
                    bad.pop(i, None)
                except (tv.VanError, OSError, IndexError):
                    if ch is not None:
                        ch.close()
                    chs.pop(i, None)
                    bad[i] = time.monotonic() + 2.0

    def push_all(self, grads, members: Optional[dict] = None,
                 members_tc: Optional[dict] = None) -> None:
        """Push a gradient tree; each owner applies its subtree immediately
        with the DC-ASGD correction against this worker's last pull from it.

        The push carries this worker's (nonce, seq) dedup token — assigned
        ONCE per logical push, reused verbatim by any failover retry, so a
        shard that already applied it (directly, via its dead primary's
        replication stream, or via a migrated key range's transferred
        tokens) acks without re-applying. ``members`` (aggregator use
        only) attaches the merged push's constituent tokens so the shard
        ledger also covers a degraded member's flat replay. The owner
        SPLIT happens inside the retried closure: a table re-route
        between attempts re-splits against the new assignment."""
        kv = self._host_grads(grads)
        pseq = self._next_push_seq()
        with self._op("push") as sp:
            tc = sp.wire()
            if self.bucket_bytes is not None:
                self.flush()
                self._with_failover(
                    lambda: self._push_buckets_sync(self._split_kv(kv),
                                                    pseq=pseq, tc=tc,
                                                    members=members,
                                                    members_tc=members_tc))
                return

            def once():
                msgs = self._fanout({
                    i: self._encode_serial_push(tv.PUSH, sub, pseq=pseq,
                                                tc=tc, members=members,
                                                members_tc=members_tc)
                    for i, sub in self._split_kv(kv).items()
                })
                for i, msg in msgs.items():
                    kind, _, _, extra = tv.decode(msg)
                    if kind != tv.OK:
                        raise self._reply_error(i, extra)
                    self.versions[i] = int(extra["version"])

            self._with_failover(once)

    def push_pull(self, grads, members: Optional[dict] = None,
                  members_tc: Optional[dict] = None) -> Any:
        """push_all + pull_all in ONE round trip per server (the async
        cycle), all servers in flight concurrently. Routed through the
        bucketed pipeline when the worker was connected with
        ``bucket_bytes`` (identical math — the server applies the same
        whole tree and snapshots the same atomic pull). ``members`` as in
        :meth:`push_all` (aggregator-forwarded merged pushes only)."""
        kv = self._host_grads(grads)
        pseq = self._next_push_seq()
        with self._op("push_pull") as sp:
            tc = sp.wire()
            if self.bucket_bytes is not None:
                self.flush()  # a cycle racing a serial call would
                # reorder epochs

                def once_bucketed():
                    self._push_buckets_sync(self._split_kv(kv), pseq=pseq,
                                            tc=tc, members=members,
                                            members_tc=members_tc)
                    return self._merge_host_params(self._pull_buckets(tc=tc))

                return self._with_failover(once_bucketed)
            return self._with_failover(
                lambda: self._merge_params(self._fanout({
                    i: self._encode_serial_push(tv.PUSH_PULL, sub,
                                                pseq=pseq, tc=tc,
                                                members=members,
                                                members_tc=members_tc)
                    for i, sub in self._split_kv(kv).items()
                })))

    # -- bucketed, pipelined transport (worker half) --------------------------

    def _encode_serial_push(self, kind: int, sub: Dict[str, np.ndarray],
                            pseq: Optional[int] = None, tc=None,
                            members: Optional[dict] = None,
                            members_tc: Optional[dict] = None):
        """One serial push frame, compressed per the policy (the packed-key
        list rides the frame's extra, as on the bucketed path) and tagged
        with the (nonce, seq) dedup token plus the op's trace context
        (``tc``, when sampled). ``members`` is the aggregator's
        constituent-token map for a merged push (None otherwise), and
        ``members_tc`` the constituents' trace contexts riding beside
        those tokens — the shard's apply span names them so each member's
        trace finds the shared upstream commit. With ``writev`` on, the
        frame travels as zero-copy parts — the grad tensors go to the
        kernel as iovecs instead of through a staging bytearray (the
        measurable serial-path win at BERT-size trees)."""
        sub, enc = self._encode_push_tree(sub)
        extra = {}
        if enc:
            extra["enc"] = enc
        if pseq is not None:
            extra["pseq"] = pseq
            extra["pnonce"] = self._transport_nonce
        if members:
            extra["members"] = members
        if members_tc:
            extra["members_tc"] = members_tc
        if tc is not None:
            extra[obs.WIRE_KEY] = tc
        extra = extra or None
        if self.writev:
            return tv.encode_parts(kind, self.worker, sub, extra)
        return tv.encode(kind, self.worker, sub, extra)

    def _require_bucketed(self) -> None:
        if self.bucket_bytes is None:
            raise RuntimeError(
                "this worker uses the serial transport — connect with "
                "bucket_bytes=... (e.g. 4 << 20) to enable the bucketed/"
                "pipelined path"
            )

    def _push_buckets_sync(self, by_owner: Dict[int, Dict[str, np.ndarray]],
                           pseq: Optional[int] = None, tc=None,
                           members: Optional[dict] = None,
                           members_tc: Optional[dict] = None) -> None:
        """Slice each owner's subtree into fusion buckets, stripe them over
        the connection pool, wait for every ack, and adopt the committed
        versions. The engine sees ONE whole-tree apply per server, exactly
        like a serial PUSH; ``pseq`` is the logical push's dedup token
        (same on every bucket — the completing bucket's apply checks it),
        ``members`` the aggregator's constituent-token map when the push
        is a merged one."""
        self._push_epoch += 1
        epoch = self._push_epoch
        futs: List[Tuple[int, Any]] = []
        for i, sub in by_owner.items():
            # codec pass first: what buckets is the WIRE form of each key
            # (packed uint8 for compressed keys, raw tensors otherwise)
            sub, enc = self._encode_push_tree(sub)
            # contiguous-normalize ONCE per subtree: encode_bucket takes
            # memoryview slices, and a non-contiguous source would
            # otherwise be re-copied whole for every bucket it spans
            sub = {k: np.ascontiguousarray(v) for k, v in sub.items()}
            plan = BucketPlan.from_arrays(sub, self.bucket_bytes)
            pumps = self._pumps[i]
            # zero-copy frames when writev is on: the bucket's slice views
            # ride to the pump as (header, chunks) parts and pin `sub`
            # until sent — the grads' only copy is the kernel's (or the
            # shm ring's)
            enc_bucket = plan.bucket_encoder(self.writev)
            for b in range(plan.nbuckets):
                extra = {"epoch": epoch,
                         "nonce": self._transport_nonce,
                         "pseq": pseq,
                         "pnonce": self._transport_nonce,
                         "enc": enc}
                if members:
                    extra["members"] = members
                if members_tc:
                    extra["members_tc"] = members_tc
                if tc is not None:
                    extra[obs.WIRE_KEY] = tc
                payload = enc_bucket(tv.BUCKET_PUSH, self.worker, sub, b,
                                     extra=extra)
                futs.append((i, pumps[b % len(pumps)].submit(
                    payload, priority=self._bucket_submit_priority(b))))
        for i, fut in futs:
            reply = self._bucket_reply(i, fut)
            kind, _, _, extra = tv.decode(reply)
            self._release_frame(reply)  # extra is json-owned; frame done
            if kind != tv.OK:
                raise self._reply_error(i, extra)
            if extra.get("committed"):
                self.versions[i] = int(extra["version"])

    def _pull_buckets(self, tc=None) -> Dict[str, np.ndarray]:
        """Bucketed pull: bucket 0 snapshots each server's subtree (and
        names the bucket count); the rest stream over the pool. Requests go
        out front-of-model first, so the keys the next forward needs first
        are the first bytes on the wire."""
        self._pull_epoch += 1
        epoch = self._pull_epoch
        pull_spec = self._pull_compress_spec()

        def _extra(b: int, **kw) -> dict:
            out = {"epoch": epoch, "bucket": b, **kw}
            if tc is not None:
                out[obs.WIRE_KEY] = tc
            return out

        first = {
            i: self._pumps[i][0].submit(tv.encode(
                tv.BUCKET_PULL, self.worker, None,
                extra=_extra(0, bucket_bytes=self.bucket_bytes,
                             compress=pull_spec),
            ))
            for i in self._active
        }
        kv: Dict[str, np.ndarray] = {}
        enc_keys: List[str] = []
        rest: List[Tuple[int, Any]] = []
        assemblers: Dict[int, Any] = {}
        for i, fut in first.items():
            reply = self._bucket_reply(i, fut)
            kind, _, tensors, extra = tv.decode(reply)
            if kind != tv.OK:
                self._release_frame(reply)  # no borrow strands on errors
                raise self._reply_error(i, extra)
            self.versions[i] = int(extra["version"])
            enc_keys.extend(extra.get("enc") or [])
            n = int(extra["nbuckets"])
            asm = BucketAssembler(epoch, n)
            done = asm.add(0, tensors["raw"], extra["slices"], epoch)
            self._release_frame(reply)  # assembler copied; buffer reusable
            if done:
                kv.update(asm.finish())
                continue
            assemblers[i] = asm
            pumps = self._pumps[i]
            for b in range(1, n):
                payload = tv.encode(tv.BUCKET_PULL, self.worker, None,
                                    extra=_extra(b))
                rest.append((i, pumps[b % len(pumps)].submit(
                    payload, priority=self._bucket_submit_priority(b))))
        for i, fut in rest:
            reply = self._bucket_reply(i, fut)
            kind, _, tensors, extra = tv.decode(reply)
            if kind != tv.OK:
                self._release_frame(reply)
                raise self._reply_error(i, extra)
            done = assemblers[i].add(int(extra["bucket"]), tensors["raw"],
                                     extra["slices"], epoch)
            self._release_frame(reply)
            if done:
                kv.update(assemblers[i].finish())
        return decode_tree(kv, enc_keys, stats=self.transport)

    def _merge_host_params(self, kv: Dict[str, np.ndarray]) -> Any:
        import jax.numpy as jnp

        missing = [k for k in self._key_order if k not in kv]
        if missing:
            raise self._incomplete_pull(missing)
        self._params = keymod.unflatten(
            self._treedef, {k: jnp.asarray(v) for k, v in kv.items()},
            self._key_order,
        )
        return self._params

    def push_pull_async(self, grads) -> PendingCycle:
        """Start one full transport cycle (bucketed push, then ordered pull
        prefetch) in the background and return immediately.

        The returned :class:`PendingCycle` resolves to the freshly pulled
        params. Cycles are serialized per worker (a second call queues
        behind the first), so the per-worker push/pull order the staleness
        bound rests on is exactly the serial order — async mode bounds
        staleness precisely as before; calling :meth:`wait`/:meth:`flush`
        before computing the next gradients restores sync-step semantics
        bit for bit. Overlap comes from everything the caller does between
        the call and the wait: next-batch prep, metrics, the previous
        step's host work."""
        self._require_bucketed()
        kv = self._host_grads(grads)  # host copy: caller may mutate
        pseq = self._next_push_seq()  # assigned NOW: retries reuse it
        pending = PendingCycle(self.transport)
        self._track_pending(pending)
        self._bg_executor().submit(self._run_cycle, kv, pseq, pending)
        return pending

    def _run_cycle(self, kv, pseq: int, pending: PendingCycle) -> None:
        t0 = time.perf_counter()
        try:
            # the background cycle is its own trace root (the caller's
            # op returned long ago); push/pull bucket frames parent to it
            with self._op("cycle", pseq=pseq) as sp:
                tc = sp.wire()

                def once():
                    self._push_buckets_sync(self._split_kv(kv), pseq=pseq,
                                            tc=tc)
                    return self._merge_host_params(self._pull_buckets(tc=tc))

                params = self._with_failover(once)
        except BaseException as e:
            pending._fail(e)
        else:
            pending._resolve(params)
        finally:
            self.transport.record_cycle(time.perf_counter() - t0)

    def stats(self) -> dict:
        """Single-server: that server's stats dict (back-compat shape).
        Multi-server: ``{"servers": [per-server stats], "version": total}``."""
        msgs = self._fanout({
            i: tv.encode(tv.STATS, self.worker, None) for i in self._active
        })
        extras = {}
        for i, msg in msgs.items():
            _, _, _, extra = tv.decode(msg)
            extras[i] = extra
        if len(self._chs) == 1:
            return extras[self._active[0]]
        return {"servers": [extras.get(i) for i in range(len(self._chs))],
                "version": sum(int(e.get("version", 0))
                               for e in extras.values())}

    def checkpoint_all(self, path: str) -> List[int]:
        """Trigger a coordinated, CROSS-SHARD-ATOMIC checkpoint.

        Four phases: **pause** (every server blocks new applies and reports
        its per-worker applied-push counts), **drain_to** (pause alone is
        not atomic — another worker's push may already be applied on one
        shard and in flight to the rest, so each server admits exactly the
        blocked/in-flight pushes needed to reach the cross-shard per-worker
        maximum; TCP guarantees those arrive), **save** (each server writes
        its shard under ``path``, ``path/shard<i>`` when partitioned),
        **resume**. The restored state is therefore a point every shard
        agrees on: whole pushes, never a push torn across shards —
        tests/test_remote_async.py hammers this invariant under a
        concurrent pusher. Returns the per-server snapshot versions.

        Restart story: each restarted server runs ``store.init(
        shard_tree(params, i, N)); store.restore(path/shard<i>);
        serve_async(store, shard=i, num_shards=N)`` and workers
        :meth:`reconnect`."""
        tokens: Dict[int, dict] = {}
        try:
            # pause inside the protected region: if ANY round fails, the
            # surviving servers are still resumed — a fleet must never be
            # left blocked by a failed checkpoint. Pause hands each server's
            # ownership token back; every later phase presents it, so a
            # concurrent coordinator can neither pause over us nor resume
            # our pause out from under the save.
            try:
                paused = self._checkpoint_round({"dir": path,
                                                 "phase": "pause"})
            except CheckpointRoundError as e:
                tokens = self._ckpt_tokens(e.oks)  # resume the paused subset
                raise
            tokens = self._ckpt_tokens(paused)
            targets: Dict[str, int] = {}
            for extra in paused.values():
                for w, n in extra.get("applied", {}).items():
                    targets[w] = max(targets.get(w, 0), int(n))
            lagging = any(
                int(extra.get("applied", {}).get(w, 0)) < n
                for extra in paused.values() for w, n in targets.items()
            )
            if lagging:
                # the drain deadline is the coordinator's to set — the
                # server defaults it, but an unproduced knob is a dead
                # knob (pslint PSL203 found exactly that drift here)
                self._checkpoint_round({"dir": path, "phase": "drain_to",
                                        "targets": targets,
                                        "timeout": DRAIN_TO_TIMEOUT_S},
                                       per_server=tokens)
            saves = self._checkpoint_round({"dir": path, "phase": "save"},
                                           per_server=tokens)
        except BaseException:
            # resume the healthy servers, then let the ORIGINAL failure
            # propagate (the resume round hits the same dead server — its
            # error would only mask the root cause)
            try:
                self._checkpoint_round({"dir": path, "phase": "resume"},
                                       per_server=tokens)
            except Exception:
                pass
            raise
        self._checkpoint_round({"dir": path, "phase": "resume"},
                               per_server=tokens)
        return [int(saves[i]["version"]) for i in range(len(self._chs))]

    def reconnect(self, addrs: Optional[Sequence[Tuple[str, int]]] = None
                  ) -> None:
        """Re-dial every server (optionally at new addresses — restarted
        servers usually come back on new ephemeral ports) and revalidate
        the partition. The first pull after a reconnect is a fresh
        snapshot; staleness restarts from the servers' restored version
        vectors. Cumulative wire counters, transport stats, and the
        push/pull epoch streams survive the re-dial — even a FAILED
        re-dial, so TrainMetrics GB/s continuity holds across a restart
        and a retried reconnect just works."""
        try:
            self.flush()  # land (or fail fast) in-flight background cycles
        except Exception:
            pass  # a dead server is exactly why we are reconnecting
        obs.record_event("reconnect", worker=self.worker,
                         servers=len(self._addrs),
                         new_addrs=addrs is not None)
        saved = self._saved_transport_state()
        self._close_transport()
        for ch in self._chs:
            ch.close()  # dead or stale; no SHUTDOWN owed
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        # a plain re-dial of an AGGREGATED worker re-dials the aggregator
        # (with the remembered flat fallback intact); explicit addresses
        # always mean the flat topology — a restarted fleet
        fb = self._agg_fallback if addrs is None else None
        try:
            self._init_multi(
                list(addrs) if addrs is not None
                else (fb["addrs"] if fb is not None else self._addrs),
                self.worker, keymod.unflatten(
                    self._treedef, self._kv_like, self._key_order),
                bucket_bytes=self.bucket_bytes, pool_size=self.pool_size,
                compress=self.compress, writev=self.writev, shm=self.shm,
                shm_bytes=self.shm_bytes,
                # explicit new addresses invalidate the old replica sets
                # (restarted servers come back elsewhere); a plain re-dial
                # keeps them
                replica_sets=(None if addrs is not None
                              else fb["replica_sets"] if fb is not None
                              else self._replica_sets),
                failover_timeout=self.failover_timeout,
                coordinator=self._coord,
                table=(None if addrs is not None
                       else fb["table"] if fb is not None else self._table),
                aggregator=None if addrs is not None else self._agg_uri,
                read_staleness=self.read_staleness,
                pull_cache=self.pull_cache)
        finally:
            # restores the compressor too: topk error-feedback residuals
            # are unsent gradient mass and must survive the re-dial
            self._restore_transport_state(saved)

    def make_async_step(self, loss_fn, has_aux: bool = False,
                        overlap: bool = False):
        """``run(batch, *extra) -> loss`` — grad against the last-pulled
        (stale) params on THIS process's devices, then one push_pull.

        With ``overlap=True`` (bucketed transport required) the cycle runs
        in the background: ``run`` returns as soon as the loss is
        dispatched, and the NEXT call waits for the fresh params before
        computing — gradients are computed against exactly the same params
        as the serial step (loss-for-loss parity), while the transport of
        step k hides under the caller's inter-step host work. Call
        :meth:`flush` after the loop (``close()`` also does) to land the
        final push."""
        import jax

        if overlap:
            self._require_bucketed()
        grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=has_aux))
        pending: List[PendingCycle] = []

        def run(batch, *extra):
            if pending:
                params = pending.pop().wait()
            elif self._params is not None:
                params = self._params
            else:
                params = self.pull_all()
            if has_aux:
                (loss, aux), grads = grad_fn(params, batch, *extra)
            else:
                loss, grads = grad_fn(params, batch, *extra)
                aux = None
            if overlap:
                pending.append(self.push_pull_async(grads))
            else:
                self.push_pull(grads)
            return (loss, aux) if has_aux else loss

        return run

    def close(self) -> None:
        self._close_read_path()
        if self._tel_reporter is not None:
            self._tel_reporter.close()
            self._tel_reporter = None
        try:
            if self._pending_cycles:
                self.flush()  # land in-flight cycles before the goodbyes
        except Exception:
            pass  # a dead server must not block the local teardown
        self._close_transport()  # pool channels hang up silently (no goodbye)
        for ch in self._chs:
            try:
                ch.request(tv.encode(tv.SHUTDOWN, self.worker, None))
            except tv.VanError:
                pass
            ch.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
