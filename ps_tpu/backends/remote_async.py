"""Cross-process async PS: server state in ONE process, workers elsewhere.

This is the reference's actual async deployment shape (SURVEY.md §4d: the
server applies each worker's stale gradient immediately; workers are
separate, unsynchronized NODES — not host threads). The sync path collapses
into SPMD collectives; async cannot, by design, so it runs host-side:

- the SERVER process owns an async ``KVStore`` (``AsyncTpuServer`` engine —
  params + per-key state on ITS mesh, DC-ASGD applies, tree-granularity
  version vector) and serves it over the native van's TCP layer
  (:class:`AsyncPSService`);
- each WORKER process runs :class:`RemoteAsyncWorker`: pull params, compute
  gradients on its OWN jax devices, push — one ``PUSH_PULL`` round trip per
  cycle. Staleness is real cross-process staleness: whatever other workers
  committed between this worker's pull and its push.

Parity contract (tests/test_remote_async.py, tests/mp_async_worker.py): the
server records its apply order; replaying that exact (worker, grads)
sequence through a threaded ``AsyncTpuServer`` yields bit-identical
parameters — the wire changes nothing about the math.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ps_tpu.control import tensor_van as tv
from ps_tpu.kv import keys as keymod


class AsyncPSService:
    """Serve an async KVStore to remote workers over the tensor van.

    Args:
      store: an initialized async-mode KVStore (the server engine).
      port: TCP port (0 = ephemeral; read :attr:`port`).
      bind: listen address. Defaults to loopback — the endpoint is
        unauthenticated, so exposing it pod-wide ("0.0.0.0") is an explicit
        opt-in, mirroring ``Config.resolved_heartbeat_bind``.
    """

    def __init__(self, store, port: int = 0, bind: str = "127.0.0.1"):
        engine = store._engine
        if getattr(engine, "mode", "sync") != "async":
            raise ValueError("AsyncPSService requires an async-mode KVStore")
        self._store = store
        self._engine = engine
        self._key_order = list(store._key_order)
        self._listener = tv.Listener(port=port, bind=bind)
        self._stop = threading.Event()
        # set under the engine lock by stop(); checked under the same lock by
        # the push path, so "no push is applied after stop() returns" holds
        # even if a serve thread outlives the join (e.g. blocked in a jit
        # compile inside the engine apply)
        self._draining = False
        self._conns: List[threading.Thread] = []
        self._channels: List[tv.Channel] = []  # live conns, for stop()
        self._log_lock = threading.Lock()
        self.apply_log: List[int] = []  # worker id per committed tree, in order
        # full ordered (op, worker) history — "pull" records matter because
        # the DC apply depends on WHAT each worker last pulled; replaying
        # this log through a threaded engine reproduces params bit-for-bit
        self.event_log: List[List] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self._listener.port

    # -- server internals -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            ch = self._listener.accept(timeout_ms=200)
            if ch is None:
                continue
            self._channels.append(ch)
            t = threading.Thread(target=self._serve, args=(ch,), daemon=True)
            t.start()
            self._conns.append(t)

    def _params_payload(self, worker: int) -> bytes:
        # engine lock makes snapshot+version+log-append atomic (torn-read
        # hazard, and the event log must mirror true engine order)
        with self._engine._lock:
            kv = self._engine.pull_tree(worker=worker)
            version = self._engine.version
            with self._log_lock:
                self.event_log.append(["pull", worker])
        host = {k: np.asarray(v) for k, v in kv.items()}
        return tv.encode(tv.OK, worker, host, extra={"version": version})

    def _apply_push(self, worker: int, grads: Dict[str, np.ndarray]) -> None:
        if sorted(grads) != sorted(self._key_order):
            raise KeyError("push keys do not match the registered tree")
        # copy out of the recv buffer: the engine may keep references beyond
        # this frame's lifetime
        grads = {k: np.array(v) for k, v in grads.items()}
        with self._engine._lock:
            if self._draining:
                raise RuntimeError("server is draining; push refused")
            self._engine.push_tree(grads, worker=worker)
            with self._log_lock:
                self.apply_log.append(worker)
                self.event_log.append(["push", worker])

    def _serve(self, ch: tv.Channel) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = ch.recv()
                except tv.VanError:
                    return  # worker hung up
                kind, worker, tensors, extra = tv.decode(msg)
                try:
                    if kind == tv.HELLO:
                        ch.send(tv.encode(tv.OK, worker, None, extra={
                            "keys": self._key_order,
                            "version": self._engine.version,
                            "num_workers": self._engine.num_workers,
                        }))
                    elif kind == tv.PULL:
                        ch.send(self._params_payload(worker))
                    elif kind == tv.PUSH:
                        self._apply_push(worker, tensors)
                        ch.send(tv.encode(tv.OK, worker, None, extra={
                            "version": self._engine.version,
                        }))
                    elif kind == tv.PUSH_PULL:
                        self._apply_push(worker, tensors)
                        ch.send(self._params_payload(worker))
                    elif kind == tv.STATS:
                        with self._log_lock:
                            log = list(self.apply_log)
                        ch.send(tv.encode(tv.OK, worker, None, extra={
                            "version": self._engine.version,
                            "staleness_hist": {
                                str(t): n for t, n in
                                self._engine.staleness_hist.items()
                            },
                            "apply_log": log,
                            "worker_version": {
                                str(w): v for w, v in
                                self._engine._worker_version.items()
                            },
                        }))
                    elif kind == tv.SHUTDOWN:
                        ch.send(tv.encode(tv.OK, worker, None))
                        return
                    else:
                        ch.send(tv.encode(tv.ERR, worker, None,
                                          extra={"error": f"bad kind {kind}"}))
                except Exception as e:  # surface server-side errors to worker
                    ch.send(tv.encode(tv.ERR, worker, None,
                                      extra={"error": repr(e)}))
        finally:
            ch.close()
            try:
                self._channels.remove(ch)
            except ValueError:
                pass  # stop() may already be iterating a snapshot

    def stop(self) -> None:
        """Drain: no new connections, sever live ones (serve threads blocked
        in recv wake with EOF and exit — no push is applied after this
        returns), then free the listener.

        The guarantee has two legs: acquiring the engine lock below waits
        out any apply already in flight, and ``_draining`` (checked under
        that same lock) refuses every later commit — so even a serve thread
        that survives the bounded join (e.g. stuck in a minutes-long jit
        compile) can never land a push after this method returns."""
        self._stop.set()
        with self._engine._lock:
            self._draining = True
        for ch in list(self._channels):
            ch.shutdown()  # non-freeing sever; each serve thread closes own
        for t in list(self._conns):
            t.join(timeout=5)
        stragglers = [t for t in self._conns if t.is_alive()]
        if stragglers:
            import logging

            logging.getLogger(__name__).warning(
                "%d serve thread(s) outlived the drain join; their pushes "
                "are refused by the draining flag", len(stragglers)
            )
        # join BEFORE closing: the accept thread may be inside tv_accept on
        # the listener handle (its 200ms timeout bounds the wait); closing
        # first would hand it a freed pointer
        self._accept_thread.join(timeout=5)
        self._listener.close()


def serve_async(store, port: int = 0,
                bind: str = "127.0.0.1") -> "AsyncPSService":
    """Expose an initialized async KVStore to remote worker processes.

    The top-level entry of the cross-process async deployment: the server
    process calls this after ``store.init(params)``; workers connect with
    :func:`connect_async`. Returns the running service (``.port`` for
    ephemeral binds, ``.stop()`` to drain). ``bind`` defaults to loopback;
    pass "0.0.0.0" explicitly for a multi-host job (the endpoint is
    unauthenticated)."""
    return AsyncPSService(store, port=port, bind=bind)


def connect_async(uri: str, worker: int, params_like) -> "RemoteAsyncWorker":
    """Join a cross-process async job as worker ``worker``.

    ``uri`` is ``host:port`` of the :func:`serve_async` process (also the
    form trainers read from ``PS_ASYNC_SERVER_URI``); ``params_like`` is a
    pytree with the model's parameter structure (used to validate the tree
    against the server and to rebuild pulled params)."""
    host, port = uri.rsplit(":", 1)
    return RemoteAsyncWorker(host, int(port), worker, params_like)


class RemoteAsyncWorker:
    """A worker NODE of the cross-process async PS.

    Computes gradients on this process's own jax devices against the params
    it last pulled (stale by whatever other workers pushed since), and
    exchanges them with the server over one TCP round trip per cycle.
    """

    def __init__(self, host: str, port: int, worker: int, params_like):
        self.worker = worker
        kv, self._treedef = keymod.flatten_with_keys(params_like)
        self._key_order = sorted(kv)
        self._ch = tv.Channel.connect(host, port)
        _, _, _, extra = tv.decode(
            self._ch.request(tv.encode(tv.HELLO, worker, None))
        )
        if sorted(extra["keys"]) != self._key_order:
            raise ValueError(
                "server tree does not match this worker's params structure"
            )
        self.version = int(extra["version"])
        # the JOB's worker count (data-sharding denominator) is the server's
        # truth, not a local guess
        self.num_workers = int(extra["num_workers"])
        if not (0 <= worker < self.num_workers):
            raise ValueError(
                f"worker id {worker} out of range for a "
                f"{self.num_workers}-worker job"
            )
        self._params = None

    # -- protocol -------------------------------------------------------------

    def _unpack_params(self, msg) -> Any:
        kind, _, tensors, extra = tv.decode(msg)
        if kind != tv.OK:
            raise RuntimeError(f"server error: {extra.get('error')}")
        import jax.numpy as jnp

        self.version = int(extra["version"])
        kv = {k: jnp.asarray(np.array(v)) for k, v in tensors.items()}
        self._params = keymod.unflatten(self._treedef, kv, self._key_order)
        return self._params

    def pull_all(self) -> Any:
        """Fetch current params (server records this worker's snapshot)."""
        return self._unpack_params(
            self._ch.request(tv.encode(tv.PULL, self.worker, None))
        )

    def push_all(self, grads) -> None:
        """Push a gradient tree; the server applies it immediately with the
        DC-ASGD correction against this worker's last pull."""
        kv, _ = keymod.flatten_with_keys(grads)
        msg = self._ch.request(tv.encode(
            tv.PUSH, self.worker, {k: np.asarray(v) for k, v in kv.items()}
        ))
        kind, _, _, extra = tv.decode(msg)
        if kind != tv.OK:
            raise RuntimeError(f"server error: {extra.get('error')}")
        self.version = int(extra["version"])

    def push_pull(self, grads) -> Any:
        """push_all + pull_all in ONE round trip (the async cycle)."""
        kv, _ = keymod.flatten_with_keys(grads)
        return self._unpack_params(self._ch.request(tv.encode(
            tv.PUSH_PULL, self.worker,
            {k: np.asarray(v) for k, v in kv.items()}
        )))

    def stats(self) -> dict:
        _, _, _, extra = tv.decode(
            self._ch.request(tv.encode(tv.STATS, self.worker, None))
        )
        return extra

    def make_async_step(self, loss_fn, has_aux: bool = False):
        """``run(batch, *extra) -> loss`` — grad against the last-pulled
        (stale) params on THIS process's devices, then one push_pull."""
        import jax

        grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=has_aux))

        def run(batch, *extra):
            params = self._params if self._params is not None else self.pull_all()
            if has_aux:
                (loss, aux), grads = grad_fn(params, batch, *extra)
            else:
                loss, grads = grad_fn(params, batch, *extra)
                aux = None
            self.push_pull(grads)
            return (loss, aux) if has_aux else loss

        return run

    def close(self) -> None:
        try:
            self._ch.request(tv.encode(tv.SHUTDOWN, self.worker, None))
        except tv.VanError:
            pass
        self._ch.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
