"""Backend engines: 'local' (single-process in-memory server) and 'tpu'
(SPMD over a device mesh)."""
