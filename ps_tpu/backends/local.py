"""Single-process local parameter server.

This is the reference's "single-process local PS, CPU" mode (BASELINE.json
config 1) — the full push/aggregate/apply/pull protocol with no network and
no mesh, used as the testing seam and for small CPU runs.

Semantics implemented here (the spec the TPU backend must match numerically):

- **Per-key optimizer state.** Each parameter key has its own optax state,
  exactly like the reference server keeps state per key. For per-tensor
  optimizers (SGD/momentum/Adam/LAMB) this is numerically identical to a
  whole-tree update, which is what the fused TPU path does; the parity tests
  assert this.
- **Sync aggregation.** A key's update fires only once all ``num_workers``
  logical workers have pushed for the current step; gradients are averaged
  (matching data-parallel pmean semantics). A pull that would observe a
  half-aggregated key raises instead of silently returning stale values.
- **Async apply** (mode='async'): whole-tree pushes apply immediately with
  DC-ASGD delay compensation against the pusher's last-pulled version;
  per-key pushes stage per worker and commit as one tree (AsyncStagingMixin).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import optax

from ps_tpu.backends.common import (
    AsyncStagingMixin,
    PeekMixin,
    make_jit_dc_apply_tree,
)
from ps_tpu.checkpoint import CheckpointMixin
from ps_tpu.config import Config


class LocalServer(PeekMixin, AsyncStagingMixin, CheckpointMixin):
    """In-memory server for one KVStore: params + per-key optimizer state."""

    def __init__(self, optimizer: optax.GradientTransformation, num_workers: int,
                 mode: str = "sync", aggregate: str = "mean", dc_lambda: float = 0.04):
        import collections
        import threading

        if aggregate not in ("mean", "sum"):
            raise ValueError("aggregate must be 'mean' or 'sum'")
        self._opt = optimizer
        self.num_workers = num_workers
        self.mode = mode
        self.aggregate = aggregate
        self.dc_lambda = dc_lambda
        self._params: Dict[str, jax.Array] = {}
        self._state: Dict[str, Any] = {}
        # sync aggregation buffers: key -> {worker_id: grad}
        self._pending: Dict[str, Dict[int, jax.Array]] = {}
        # async: (worker_id, key) -> param snapshot at that worker's last pull
        self._stale: Dict[tuple, jax.Array] = {}
        self.apply_count: Dict[str, int] = {}
        # async version vector: tree-granularity, mirroring AsyncTpuServer
        self._version = 0
        self._staged_async = {}  # worker -> {key: grad} (async per-key staging)
        self._worker_version: Dict[int, int] = {}
        self.staleness_hist = collections.Counter()
        # serializes applies/pulls, like the reference server's apply loop
        self._lock = threading.RLock()

        def _apply(param, state, grad):
            updates, new_state = self._opt.update(grad, state, param)
            return optax.apply_updates(param, updates), new_state

        self._jit_apply = jax.jit(_apply)
        self._jit_apply_dc_tree = make_jit_dc_apply_tree(optimizer)

    # -- registration -------------------------------------------------------

    def register(self, key: str, value: jax.Array) -> None:
        if key in self._params:
            raise ValueError(f"key {key!r} already registered")
        self._params[key] = value
        self._state[key] = self._opt.init(value)
        self.apply_count[key] = 0

    def keys(self):
        return list(self._params)

    # -- push/pull ----------------------------------------------------------

    def push(self, key: str, grad: jax.Array, worker: int = 0) -> None:
        if key not in self._params:
            raise KeyError(f"unregistered key {key!r}")
        if not (0 <= worker < self.num_workers):
            raise ValueError(f"worker {worker} out of range [0, {self.num_workers})")
        with self._lock:
            if self.mode == "async":
                # stage per worker; commit as ONE fused tree apply when this
                # worker's tree completes (AsyncStagingMixin)
                self._stage_async_push(key, grad, worker)
                return
            slot = self._pending.setdefault(key, {})
            if worker in slot:
                raise RuntimeError(
                    f"worker {worker} pushed key {key!r} twice before aggregation fired"
                )
            slot[worker] = grad
            if len(slot) == self.num_workers:
                agg = slot[0]
                for w in range(1, self.num_workers):
                    agg = jax.tree_util.tree_map(lambda a, b: a + b, agg, slot[w])
                if self.aggregate == "mean" and self.num_workers > 1:
                    agg = jax.tree_util.tree_map(lambda a: a / self.num_workers, agg)
                self._params[key], self._state[key] = self._jit_apply(
                    self._params[key], self._state[key], agg
                )
                self.apply_count[key] += 1
                del self._pending[key]

    def push_tree(self, grads_kv: Dict[str, jax.Array], worker: int = 0) -> None:
        """Whole-tree push. Async: ONE fused DC apply for every key (same
        math as per-key pushes — keys are independent). Sync: the per-key
        staging protocol in a loop (aggregation fires per key)."""
        if self.mode != "async":
            for k, g in grads_kv.items():
                self.push(k, g, worker=worker)
            return
        if set(grads_kv) != set(self._params):
            raise ValueError("gradient keys do not match registered keys")
        from ps_tpu.backends.common import AGG_WORKER_BASE

        # aggregator identities (merged host-group pushes) are legal
        # pushers outside [0, num_workers) — see AsyncTpuServer._check_worker
        if worker < AGG_WORKER_BASE and not (0 <= worker < self.num_workers):
            raise ValueError(f"worker {worker} out of range [0, {self.num_workers})")
        with self._lock:
            self._commit_tree(grads_kv, worker)

    def pull(self, key: str, worker: int = 0) -> jax.Array:
        if key not in self._params:
            raise KeyError(f"unregistered key {key!r}")
        with self._lock:
            if self.mode == "sync" and key in self._pending:
                got = sorted(self._pending[key])
                raise RuntimeError(
                    f"pull({key!r}) would block: only workers {got} of "
                    f"{self.num_workers} have pushed this step"
                )
            if self.mode == "async":
                self._flush_staged(worker)  # pull ends the push phase
                self._stale[(worker, key)] = self._params[key]
                self._worker_version[worker] = self._version
            return self._params[key]

    @property
    def version(self) -> int:
        """Async mode: server version in whole-model steps."""
        return self._version

    def staleness(self, worker: int) -> int:
        """Async mode: whole-model versions since this worker's last pull."""
        return self._version - self._worker_version.get(worker, 0)

    def pull_tree(self, worker: int = 0) -> Dict[str, jax.Array]:
        """Atomic whole-tree pull (async: one consistent snapshot + stale
        record; sync: per-key blocked-pull checks under one lock)."""
        with self._lock:
            return {k: self.pull(k, worker=worker) for k in self._params}

    def optimizer_state(self, key: str):
        return self._state[key]

    # -- checkpoint hooks (CheckpointMixin) ---------------------------------

    engine_name = "local"

    def _check_checkpointable(self):
        if self._pending:
            raise RuntimeError(
                f"cannot checkpoint mid-step: keys {sorted(self._pending)} "
                f"have pending sync pushes"
            )
        self._check_staged_async()

    def _checkpoint_meta(self):
        return {
            "mode": self.mode,
            "num_workers": self.num_workers,
            "aggregate": self.aggregate,
            "apply_count": dict(self.apply_count),
            "version": self._version,
            "worker_version": {str(w): v for w, v in self._worker_version.items()},
            "staleness_hist": {str(t): n for t, n in self.staleness_hist.items()},
        }

    def _validate_checkpoint_meta(self, meta, elastic=False):
        # mode/aggregate always strict (different math, not topology);
        # num_workers relaxes under elastic resume
        strict = ("mode", "aggregate") if elastic else (
            "mode", "num_workers", "aggregate")
        for field in strict:
            if meta[field] != getattr(self, field):
                raise ValueError(
                    f"checkpoint was written with {field}={meta[field]!r} but "
                    f"this store runs {field}={getattr(self, field)!r} — "
                    f"resume semantics would differ"
                )

    def _load_checkpoint_meta(self, meta, elastic=False):
        import collections

        from ps_tpu.checkpoint import keep_worker

        self._pending = {}
        self.apply_count = {k: int(v) for k, v in meta["apply_count"].items()}
        # .get defaults accept checkpoints from before version accounting
        self._version = int(meta.get("version", 0))
        self._worker_version = {
            int(w): int(v) for w, v in meta.get("worker_version", {}).items()
            if keep_worker(int(w), self.num_workers, elastic)
        }
        self.staleness_hist = collections.Counter(
            {int(t): int(n) for t, n in meta.get("staleness_hist", {}).items()}
        )


class LocalBackend:
    """Backend for ``ps_tpu.init(backend='local')``."""

    def __init__(self, config: Config):
        self.config = config
        self.num_workers = config.num_workers

    def create_server(self, optimizer: optax.GradientTransformation,
                      mode: Optional[str] = None,
                      aggregate: str = "mean") -> LocalServer:
        return LocalServer(
            optimizer,
            num_workers=self.num_workers,
            mode=mode or self.config.mode,
            aggregate=aggregate,
            dc_lambda=self.config.dc_lambda,
        )

    def shutdown(self, abort: bool = False) -> None:
        del abort  # single-process: nothing to barrier on either way
