// Control-plane "van": heartbeat liveness over UDP.
//
// The reference family's ZMQ van carries BOTH the data plane (tensor
// push/pull) and the control plane (connect/barrier/heartbeat). On TPU the
// data plane is XLA collectives over ICI/DCN (SURVEY.md §3 row 9) — what
// remains host-side is liveness: every node beats, every node watches its
// peers, and a silent peer is declared dead after a timeout instead of the
// job hanging in a collective. This file is that control plane, kept native
// (C++, like the reference's van) so beat/poll latency is independent of the
// Python interpreter (GIL pauses during jit dispatch must not fake a death).
//
// Exposed as a C ABI for ctypes (ps_tpu/control/heartbeat.py). Threading
// model: one receiver thread per server, one sender thread per client;
// handles are opaque pointers; all public calls are thread-safe.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Beat {
  uint32_t magic;    // 'PSHB' beat | 'PSGB' goodbye
  uint32_t node_id;
  uint64_t seq;
};

constexpr uint32_t kMagic = 0x50534842;    // "PSHB"
constexpr uint32_t kGoodbye = 0x50534742;  // "PSGB" — clean leave, not death

struct Server {
  int fd = -1;
  int port = 0;
  int timeout_ms = 1000;
  std::atomic<bool> stop{false};
  std::thread rx;
  std::mutex mu;
  std::map<uint32_t, Clock::time_point> last_seen;
  std::map<uint32_t, uint64_t> last_seq;
  std::map<uint32_t, uint64_t> beat_addr;  // ip:port the node beats from
  std::set<uint32_t> left;  // nodes that said goodbye: never declared dead

  static uint64_t addr_key(const sockaddr_in& a) {
    return ((uint64_t)a.sin_addr.s_addr << 16) | a.sin_port;
  }

  void run() {
    Beat b;
    sockaddr_in src{};
    socklen_t slen = sizeof(src);
    while (!stop.load(std::memory_order_relaxed)) {
      slen = sizeof(src);
      ssize_t n = recvfrom(fd, &b, sizeof(b), 0, (sockaddr*)&src, &slen);
      if (n == (ssize_t)sizeof(b) &&
          (b.magic == kMagic || b.magic == kGoodbye)) {
        std::lock_guard<std::mutex> lock(mu);
        if (b.magic == kGoodbye) {
          // a goodbye permanently suppresses death detection for the node,
          // so it is only honored from the exact source address the node's
          // beats came from — a stray or forged datagram from anywhere
          // else cannot silence the detector (beats share the client fd,
          // so a genuine goodbye always matches)
          auto it = beat_addr.find(b.node_id);
          if (it == beat_addr.end() || it->second != addr_key(src)) continue;
          left.insert(b.node_id);
          last_seen[b.node_id] = Clock::now();
          continue;
        }
        last_seen[b.node_id] = Clock::now();
        last_seq[b.node_id] = b.seq;
        beat_addr[b.node_id] = addr_key(src);
      }
      // timeouts fall through so the stop flag is polled
    }
  }
};

struct Client {
  int fd = -1;
  sockaddr_in dest{};
  uint32_t node_id = 0;
  int interval_ms = 100;
  std::atomic<bool> stop{false};
  std::thread tx;

  void run() {
    Beat b{kMagic, node_id, 0};
    while (!stop.load(std::memory_order_relaxed)) {
      ++b.seq;
      sendto(fd, &b, sizeof(b), 0, (sockaddr*)&dest, sizeof(dest));
      std::this_thread::sleep_for(std::chrono::milliseconds(interval_ms));
    }
  }
};

}  // namespace

extern "C" {

// Start a heartbeat monitor bound to `bind_addr:port` (0 = ephemeral port).
// `bind_addr` is a dotted-quad IPv4 address — "0.0.0.0" accepts beats from
// any host (pod deployments), "127.0.0.1" restricts to this host (tests).
// A node is "alive" once its first beat arrives and "dead" when silent >
// timeout_ms — unless it said goodbye first (clean leave, state "left").
void* hb_server_start(const char* bind_addr, int port, int timeout_ms) {
  // no SO_REUSEADDR: a port collision must fail loudly at bind, not split
  // the beat stream between two silently-coexisting sockets
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return nullptr;
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  timeval tv{0, 100 * 1000};  // 100ms recv timeout: stop-flag poll cadence
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

  auto* s = new Server();
  s->fd = fd;
  s->port = ntohs(addr.sin_port);
  s->timeout_ms = timeout_ms;
  s->rx = std::thread([s] { s->run(); });
  return s;
}

int hb_server_port(void* h) { return static_cast<Server*>(h)->port; }

// Fill `out` (capacity `cap`) with ids in the given state; returns the count.
// state 0 = alive (beating within timeout), 1 = dead (seen, then silent
// WITHOUT a goodbye), 2 = left (sent a goodbye — clean membership change).
int hb_server_poll(void* h, int state, uint32_t* out, int cap) {
  auto* s = static_cast<Server*>(h);
  auto now = Clock::now();
  auto horizon = std::chrono::milliseconds(s->timeout_ms);
  std::lock_guard<std::mutex> lock(s->mu);
  int n = 0;
  for (const auto& kv : s->last_seen) {
    int st = s->left.count(kv.first) ? 2
             : ((now - kv.second) > horizon ? 1 : 0);
    if (st == state && n < cap) out[n++] = kv.first;
  }
  return n;
}

uint64_t hb_server_seq(void* h, uint32_t node_id) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->last_seq.find(node_id);
  return it == s->last_seq.end() ? 0 : it->second;
}

void hb_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop.store(true);
  if (s->rx.joinable()) s->rx.join();
  close(s->fd);
  delete s;
}

// Start beating `node_id` at `host:port` every interval_ms. `host` must be
// a dotted-quad IPv4 address (the Python wrapper resolves hostnames);
// anything else is a hard error, never a silent localhost fallback.
void* hb_client_start(const char* host, int port, uint32_t node_id,
                      int interval_ms) {
  sockaddr_in dest{};
  dest.sin_family = AF_INET;
  dest.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &dest.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return nullptr;
  auto* c = new Client();
  c->fd = fd;
  c->node_id = node_id;
  c->interval_ms = interval_ms;
  c->dest = dest;
  c->tx = std::thread([c] { c->run(); });
  return c;
}

// Announce a clean leave: a burst of goodbye datagrams (UDP may drop some;
// any one arriving flips the peer's state to "left" permanently). Safe to
// call while the beat thread runs — concurrent sendto on one UDP fd is
// per-datagram atomic.
void hb_client_goodbye(void* h) {
  auto* c = static_cast<Client*>(h);
  Beat b{kGoodbye, c->node_id, ~0ull};
  for (int i = 0; i < 3; ++i) {
    sendto(c->fd, &b, sizeof(b), 0, (sockaddr*)&c->dest, sizeof(c->dest));
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void hb_client_stop(void* h) {
  auto* c = static_cast<Client*>(h);
  c->stop.store(true);
  if (c->tx.joinable()) c->tx.join();
  close(c->fd);
  delete c;
}

}  // extern "C"
