// Native "van": heartbeat liveness over UDP + framed tensor messages over
// TCP.
//
// The reference family's ZMQ van carries BOTH the data plane (tensor
// push/pull) and the control plane (connect/barrier/heartbeat). On TPU the
// sync data plane is XLA collectives over ICI/DCN (SURVEY.md §3 row 9); what
// remains host-side is (a) liveness — every node beats, every node watches
// its peers, a silent peer is declared dead instead of the job hanging in a
// collective — and (b) the ASYNC data plane (SURVEY.md §4d): async workers
// are deliberately unsynchronized processes, so their grad/param exchange
// with the server process cannot ride a collective and travels as framed
// byte messages over TCP (the `tv_*` ABI below; ps_tpu/control/tensor_van.py
// does the tensor encoding). Kept native (C++, like the reference's van) so
// beat/poll latency and bulk sends are independent of the Python
// interpreter (GIL pauses during jit dispatch must not fake a death, and a
// multi-MB push must not stall the beat loops).
//
// Exposed as a C ABI for ctypes (ps_tpu/control/heartbeat.py,
// ps_tpu/control/tensor_van.py). Threading model: one receiver thread per
// heartbeat server, one sender thread per heartbeat client; TCP handles are
// plain blocking sockets driven by the caller's threads (ctypes releases
// the GIL for the duration of each call); handles are opaque pointers.

#include <arpa/inet.h>
#include <limits.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <cerrno>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Beat {
  uint32_t magic;    // 'PSHB' beat | 'PSGB' goodbye
  uint32_t node_id;
  uint64_t seq;
};

constexpr uint32_t kMagic = 0x50534842;    // "PSHB"
constexpr uint32_t kGoodbye = 0x50534742;  // "PSGB" — clean leave, not death

struct Server {
  int fd = -1;
  int port = 0;
  int timeout_ms = 1000;
  std::atomic<bool> stop{false};
  std::thread rx;
  std::mutex mu;
  std::map<uint32_t, Clock::time_point> last_seen;
  std::map<uint32_t, uint64_t> last_seq;
  std::map<uint32_t, uint64_t> beat_addr;  // ip:port the node beats from
  std::set<uint32_t> left;  // nodes that said goodbye: never declared dead

  static uint64_t addr_key(const sockaddr_in& a) {
    return ((uint64_t)a.sin_addr.s_addr << 16) | a.sin_port;
  }

  void run() {
    Beat b;
    sockaddr_in src{};
    socklen_t slen = sizeof(src);
    while (!stop.load(std::memory_order_relaxed)) {
      slen = sizeof(src);
      ssize_t n = recvfrom(fd, &b, sizeof(b), 0, (sockaddr*)&src, &slen);
      if (n == (ssize_t)sizeof(b) &&
          (b.magic == kMagic || b.magic == kGoodbye)) {
        std::lock_guard<std::mutex> lock(mu);
        if (b.magic == kGoodbye) {
          // a goodbye permanently suppresses death detection for the node,
          // so it is only honored from the exact source address the node's
          // beats came from — a stray or forged datagram from anywhere
          // else cannot silence the detector (beats share the client fd,
          // so a genuine goodbye always matches)
          auto it = beat_addr.find(b.node_id);
          if (it == beat_addr.end() || it->second != addr_key(src)) continue;
          left.insert(b.node_id);
          last_seen[b.node_id] = Clock::now();
          continue;
        }
        last_seen[b.node_id] = Clock::now();
        last_seq[b.node_id] = b.seq;
        beat_addr[b.node_id] = addr_key(src);
      }
      // timeouts fall through so the stop flag is polled
    }
  }
};

struct Client {
  int fd = -1;
  sockaddr_in dest{};
  uint32_t node_id = 0;
  int interval_ms = 100;
  std::atomic<bool> stop{false};
  std::thread tx;

  void run() {
    Beat b{kMagic, node_id, 0};
    while (!stop.load(std::memory_order_relaxed)) {
      ++b.seq;
      sendto(fd, &b, sizeof(b), 0, (sockaddr*)&dest, sizeof(dest));
      std::this_thread::sleep_for(std::chrono::milliseconds(interval_ms));
    }
  }
};

}  // namespace

extern "C" {

// Start a heartbeat monitor bound to `bind_addr:port` (0 = ephemeral port).
// `bind_addr` is a dotted-quad IPv4 address — "0.0.0.0" accepts beats from
// any host (pod deployments), "127.0.0.1" restricts to this host (tests).
// A node is "alive" once its first beat arrives and "dead" when silent >
// timeout_ms — unless it said goodbye first (clean leave, state "left").
void* hb_server_start(const char* bind_addr, int port, int timeout_ms) {
  // no SO_REUSEADDR: a port collision must fail loudly at bind, not split
  // the beat stream between two silently-coexisting sockets
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return nullptr;
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  timeval tv{0, 100 * 1000};  // 100ms recv timeout: stop-flag poll cadence
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

  auto* s = new Server();
  s->fd = fd;
  s->port = ntohs(addr.sin_port);
  s->timeout_ms = timeout_ms;
  s->rx = std::thread([s] { s->run(); });
  return s;
}

int hb_server_port(void* h) { return static_cast<Server*>(h)->port; }

// Fill `out` (capacity `cap`) with ids in the given state; returns the count.
// state 0 = alive (beating within timeout), 1 = dead (seen, then silent
// WITHOUT a goodbye), 2 = left (sent a goodbye — clean membership change).
int hb_server_poll(void* h, int state, uint32_t* out, int cap) {
  auto* s = static_cast<Server*>(h);
  auto now = Clock::now();
  auto horizon = std::chrono::milliseconds(s->timeout_ms);
  std::lock_guard<std::mutex> lock(s->mu);
  int n = 0;
  for (const auto& kv : s->last_seen) {
    int st = s->left.count(kv.first) ? 2
             : ((now - kv.second) > horizon ? 1 : 0);
    if (st == state && n < cap) out[n++] = kv.first;
  }
  return n;
}

uint64_t hb_server_seq(void* h, uint32_t node_id) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->last_seq.find(node_id);
  return it == s->last_seq.end() ? 0 : it->second;
}

// Milliseconds since node_id's last beat (a goodbye refreshes last_seen
// too, so a just-left node ages from its goodbye); -1 = never seen. The
// coordinator's membership view (ps_tpu/elastic) and ps_top render this
// as the per-peer "beat age" column.
int64_t hb_server_age_ms(void* h, uint32_t node_id) {
  auto* s = static_cast<Server*>(h);
  auto now = Clock::now();
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->last_seen.find(node_id);
  if (it == s->last_seen.end()) return -1;
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             now - it->second)
      .count();
}

void hb_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop.store(true);
  if (s->rx.joinable()) s->rx.join();
  close(s->fd);
  delete s;
}

// Start beating `node_id` at `host:port` every interval_ms. `host` must be
// a dotted-quad IPv4 address (the Python wrapper resolves hostnames);
// anything else is a hard error, never a silent localhost fallback.
void* hb_client_start(const char* host, int port, uint32_t node_id,
                      int interval_ms) {
  sockaddr_in dest{};
  dest.sin_family = AF_INET;
  dest.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &dest.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return nullptr;
  auto* c = new Client();
  c->fd = fd;
  c->node_id = node_id;
  c->interval_ms = interval_ms;
  c->dest = dest;
  c->tx = std::thread([c] { c->run(); });
  return c;
}

// Announce a clean leave: a burst of goodbye datagrams (UDP may drop some;
// any one arriving flips the peer's state to "left" permanently). Safe to
// call while the beat thread runs — concurrent sendto on one UDP fd is
// per-datagram atomic.
void hb_client_goodbye(void* h) {
  auto* c = static_cast<Client*>(h);
  Beat b{kGoodbye, c->node_id, ~0ull};
  for (int i = 0; i < 3; ++i) {
    sendto(c->fd, &b, sizeof(b), 0, (sockaddr*)&c->dest, sizeof(c->dest));
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void hb_client_stop(void* h) {
  auto* c = static_cast<Client*>(h);
  c->stop.store(true);
  if (c->tx.joinable()) c->tx.join();
  close(c->fd);
  delete c;
}

// ---------------------------------------------------------------------------
// Tensor van: length-framed byte messages over TCP. A frame on the wire is
// [u64 little-endian length][length bytes]. The payload encoding (tensor
// trees) lives in Python; this layer only moves opaque frames reliably.
// All calls are blocking (ctypes releases the GIL); one connection is meant
// to be driven by one thread at a time.

namespace {

constexpr uint64_t kMaxFrame = 1ull << 34;  // 16 GiB sanity bound

bool read_exact(int fd, void* buf, uint64_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;  // peer closed or hard error
    }
    p += r;
    n -= (uint64_t)r;
  }
  return true;
}

bool write_exact(int fd, const void* buf, uint64_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= (uint64_t)r;
  }
  return true;
}

struct Listener {
  int fd = -1;
  int port = 0;
};

struct Conn {
  int fd = -1;
  uint64_t pending = 0;  // size of the frame body announced but not yet read
};

}  // namespace

// Listen on bind_addr:port (0 = ephemeral). Returns nullptr on failure.
void* tv_listen(const char* bind_addr, int port, int backlog) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, backlog) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  auto* l = new Listener();
  l->fd = fd;
  l->port = ntohs(addr.sin_port);
  return l;
}

int tv_listener_port(void* h) { return static_cast<Listener*>(h)->port; }

// Accept one connection; timeout_ms < 0 blocks forever; returns nullptr on
// timeout or listener close.
void* tv_accept(void* h, int timeout_ms) {
  auto* l = static_cast<Listener*>(h);
  // poll(), not SO_RCVTIMEO on the listener: some kernels/sandboxes (e.g.
  // gVisor-style runtimes) do not honor RCVTIMEO for accept(2), which
  // turned every accept-poll tick into an indefinite block (and every
  // service stop() into a full 5s thread-join timeout)
  pollfd p{l->fd, POLLIN, 0};
  int r = poll(&p, 1, timeout_ms);  // timeout_ms < 0 blocks indefinitely
  if (r <= 0 || !(p.revents & POLLIN)) return nullptr;
  int fd = accept(l->fd, nullptr, nullptr);
  if (fd < 0) return nullptr;
  // the accepted fd INHERITS the listener's SO_RCVTIMEO (the accept-poll
  // cadence) on Linux — clear it, or any >timeout idle gap between client
  // requests would surface as a spurious EAGAIN "peer closed"
  timeval off{0, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &off, sizeof(off));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn();
  c->fd = fd;
  return c;
}

void tv_listener_close(void* h) {
  auto* l = static_cast<Listener*>(h);
  close(l->fd);
  delete l;
}

// Connect to host:port (dotted quad; Python resolves names). nullptr on
// failure/timeout. timeout_ms bounds the CONNECT only — once connected the
// socket blocks indefinitely (a server mid-jit-compile may legitimately
// take minutes to answer; a short lingering SO_RCVTIMEO would misreport
// that as a dead peer and desync the framing).
void* tv_connect(const char* host, int port, int timeout_ms) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  if (timeout_ms >= 0) {
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  timeval off{0, 0};  // clear the connect deadline: block forever from here
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &off, sizeof(off));
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn();
  c->fd = fd;
  return c;
}

// Send one frame. Returns 1 on success, 0 on a broken connection.
int tv_send(void* h, const void* buf, uint64_t n) {
  auto* c = static_cast<Conn*>(h);
  uint64_t len_le = n;  // this ABI is little-endian-host only (x86/ARM)
  if (!write_exact(c->fd, &len_le, sizeof(len_le))) return 0;
  return write_exact(c->fd, buf, n) ? 1 : 0;
}

// Send one frame gathered from `n` buffers WITHOUT any staging copy: the
// u64 length prefix plus every buffer goes out through sendmsg(2) scatter-
// gather iovecs (batched at IOV_MAX, partial writes resumed mid-iovec).
// The Python side hands live tensor memoryviews straight here — this is
// what deletes the per-frame staging bytearray of the legacy encode path.
// Returns 1 on success, 0 on a broken connection.
int tv_send_vec(void* h, const void** bufs, const uint64_t* lens, int n) {
  auto* c = static_cast<Conn*>(h);
  uint64_t total = 0;
  for (int i = 0; i < n; ++i) total += lens[i];
  uint64_t len_le = total;  // little-endian-host only, like tv_send
  std::vector<iovec> iov;
  iov.reserve((size_t)n + 1);
  iov.push_back({&len_le, sizeof(len_le)});
  for (int i = 0; i < n; ++i)
    if (lens[i]) iov.push_back({const_cast<void*>(bufs[i]), (size_t)lens[i]});
  size_t idx = 0;
  while (idx < iov.size()) {
    size_t cnt = iov.size() - idx;
    if (cnt > (size_t)IOV_MAX) cnt = (size_t)IOV_MAX;
    msghdr mh{};
    mh.msg_iov = &iov[idx];
    mh.msg_iovlen = cnt;
    ssize_t r = sendmsg(c->fd, &mh, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return 0;
    }
    while (r > 0 && idx < iov.size()) {
      if ((size_t)r >= iov[idx].iov_len) {
        r -= (ssize_t)iov[idx].iov_len;
        ++idx;
      } else {
        iov[idx].iov_base = (char*)iov[idx].iov_base + r;
        iov[idx].iov_len -= (size_t)r;
        r = 0;
      }
    }
  }
  return 1;
}

// Non-blocking (or bounded) readability probe: 1 when the next tv_recv_size
// would not block — data pending OR the peer hung up (EOF is "readable").
// The shm lane's poll loops use this to watch the TCP side for spilled
// frames and peer death without ever blocking on the socket.
int tv_poll_readable(void* h, int timeout_ms) {
  auto* c = static_cast<Conn*>(h);
  pollfd p{c->fd, POLLIN, 0};
  int r = poll(&p, 1, timeout_ms);
  return (r > 0 && (p.revents & (POLLIN | POLLHUP | POLLERR))) ? 1 : 0;
}

// ---------------------------------------------------------------------------
// Shared-memory ring primitives (ps_tpu/control/shm_lane.py). The lane's
// hot path must not run under the Python interpreter lock: ctypes releases
// the GIL for each of these calls, so frame copies run truly parallel with
// the peer thread (the same-process worker+server topology of every test
// and bench here) and the cursor waits burn no interpreter time at all.
// Cursors are published with release stores and read with acquire loads —
// the cross-process ordering contract the pure-Python seqlock could only
// approximate on TSO hardware.

// memcpy with the GIL released (ctypes drops it for the call's duration).
void tv_memcpy(void* dst, const void* src, uint64_t n) {
  memcpy(dst, src, n);
}

// Fault a fresh mapping in NOW (GIL-free), at negotiation time. mode 1:
// zero-fill (creator — allocates the backing pages); mode 2: rewrite one
// byte per page in place (attacher — maps the existing pages WITH write
// access; only safe while no traffic flows, i.e. during negotiation);
// mode 0: read-touch only. Without this, every first pass around a ring
// pays a page fault per 4 KiB — an order of magnitude over the copy
// itself on sandboxed kernels.
void tv_prefault(void* addr, uint64_t n, int mode) {
  if (mode == 1) {
    memset(addr, 0, n);
    return;
  }
  auto* p = static_cast<volatile char*>(addr);
  if (mode == 2) {
    for (uint64_t i = 0; i < n; i += 4096) p[i] = p[i];
    return;
  }
  uint64_t sum = 0;
  for (uint64_t i = 0; i < n; i += 4096) sum += (uint64_t)p[i];
  (void)sum;
}

uint64_t tv_load_u64(const void* addr) {
  return reinterpret_cast<const std::atomic<uint64_t>*>(addr)->load(
      std::memory_order_acquire);
}

void tv_store_u64(void* addr, uint64_t v) {
  reinterpret_cast<std::atomic<uint64_t>*>(addr)->store(
      v, std::memory_order_release);
}

// Futex-free adaptive wait until *addr != last or ~timeout_us elapses,
// in three phases tuned for hostile (sandboxed) kernels as much as bare
// metal: (1) a brief hot spin catches back-to-back traffic for free;
// (2) a yield-spin — check + sched_yield — carries the typical multi-MB
// frame latency (~ms) with wakeup granularity of one yield (µs..tens of
// µs under gVisor-style sandboxes) while handing the core to the peer's
// copy; (3) nanosleeps from 0.5 ms doubling to 2 ms, because sleep is
// the only phase that is truly free and some sandbox kernels round every
// nanosleep up to ~0.5 ms anyway — idle connections decay here and cost
// ~nothing. Returns 1 (changed in a spin phase), 2 (changed after
// sleeping), 0 (timeout — the caller re-checks its closed/peer-death
// conditions and calls again). GIL-free throughout (ctypes).
// `skip_spin`: nonzero jumps straight to the sleep phase — the caller
// passes it after a previous slice already timed out, so long-idle
// connections pay sleeps only, never re-burning the spin phases.
int tv_wait_u64(const void* addr, uint64_t last, int timeout_us,
                int skip_spin) {
  auto* p = reinterpret_cast<const std::atomic<uint64_t>*>(addr);
  auto start = Clock::now();
  auto deadline = start + std::chrono::microseconds(timeout_us);
  if (!skip_spin) {
    for (int i = 0; i < 512; ++i)
      if (p->load(std::memory_order_acquire) != last) return 1;
    auto yield_until =
        std::min(deadline, start + std::chrono::microseconds(3000));
    while (Clock::now() < yield_until) {
      if (p->load(std::memory_order_acquire) != last) return 1;
      std::this_thread::yield();
    }
  }
  int64_t ns = 500 * 1000;
  while (true) {
    if (p->load(std::memory_order_acquire) != last) return 2;
    if (Clock::now() >= deadline) return 0;
    std::this_thread::sleep_for(std::chrono::nanoseconds(ns));
    ns = std::min<int64_t>(ns * 2, 2 * 1000 * 1000);
  }
}

// Read the next frame's size (blocking). Returns -1 on EOF/error, -2 on an
// insane frame. The body MUST then be drained with tv_recv_into.
int64_t tv_recv_size(void* h) {
  auto* c = static_cast<Conn*>(h);
  uint64_t n = 0;
  if (!read_exact(c->fd, &n, sizeof(n))) return -1;
  if (n > kMaxFrame) return -2;
  c->pending = n;
  return (int64_t)n;
}

// Read exactly n bytes of the announced frame body into buf. 1 on success.
int tv_recv_into(void* h, void* buf, uint64_t n) {
  auto* c = static_cast<Conn*>(h);
  if (n > c->pending) return 0;
  if (!read_exact(c->fd, buf, n)) return 0;
  c->pending -= n;
  return 1;
}

// Sever the connection WITHOUT freeing the handle: any thread blocked in
// tv_recv_size/tv_recv_into on this conn wakes with EOF and can run its own
// tv_close. This is how a server interrupts serve threads that block
// indefinitely on idle clients (the fd outlives the shutdown; only tv_close
// frees).
void tv_shutdown(void* h) {
  auto* c = static_cast<Conn*>(h);
  shutdown(c->fd, SHUT_RDWR);
}

void tv_close(void* h) {
  auto* c = static_cast<Conn*>(h);
  shutdown(c->fd, SHUT_RDWR);
  close(c->fd);
  delete c;
}

}  // extern "C"
