// Native "van": heartbeat liveness over UDP + framed tensor messages over
// TCP.
//
// The reference family's ZMQ van carries BOTH the data plane (tensor
// push/pull) and the control plane (connect/barrier/heartbeat). On TPU the
// sync data plane is XLA collectives over ICI/DCN (SURVEY.md §3 row 9); what
// remains host-side is (a) liveness — every node beats, every node watches
// its peers, a silent peer is declared dead instead of the job hanging in a
// collective — and (b) the ASYNC data plane (SURVEY.md §4d): async workers
// are deliberately unsynchronized processes, so their grad/param exchange
// with the server process cannot ride a collective and travels as framed
// byte messages over TCP (the `tv_*` ABI below; ps_tpu/control/tensor_van.py
// does the tensor encoding). Kept native (C++, like the reference's van) so
// beat/poll latency and bulk sends are independent of the Python
// interpreter (GIL pauses during jit dispatch must not fake a death, and a
// multi-MB push must not stall the beat loops).
//
// Exposed as a C ABI for ctypes (ps_tpu/control/heartbeat.py,
// ps_tpu/control/tensor_van.py). Threading model: one receiver thread per
// heartbeat server, one sender thread per heartbeat client; TCP handles are
// plain blocking sockets driven by the caller's threads (ctypes releases
// the GIL for the duration of each call); handles are opaque pointers.

#include <arpa/inet.h>
#include <fcntl.h>
#include <limits.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <cerrno>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Beat {
  uint32_t magic;    // 'PSHB' beat | 'PSGB' goodbye
  uint32_t node_id;
  uint64_t seq;
};

constexpr uint32_t kMagic = 0x50534842;    // "PSHB"
constexpr uint32_t kGoodbye = 0x50534742;  // "PSGB" — clean leave, not death

struct Server {
  int fd = -1;
  int port = 0;
  int timeout_ms = 1000;
  std::atomic<bool> stop{false};
  std::thread rx;
  std::mutex mu;  // beat table — pslint: hot-lock
  std::map<uint32_t, Clock::time_point> last_seen;
  std::map<uint32_t, uint64_t> last_seq;
  std::map<uint32_t, uint64_t> beat_addr;  // ip:port the node beats from
  std::set<uint32_t> left;  // nodes that said goodbye: never declared dead

  static uint64_t addr_key(const sockaddr_in& a) {
    return ((uint64_t)a.sin_addr.s_addr << 16) | a.sin_port;
  }

  void run() {
    Beat b;
    sockaddr_in src{};
    socklen_t slen = sizeof(src);
    while (!stop.load(std::memory_order_relaxed)) {
      slen = sizeof(src);
      ssize_t n = recvfrom(fd, &b, sizeof(b), 0, (sockaddr*)&src, &slen);
      if (n == (ssize_t)sizeof(b) &&
          (b.magic == kMagic || b.magic == kGoodbye)) {
        std::lock_guard<std::mutex> lock(mu);
        if (b.magic == kGoodbye) {
          // a goodbye permanently suppresses death detection for the node,
          // so it is only honored from the exact source address the node's
          // beats came from — a stray or forged datagram from anywhere
          // else cannot silence the detector (beats share the client fd,
          // so a genuine goodbye always matches)
          auto it = beat_addr.find(b.node_id);
          if (it == beat_addr.end() || it->second != addr_key(src)) continue;
          left.insert(b.node_id);
          last_seen[b.node_id] = Clock::now();
          continue;
        }
        last_seen[b.node_id] = Clock::now();
        last_seq[b.node_id] = b.seq;
        beat_addr[b.node_id] = addr_key(src);
      }
      // timeouts fall through so the stop flag is polled
    }
  }
};

struct Client {
  int fd = -1;
  sockaddr_in dest{};
  uint32_t node_id = 0;
  int interval_ms = 100;
  std::atomic<bool> stop{false};
  std::thread tx;

  void run() {
    Beat b{kMagic, node_id, 0};
    while (!stop.load(std::memory_order_relaxed)) {
      ++b.seq;
      sendto(fd, &b, sizeof(b), 0, (sockaddr*)&dest, sizeof(dest));
      std::this_thread::sleep_for(std::chrono::milliseconds(interval_ms));
    }
  }
};

}  // namespace

extern "C" {

// Start a heartbeat monitor bound to `bind_addr:port` (0 = ephemeral port).
// `bind_addr` is a dotted-quad IPv4 address — "0.0.0.0" accepts beats from
// any host (pod deployments), "127.0.0.1" restricts to this host (tests).
// A node is "alive" once its first beat arrives and "dead" when silent >
// timeout_ms — unless it said goodbye first (clean leave, state "left").
void* hb_server_start(const char* bind_addr, int port, int timeout_ms) {
  // no SO_REUSEADDR: a port collision must fail loudly at bind, not split
  // the beat stream between two silently-coexisting sockets
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return nullptr;
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  timeval tv{0, 100 * 1000};  // 100ms recv timeout: stop-flag poll cadence
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

  auto* s = new Server();
  s->fd = fd;
  s->port = ntohs(addr.sin_port);
  s->timeout_ms = timeout_ms;
  s->rx = std::thread([s] { s->run(); });
  return s;
}

int hb_server_port(void* h) { return static_cast<Server*>(h)->port; }

// Fill `out` (capacity `cap`) with ids in the given state; returns the count.
// state 0 = alive (beating within timeout), 1 = dead (seen, then silent
// WITHOUT a goodbye), 2 = left (sent a goodbye — clean membership change).
int hb_server_poll(void* h, int state, uint32_t* out, int cap) {
  auto* s = static_cast<Server*>(h);
  auto now = Clock::now();
  auto horizon = std::chrono::milliseconds(s->timeout_ms);
  std::lock_guard<std::mutex> lock(s->mu);
  int n = 0;
  for (const auto& kv : s->last_seen) {
    int st = s->left.count(kv.first) ? 2
             : ((now - kv.second) > horizon ? 1 : 0);
    if (st == state && n < cap) out[n++] = kv.first;
  }
  return n;
}

uint64_t hb_server_seq(void* h, uint32_t node_id) {
  auto* s = static_cast<Server*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->last_seq.find(node_id);
  return it == s->last_seq.end() ? 0 : it->second;
}

// Milliseconds since node_id's last beat (a goodbye refreshes last_seen
// too, so a just-left node ages from its goodbye); -1 = never seen. The
// coordinator's membership view (ps_tpu/elastic) and ps_top render this
// as the per-peer "beat age" column.
int64_t hb_server_age_ms(void* h, uint32_t node_id) {
  auto* s = static_cast<Server*>(h);
  auto now = Clock::now();
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->last_seen.find(node_id);
  if (it == s->last_seen.end()) return -1;
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             now - it->second)
      .count();
}

void hb_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop.store(true);
  if (s->rx.joinable()) s->rx.join();
  close(s->fd);
  delete s;
}

// Start beating `node_id` at `host:port` every interval_ms. `host` must be
// a dotted-quad IPv4 address (the Python wrapper resolves hostnames);
// anything else is a hard error, never a silent localhost fallback.
void* hb_client_start(const char* host, int port, uint32_t node_id,
                      int interval_ms) {
  sockaddr_in dest{};
  dest.sin_family = AF_INET;
  dest.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &dest.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) return nullptr;
  auto* c = new Client();
  c->fd = fd;
  c->node_id = node_id;
  c->interval_ms = interval_ms;
  c->dest = dest;
  c->tx = std::thread([c] { c->run(); });
  return c;
}

// Announce a clean leave: a burst of goodbye datagrams (UDP may drop some;
// any one arriving flips the peer's state to "left" permanently). Safe to
// call while the beat thread runs — concurrent sendto on one UDP fd is
// per-datagram atomic.
void hb_client_goodbye(void* h) {
  auto* c = static_cast<Client*>(h);
  Beat b{kGoodbye, c->node_id, ~0ull};
  for (int i = 0; i < 3; ++i) {
    sendto(c->fd, &b, sizeof(b), 0, (sockaddr*)&c->dest, sizeof(c->dest));
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void hb_client_stop(void* h) {
  auto* c = static_cast<Client*>(h);
  c->stop.store(true);
  if (c->tx.joinable()) c->tx.join();
  close(c->fd);
  delete c;
}

// ---------------------------------------------------------------------------
// Tensor van: length-framed byte messages over TCP. A frame on the wire is
// [u64 little-endian length][length bytes]. The payload encoding (tensor
// trees) lives in Python; this layer only moves opaque frames reliably.
// All calls are blocking (ctypes releases the GIL); one connection is meant
// to be driven by one thread at a time.

namespace {

constexpr uint64_t kMaxFrame = 1ull << 34;  // 16 GiB sanity bound

bool read_exact(int fd, void* buf, uint64_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;  // peer closed or hard error
    }
    p += r;
    n -= (uint64_t)r;
  }
  return true;
}

bool write_exact(int fd, const void* buf, uint64_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= (uint64_t)r;
  }
  return true;
}

struct Listener {
  int fd = -1;
  int port = 0;
};

struct Conn {
  int fd = -1;
  uint64_t pending = 0;  // size of the frame body announced but not yet read
};

}  // namespace

// Listen on bind_addr:port (0 = ephemeral). Returns nullptr on failure.
void* tv_listen(const char* bind_addr, int port, int backlog) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, backlog) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  auto* l = new Listener();
  l->fd = fd;
  l->port = ntohs(addr.sin_port);
  return l;
}

int tv_listener_port(void* h) { return static_cast<Listener*>(h)->port; }

// Accept one connection; timeout_ms < 0 blocks forever; returns nullptr on
// timeout or listener close.
void* tv_accept(void* h, int timeout_ms) {
  auto* l = static_cast<Listener*>(h);
  // poll(), not SO_RCVTIMEO on the listener: some kernels/sandboxes (e.g.
  // gVisor-style runtimes) do not honor RCVTIMEO for accept(2), which
  // turned every accept-poll tick into an indefinite block (and every
  // service stop() into a full 5s thread-join timeout)
  pollfd p{l->fd, POLLIN, 0};
  int r = poll(&p, 1, timeout_ms);  // timeout_ms < 0 blocks indefinitely
  if (r <= 0 || !(p.revents & POLLIN)) return nullptr;
  int fd = accept(l->fd, nullptr, nullptr);
  if (fd < 0) return nullptr;
  // the accepted fd INHERITS the listener's SO_RCVTIMEO (the accept-poll
  // cadence) on Linux — clear it, or any >timeout idle gap between client
  // requests would surface as a spurious EAGAIN "peer closed"
  timeval off{0, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &off, sizeof(off));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn();
  c->fd = fd;
  return c;
}

void tv_listener_close(void* h) {
  auto* l = static_cast<Listener*>(h);
  close(l->fd);
  delete l;
}

// Connect to host:port (dotted quad; Python resolves names). nullptr on
// failure/timeout. timeout_ms bounds the CONNECT only — once connected the
// socket blocks indefinitely (a server mid-jit-compile may legitimately
// take minutes to answer; a short lingering SO_RCVTIMEO would misreport
// that as a dead peer and desync the framing).
void* tv_connect(const char* host, int port, int timeout_ms) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  if (timeout_ms >= 0) {
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return nullptr;
  }
  timeval off{0, 0};  // clear the connect deadline: block forever from here
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &off, sizeof(off));
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn();
  c->fd = fd;
  return c;
}

// Send one frame. Returns 1 on success, 0 on a broken connection.
int tv_send(void* h, const void* buf, uint64_t n) {
  auto* c = static_cast<Conn*>(h);
  uint64_t len_le = n;  // this ABI is little-endian-host only (x86/ARM)
  if (!write_exact(c->fd, &len_le, sizeof(len_le))) return 0;
  return write_exact(c->fd, buf, n) ? 1 : 0;
}

// Send one frame gathered from `n` buffers WITHOUT any staging copy: the
// u64 length prefix plus every buffer goes out through sendmsg(2) scatter-
// gather iovecs (batched at IOV_MAX, partial writes resumed mid-iovec).
// The Python side hands live tensor memoryviews straight here — this is
// what deletes the per-frame staging bytearray of the legacy encode path.
// Returns 1 on success, 0 on a broken connection.
int tv_send_vec(void* h, const void** bufs, const uint64_t* lens, int n) {
  auto* c = static_cast<Conn*>(h);
  uint64_t total = 0;
  for (int i = 0; i < n; ++i) total += lens[i];
  uint64_t len_le = total;  // little-endian-host only, like tv_send
  std::vector<iovec> iov;
  iov.reserve((size_t)n + 1);
  iov.push_back({&len_le, sizeof(len_le)});
  for (int i = 0; i < n; ++i)
    if (lens[i]) iov.push_back({const_cast<void*>(bufs[i]), (size_t)lens[i]});
  size_t idx = 0;
  while (idx < iov.size()) {
    size_t cnt = iov.size() - idx;
    if (cnt > (size_t)IOV_MAX) cnt = (size_t)IOV_MAX;
    msghdr mh{};
    mh.msg_iov = &iov[idx];
    mh.msg_iovlen = cnt;
    ssize_t r = sendmsg(c->fd, &mh, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return 0;
    }
    while (r > 0 && idx < iov.size()) {
      if ((size_t)r >= iov[idx].iov_len) {
        r -= (ssize_t)iov[idx].iov_len;
        ++idx;
      } else {
        iov[idx].iov_base = (char*)iov[idx].iov_base + r;
        iov[idx].iov_len -= (size_t)r;
        r = 0;
      }
    }
  }
  return 1;
}

// Non-blocking (or bounded) readability probe: 1 when the next tv_recv_size
// would not block — data pending OR the peer hung up (EOF is "readable").
// The shm lane's poll loops use this to watch the TCP side for spilled
// frames and peer death without ever blocking on the socket.
int tv_poll_readable(void* h, int timeout_ms) {
  auto* c = static_cast<Conn*>(h);
  pollfd p{c->fd, POLLIN, 0};
  int r = poll(&p, 1, timeout_ms);
  return (r > 0 && (p.revents & (POLLIN | POLLHUP | POLLERR))) ? 1 : 0;
}

// ---------------------------------------------------------------------------
// Shared-memory ring primitives (ps_tpu/control/shm_lane.py). The lane's
// hot path must not run under the Python interpreter lock: ctypes releases
// the GIL for each of these calls, so frame copies run truly parallel with
// the peer thread (the same-process worker+server topology of every test
// and bench here) and the cursor waits burn no interpreter time at all.
// Cursors are published with release stores and read with acquire loads —
// the cross-process ordering contract the pure-Python seqlock could only
// approximate on TSO hardware.

// memcpy with the GIL released (ctypes drops it for the call's duration).
// pslint: hot-path
void tv_memcpy(void* dst, const void* src, uint64_t n) {
  memcpy(dst, src, n);
}

// Fault a fresh mapping in NOW (GIL-free), at negotiation time. mode 1:
// zero-fill (creator — allocates the backing pages); mode 2: rewrite one
// byte per page in place (attacher — maps the existing pages WITH write
// access; only safe while no traffic flows, i.e. during negotiation);
// mode 0: read-touch only. Without this, every first pass around a ring
// pays a page fault per 4 KiB — an order of magnitude over the copy
// itself on sandboxed kernels.
// pslint: hot-path
void tv_prefault(void* addr, uint64_t n, int mode) {
  if (mode == 1) {
    memset(addr, 0, n);
    return;
  }
  auto* p = static_cast<volatile char*>(addr);
  if (mode == 2) {
    for (uint64_t i = 0; i < n; i += 4096) p[i] = p[i];
    return;
  }
  uint64_t sum = 0;
  for (uint64_t i = 0; i < n; i += 4096) sum += (uint64_t)p[i];
  (void)sum;
}

// pslint: hot-path
uint64_t tv_load_u64(const void* addr) {
  return reinterpret_cast<const std::atomic<uint64_t>*>(addr)->load(
      std::memory_order_acquire);
}

// pslint: hot-path
void tv_store_u64(void* addr, uint64_t v) {
  reinterpret_cast<std::atomic<uint64_t>*>(addr)->store(
      v, std::memory_order_release);
}

// Futex-free adaptive wait until *addr != last or ~timeout_us elapses,
// in three phases tuned for hostile (sandboxed) kernels as much as bare
// metal: (1) a brief hot spin catches back-to-back traffic for free;
// (2) a yield-spin — check + sched_yield — carries the typical multi-MB
// frame latency (~ms) with wakeup granularity of one yield (µs..tens of
// µs under gVisor-style sandboxes) while handing the core to the peer's
// copy; (3) nanosleeps from 0.5 ms doubling to 2 ms, because sleep is
// the only phase that is truly free and some sandbox kernels round every
// nanosleep up to ~0.5 ms anyway — idle connections decay here and cost
// ~nothing. Returns 1 (changed in a spin phase), 2 (changed after
// sleeping), 0 (timeout — the caller re-checks its closed/peer-death
// conditions and calls again). GIL-free throughout (ctypes).
// `skip_spin`: nonzero jumps straight to the sleep phase — the caller
// passes it after a previous slice already timed out, so long-idle
// connections pay sleeps only, never re-burning the spin phases.
// pslint: hot-path
int tv_wait_u64(const void* addr, uint64_t last, int timeout_us,
                int skip_spin) {
  auto* p = reinterpret_cast<const std::atomic<uint64_t>*>(addr);
  auto start = Clock::now();
  auto deadline = start + std::chrono::microseconds(timeout_us);
  if (!skip_spin) {
    for (int i = 0; i < 512; ++i)
      if (p->load(std::memory_order_acquire) != last) return 1;
    auto yield_until =
        std::min(deadline, start + std::chrono::microseconds(3000));
    while (Clock::now() < yield_until) {
      if (p->load(std::memory_order_acquire) != last) return 1;
      std::this_thread::yield();
    }
  }
  int64_t ns = 500 * 1000;
  while (true) {
    if (p->load(std::memory_order_acquire) != last) return 2;
    if (Clock::now() >= deadline) return 0;
    std::this_thread::sleep_for(std::chrono::nanoseconds(ns));
    ns = std::min<int64_t>(ns * 2, 2 * 1000 * 1000);
  }
}

// Read the next frame's size (blocking). Returns -1 on EOF/error, -2 on an
// insane frame. The body MUST then be drained with tv_recv_into.
int64_t tv_recv_size(void* h) {
  auto* c = static_cast<Conn*>(h);
  uint64_t n = 0;
  if (!read_exact(c->fd, &n, sizeof(n))) return -1;
  if (n > kMaxFrame) return -2;
  c->pending = n;
  return (int64_t)n;
}

// Read exactly n bytes of the announced frame body into buf. 1 on success.
int tv_recv_into(void* h, void* buf, uint64_t n) {
  auto* c = static_cast<Conn*>(h);
  if (n > c->pending) return 0;
  if (!read_exact(c->fd, buf, n)) return 0;
  c->pending -= n;
  return 1;
}

// Sever the connection WITHOUT freeing the handle: any thread blocked in
// tv_recv_size/tv_recv_into on this conn wakes with EOF and can run its own
// tv_close. This is how a server interrupts serve threads that block
// indefinitely on idle clients (the fd outlives the shutdown; only tv_close
// frees).
void tv_shutdown(void* h) {
  auto* c = static_cast<Conn*>(h);
  shutdown(c->fd, SHUT_RDWR);
}

void tv_close(void* h) {
  auto* c = static_cast<Conn*>(h);
  shutdown(c->fd, SHUT_RDWR);
  close(c->fd);
  delete c;
}

// Wrap an already-connected fd (e.g. one detached from the event loop
// below) as a blocking Conn handle the Channel wrapper can drive.
void* tv_adopt_fd(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl >= 0) fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Conn();
  c->fd = fd;
  return c;
}

// ---------------------------------------------------------------------------
// Native epoll event loop ("nl_*"): the server-side serve/pump hot loop for
// N connections on a small fixed pool of native threads — accept, frame
// reads, and scatter-gather reply writes all run here with the Python
// interpreter entirely out of the picture. Python's part shrinks to ONE
// pump thread calling nl_poll (GIL released), which hands back a BATCH of
// complete request frames; the pump decodes/dispatches each and answers
// with nl_reply_vec (immediate non-blocking writev of the live reply
// tensors; any unsent tail is buffered and dribbled out by the loop on
// EPOLLOUT). Per-connection cost is one ~200-byte struct + one epoll
// registration instead of a Python thread + stack — the thing that keeps
// per-connection overhead flat to 64+ workers.
//
// Threading model: `nthreads` loop threads, each owning a private epoll
// set; connections are assigned round-robin at accept and are only read /
// destroyed by their owner thread. Cross-thread work arrives either as a
// queued command (run by the owner between epoll_wait batches) or through
// the per-connection write mutex (nl_reply_vec runs on the Python pump
// thread). Lock order: loop table mutex -> per-conn write mutex; the
// ready queue has its own mutex. Request bodies are malloc'd per frame
// and owned by Python from nl_poll until nl_body_free.

namespace {

constexpr uint32_t kNlMaxOutstanding = 1024;  // queued frames per conn
// before the peer is declared abusive (every in-tree client is
// request/reply per connection, so the real depth is 1..window)
constexpr uint64_t kNlMaxWbufBacklog = 64ull << 20;  // staged-reply
// BACKLOG bound per conn: one reply of any size may stage its unsent
// tail, but a pipelining peer that stops READING does not get further
// replies copied behind it without limit — the threaded path's blocking
// send bounded this to one in-flight reply; here the bound is explicit

struct NlThread;

// ---------------------------------------------------------------------------
// In-loop telemetry: per-stripe log2-bucket histograms + counters. The hot
// path (loop threads reading frames, the pump claiming them) only ever does
// relaxed atomic increments into its OWN stripe — no locks, no allocation —
// and nl_hist_snapshot aggregates across stripes on read. The bucket
// geometry is an exact mirror of ps_tpu/obs/metrics.Histogram's defaults
// (lo=1e-6 s, hi=3600 s, 4 sub-buckets per octave), so a snapshot's raw
// buckets merge LOSSLESSLY into the Python registry and the coordinator's
// pooled-sample fleet quantiles (state_add) with no re-bucketing.

constexpr int kNlHistSub = 4;       // sub-buckets per octave (2^(1/4))
constexpr int kNlHistNb = 127;      // ceil(log2(3600 / 1e-6) * kNlHistSub)
constexpr int kNlHistBuckets = kNlHistNb + 2;  // + underflow + overflow
constexpr double kNlHistLo = 1e-6;  // seconds (1 ns..1 µs = underflow bin)
constexpr int kNlHistCount = 4;
// nl_hist_snapshot `which` indices (ctypes mirrors these by position)
constexpr int kNlHistReadFrame = 0;  // first byte -> frame complete
constexpr int kNlHistQueueWait = 1;  // frame complete -> claimed by pump
constexpr int kNlHistReadHit = 2;    // frame complete -> cache reply written
constexpr int kNlHistFlush = 3;      // tail staged -> EPOLLOUT drain done

struct NlHist {
  std::atomic<uint64_t> counts[kNlHistBuckets]{};
  std::atomic<uint64_t> total{0}, sum_ns{0};
  std::atomic<uint64_t> min_ns{~0ull}, max_ns{0};
};

struct NlStripe {
  NlHist hist[kNlHistCount];
};

// Slow-frame flight capture: a frame whose in-loop latency crossed the
// configured threshold leaves a bounded ring entry (kind byte, size, conn,
// per-stage timings, and the request's propagated trace context when the
// frame's meta carries one) for the Python pump to drain into a
// `slow_frame` flight event + a reconstructed span.
constexpr size_t kNlSlowRing = 256;
constexpr int kNlTidLen = 20;  // 16-hex id + NUL, padded to 8-byte multiple

struct NlSlowFrame {
  uint64_t conn = 0, size = 0;
  uint32_t kind = 0;
  uint64_t read_ns = 0, wait_ns = 0, serve_ns = 0;
  uint64_t mono_ns = 0;  // steady-clock stamp at record time
  char trace[kNlTidLen] = {0};
  char span[kNlTidLen] = {0};
};

// pslint: hot-path
uint64_t nl_now_ns() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             Clock::now().time_since_epoch())
      .count();
}

// One sample into one stripe's histogram: the same bucket formula as
// obs.metrics.Histogram.record (floor(log2(v/lo) * SUB) + 1, edge bins at
// 0 and nb+1), relaxed atomics only.
// pslint: hot-path
void nl_hist_add(NlHist& h, uint64_t ns) {
  double v = (double)ns * 1e-9;
  int k;
  if (v < kNlHistLo) {
    k = 0;
  } else {
    k = (int)(std::log2(v / kNlHistLo) * kNlHistSub) + 1;
    if (k < 1) k = 1;
    if (k > kNlHistNb) k = kNlHistNb + 1;
  }
  h.counts[k].fetch_add(1, std::memory_order_relaxed);
  h.total.fetch_add(1, std::memory_order_relaxed);
  h.sum_ns.fetch_add(ns, std::memory_order_relaxed);
  uint64_t cur = h.max_ns.load(std::memory_order_relaxed);
  while (ns > cur &&
         !h.max_ns.compare_exchange_weak(cur, ns,
                                         std::memory_order_relaxed)) {
  }
  cur = h.min_ns.load(std::memory_order_relaxed);
  while (ns < cur &&
         !h.min_ns.compare_exchange_weak(cur, ns,
                                         std::memory_order_relaxed)) {
  }
}

// Best-effort trace-context sniff over a complete frame body. The body
// layout is [u8 kind][u32 worker][u64 meta_len][json meta][raw buffers]
// (ps_tpu/control/tensor_van.py); a propagated context is the meta's
// `"tc": ["<trace>", "<span>"]` entry (json.dumps spacing, but tolerant
// of none). Scan is bounded to the meta region (capped at 4 KiB — in-tree
// encoders put `tc` in the first few hundred bytes), copies at most 16 hex
// chars per id, and never allocates — it only runs for frames ALREADY
// classified slow, never on the ordinary path.
void nl_extract_tc(const char* body, uint64_t len, char* trace, char* span) {
  trace[0] = span[0] = 0;
  if (body == nullptr || len < 13) return;
  uint64_t mlen;
  memcpy(&mlen, body + 5, 8);
  if (mlen > len - 13) return;
  uint64_t scan = mlen > 4096 ? 4096 : mlen;
  const char* meta = body + 13;
  static const char kKey[] = "\"tc\":";
  const uint64_t klen = sizeof(kKey) - 1;
  if (scan < klen) return;
  uint64_t i = 0;
  bool found = false;
  for (; i + klen <= scan; ++i) {  // <=: the last valid start offset is
    if (memcmp(meta + i, kKey, klen) == 0) {  // scan - klen inclusive
      found = true;
      break;
    }
  }
  if (!found) return;
  i += klen;
  char* out[2] = {trace, span};
  int which = 0;
  for (; i < scan && which < 2; ++i) {
    if (meta[i] != '"') continue;
    ++i;  // inside the string
    int n = 0;
    while (i < scan && meta[i] != '"' && n < kNlTidLen - 1)
      out[which][n++] = meta[i++];
    out[which][n] = 0;
    ++which;
  }
  if (which < 2) span[0] = 0;  // torn scan: never emit half a context
}

struct NlConn {
  int fd = -1;
  uint64_t id = 0;
  int owner = 0;  // loop-thread index
  // read state: owner thread only
  uint8_t lenbuf[8];
  int lenoff = 0;
  char* body = nullptr;  // frame body mid-read
  uint64_t body_len = 0, body_off = 0;
  uint64_t t_frame_ns = 0;  // first byte of the current frame (owner only)
  uint64_t t_stall_ns = 0;  // tail staged, not yet drained (guarded by wmu)
  bool dead = false;  // removed from the table; freed at iteration end
  // write state: guarded by wmu (pump thread replies, owner flushes)
  std::mutex wmu;
  std::string wbuf;  // unsent reply tail (only populated when the
  size_t woff = 0;   // immediate non-blocking writev could not finish)
  uint32_t outstanding = 0;  // frames queued/claimed, reply not yet sent
  uint32_t pins = 0;  // repliers inside the conn (guarded by loop tmu):
  // nl_reply_vec pins under a BRIEF table lock, writes under wmu only,
  // unpins; destroy waits for 0 — so a multi-MB reply memcpy never
  // serializes accepts/destroys/other replies behind the global table
  bool want_write = false;   // EPOLLOUT armed
  bool close_after = false;  // goodbye: destroy once the tail drains
  int prio = 0;  // drain priority of the staged tail (guarded by wmu):
  // lowest flushes first when several conns await EPOLLOUT service in
  // one epoll batch — bucket replies carry their bucket index, so the
  // front-of-model bytes a worker's next step needs leave first
};

struct NlReq {
  uint64_t conn_id;
  char* body;
  uint64_t len;
  uint64_t read_ns;    // first byte -> frame complete (0 = stats off)
  uint64_t ready_ns;   // frame-complete stamp for the queue-wait measure
  uint64_t admit_gen;  // native admission stamp (0 = not classified):
  // admit_floor + 1 captured when the owner thread classified this PUSH
  // frame fresh — Python skips its per-key dedup scan only while its
  // _read_gen still equals stamp - 1 (no apply landed in between)
};

struct NlThread {
  int epfd = -1;
  int evfd = -1;
  int idx = 0;  // this thread's stripe index (set once at nl_start)
  std::thread th;
  std::mutex cmu;
  std::vector<std::function<void(NlThread&)>> cmds;
  std::vector<NlConn*> graveyard;  // owner-thread only (and nl_stop)
};

// One native read-cache entry: a verbatim request body (exact-match key —
// byte-identical READ frames share one entry, and a hash collision can
// never serve the wrong reply) mapped to a ready-to-send reply buffer
// (u64 length prefix already prepended). Entries are immutable after
// construction and held by shared_ptr, so a hit can serve from one while
// an invalidation drops the table's reference concurrently.
struct NlCacheEntry {
  std::string key;    // full request body bytes
  std::string reply;  // [u64 le length][reply frame bytes]
  uint64_t gen = 0;   // publish generation (see cache_floor)
  // per-key invalidation tags (sorted): opaque u64s naming the state this
  // reply covers (the sparse service hashes each (table, row id) of the
  // cached id-set). An EMPTY set means "no claim" — such entries drop on
  // every tagged invalidation, so dense whole-tree replies and over-cap
  // id-sets stay exactly as conservative as before.
  std::vector<uint64_t> tags;
  // conditional-read entries (nl_cache_put_cond): the key is the request
  // body with the "cond" version DIGITS EXCISED, so readers at different
  // known versions share one entry; vfloor is the server version the
  // cached NOT_MODIFIED reply stamps — a sniffed request version v
  // serves iff v >= vfloor (the exact comparison the pump would make,
  // and entry liveness under invalidation-on-apply proves the state the
  // floor was taken against is still current).
  bool cond = false;
  uint64_t vfloor = 0;
};

//: bounded tail window of the meta region the push-token sniff walks
//: (the token lives in `extra`, the LAST top-level meta key)
constexpr uint64_t kNlAdmitScan = 4096;
//: longest worker push nonce the native ledger mirrors (in-tree nonces
//: are short uuid hex; anything longer punts to the pump)
constexpr int kNlAdmitNonceMax = 96;

// One worker's native push-admission ledger mirror: the engine's settled
// dedup bounds for the worker's CURRENT nonce. `lo` = every key the
// worker pushes is settled at seq <= lo (a frame with pseq <= lo is a
// PURE replay, ackable from the template alone); `hi` = no recorded OR
// stamped-fresh seq exceeds hi (a frame with pseq > hi is FRESH — the
// serve advances hi immediately, so a racing duplicate of the same seq
// punts to the pump instead of also stamping fresh). Python publishes an
// entry only when the worker's ledger is EXACT (one uniform nonce across
// every key); everything else punts.
struct NlAdmitEntry {
  std::string nonce;
  uint64_t lo = 0;
  uint64_t hi = 0;
};

struct NlLoop {
  Listener* listener = nullptr;  // borrowed: Python closes it after nl_stop
  std::atomic<bool> stop{false};
  std::atomic<bool> accepting{true};
  int nthreads = 1;
  std::deque<NlThread> threads;  // deque: NlThread is not movable
  // pslint: lock-order: tmu -> wmu
  std::mutex tmu;                // conn table — pslint: hot-lock
  std::condition_variable pin_cv;  // destroy/detach wait out repliers
  std::map<uint64_t, NlConn*> conns;
  uint64_t next_id = 1;
  uint64_t rr = 0;
  std::mutex qmu;  // ready queue — pslint: hot-lock
  std::condition_variable qcv;
  std::deque<NlReq> ready;
  std::atomic<uint64_t> iters{0}, accepted{0}, requests{0};
  std::atomic<uint64_t> popped{0}, freed{0};
  // Native read cache (the zero-upcall pull path, README "Read path"):
  // committed-state reply buffers published by Python (nl_cache_put on a
  // READ miss), answered entirely inside the loop threads on a hit — no
  // GIL, no upcall, no Python. cachemu is a LEAF lock: taken alone to
  // look up / mutate the table, always released before the per-conn wmu
  // write — never nested with tmu/qmu/wmu, so it adds no lock-order
  // edges. cache_floor is the invalidation generation: Python bumps it
  // on every committed apply (nl_cache_invalidate), and a put whose gen
  // predates the floor is refused — the race where a snapshot taken
  // before an apply is published after it can therefore never park a
  // stale reply in the cache.
  std::mutex cachemu;
  std::map<uint64_t, std::vector<std::shared_ptr<NlCacheEntry>>> cache;
  std::deque<std::shared_ptr<NlCacheEntry>> cache_fifo;  // eviction order
  uint64_t cache_floor = 0;
  uint64_t cache_bytes = 0;
  uint64_t cache_limit = 0;  // 0 = cache disabled
  // first body byte marking a cacheable frame; atomic so the read hot
  // path can gate on it without touching cachemu for ordinary frames
  std::atomic<int> cache_kind{-1};
  std::atomic<uint64_t> cache_hits{0}, cache_miss{0}, cache_puts{0},
      cache_rejects{0}, cache_invals{0};
  // conditional-read hits: the subset of cache_hits answered from a
  // version-floor entry (a NOT_MODIFIED revalidation served natively)
  std::atomic<uint64_t> cache_cond_hits{0};
  // in-loop telemetry (see the NlHist block above): one stripe per loop
  // thread plus one shared by the pump/punted callers (index nthreads).
  // stats_on/slow_ns are read per frame with relaxed loads — toggling
  // costs the hot path one branch.
  std::unique_ptr<NlStripe[]> stripes;
  std::atomic<int> stats_on{1};
  std::atomic<uint64_t> slow_ns{0};  // 0 = slow-frame watchdog off
  // staged-reply tail accounting (updated under each conn's wmu; atomics
  // so nl_stats_snapshot reads them without touching any conn lock)
  std::atomic<uint64_t> tail_staged{0};   // cumulative bytes ever staged
  std::atomic<uint64_t> tail_backlog{0};  // staged minus drained/dropped
  std::atomic<uint64_t> tail_flushes{0};  // tails drained to empty
  // slow-frame ring. slowmu is a LEAF lock (nothing else is ever taken
  // under it) and is only touched for frames already past the threshold,
  // so it is deliberately NOT a hot lock.
  std::mutex slowmu;
  std::deque<NlSlowFrame> slow_ring;
  std::atomic<uint64_t> slow_total{0}, slow_dropped{0};
  // Native push admission (the zero-upcall push plane, README "Push
  // path"): Python mirrors each worker's dedup ledger here so the owner
  // thread can classify an arriving PUSH frame without an upcall — pure
  // replays are acked from `admit_ack` (the byte-exact OK the pump
  // would produce, worker id patched in per serve), role refusals from
  // `admit_refusal` (armed only while this shard must refuse pushes:
  // backup role, fenced zombie), and fresh frames are STAMPED with
  // admit_floor + 1 and queued as usual. admitmu is a LEAF lock like
  // cachemu: taken alone for the ledger/template touch, always released
  // before the per-conn wmu write — never nested with tmu/qmu/wmu, so
  // it adds no lock-order edges. admit_floor is the same invalidation
  // generation the read cache uses: every committed apply raises it
  // (nl_admit_invalidate), a publish below it is refused, and Python
  // trusts a fresh stamp only while the floor it was taken at is still
  // current — so a pre-apply classification can never ack (or skip the
  // dedup scan for) a post-apply replay.
  std::mutex admitmu;  // pslint: hot-lock
  std::map<uint32_t, NlAdmitEntry> admit;
  std::string admit_ack;      // [u64 le length][reply frame], or empty
  std::string admit_refusal;  // same shape; armed = non-empty
  uint64_t admit_floor = 0;
  // first body byte marking an admissible frame; atomic so the read hot
  // path gates on it without touching admitmu (mirrors cache_kind)
  std::atomic<int> admit_kind{-1};
  std::atomic<uint64_t> admit_acks{0}, admit_refusals{0};
  std::atomic<uint64_t> admit_fresh{0}, admit_punts{0};
};

uint64_t nl_cache_hash(const char* p, uint64_t n) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a 64
  for (uint64_t i = 0; i < n; ++i) {
    h ^= (uint8_t)p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// Drop one entry from the table + fifo (cachemu held). `e` is BY VALUE
// on purpose: a caller may hand in a reference aliasing the very vector
// slot erased below — the copy keeps the entry alive for the fifo scan
// and the byte accounting after that slot is destroyed.
void nl_cache_erase(NlLoop* l, std::shared_ptr<NlCacheEntry> e,
                    uint64_t hv) {
  auto it = l->cache.find(hv);
  if (it != l->cache.end()) {
    auto& v = it->second;
    for (size_t i = 0; i < v.size(); ++i) {
      if (v[i] == e) {
        v.erase(v.begin() + i);
        break;
      }
    }
    if (v.empty()) l->cache.erase(it);
  }
  for (size_t i = 0; i < l->cache_fifo.size(); ++i) {
    if (l->cache_fifo[i] == e) {
      l->cache_fifo.erase(l->cache_fifo.begin() + i);
      break;
    }
  }
  l->cache_bytes -= e->key.size() + e->reply.size();
}

void nl_wake(NlThread& t) {
  uint64_t one = 1;
  ssize_t r = write(t.evfd, &one, sizeof(one));
  (void)r;
}

// Record one over-threshold frame into the bounded slow ring. Only called
// for frames already classified slow, so the leaf mutex + the bounded tc
// scan cost nothing on the ordinary path. Oldest entries are overwritten
// (counted) when the pump falls behind.
void nl_slow_record(NlLoop* l, uint64_t conn, const char* body,
                    uint64_t len, uint64_t read_ns, uint64_t wait_ns,
                    uint64_t serve_ns) {
  NlSlowFrame f;
  f.conn = conn;
  f.size = len;
  f.kind = len ? (uint8_t)body[0] : 0;
  f.read_ns = read_ns;
  f.wait_ns = wait_ns;
  f.serve_ns = serve_ns;
  f.mono_ns = nl_now_ns();
  nl_extract_tc(body, len, f.trace, f.span);
  std::lock_guard<std::mutex> lock(l->slowmu);
  if (l->slow_ring.size() >= kNlSlowRing) {
    l->slow_ring.pop_front();
    l->slow_dropped.fetch_add(1, std::memory_order_relaxed);
  }
  l->slow_ring.push_back(f);
  l->slow_total.fetch_add(1, std::memory_order_relaxed);
}

// Tail staged into a conn's write buffer (wmu held by the caller): account
// the backlog and stamp the stall start when the tail opens.
void nl_tail_staged(NlLoop* l, NlConn* c, uint64_t nbytes,
                    bool was_empty) {
  l->tail_staged.fetch_add(nbytes, std::memory_order_relaxed);
  l->tail_backlog.fetch_add(nbytes, std::memory_order_relaxed);
  if (was_empty) c->t_stall_ns = nl_now_ns();
}

// Owner thread (or nl_stop after join): unlink + free one connection.
// pslint: owns: body -- c->body here is a MID-READ frame that was never
// queued (queued frames move their pointer into the ready queue and
// null c->body), so no ownership ever transferred to Python
void nl_destroy(NlLoop* l, NlThread& t, NlConn* c) {
  {
    std::unique_lock<std::mutex> lock(l->tmu);
    l->conns.erase(c->id);  // erased first: no NEW pin can be taken
    while (c->pins > 0) l->pin_cv.wait(lock);  // a replier mid-write
    // still holds live pointers into the struct and its fd
  }
  {
    // pins are drained and the conn left the table, so the write state is
    // quiescent: any unflushed tail dies with the conn — return it to the
    // backlog gauge so the fleet view never reports ghost bytes
    std::lock_guard<std::mutex> wl(c->wmu);
    if (c->wbuf.size() > c->woff)
      l->tail_backlog.fetch_sub(c->wbuf.size() - c->woff,
                                std::memory_order_relaxed);
    c->woff = c->wbuf.size();
  }
  epoll_ctl(t.epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  free(c->body);
  c->body = nullptr;
  c->dead = true;
  t.graveyard.push_back(c);  // freed at iteration end: events already
  // fetched in this batch may still point at the struct
}

// Owner thread: write one ready-made reply (length prefix included) to
// c under the per-conn wmu only — the shared tail of the native serve
// paths (read-cache hits and push-admission acks/refusals; the caller's
// table lock — cachemu or admitmu — is already released, since a
// multi-KB reply send must not serialize other lookups/puts; same
// ordering discipline as nl_reply_vec's staged-tail path). Returns
// false ONLY for the pipelining punt: a peer with earlier frames still
// queued at the pump would see its replies reordered — per-connection
// reply order is part of the framed request/reply contract, so such a
// frame must take the pump path behind them. (In-tree clients are
// strict request/reply, so that branch costs real workloads nothing;
// the decrement in nl_reply_vec happens under this same wmu and writes
// under the same hold, so outstanding == 0 here proves every prior
// reply is fully written or staged ahead of us in wbuf.) True =
// handled: written, staged for EPOLLOUT, or severed as protocol abuse.
bool nl_serve_bytes(NlLoop* l, NlThread& t, NlConn* c, const char* data,
                    size_t len) {
  std::lock_guard<std::mutex> wl(c->wmu);
  if (c->outstanding != 0) return false;
  if (!c->wbuf.empty() && c->wbuf.size() - c->woff > kNlMaxWbufBacklog) {
    // pipelining peer stopped reading: bound server memory (same
    // protocol-abuse sever as nl_reply_vec)
    shutdown(c->fd, SHUT_RDWR);
    return true;
  }
  // a native reply is front-of-model-critical serving traffic: priority
  // 0 (the min rule matches nl_reply_vec — a staged tail keeps its most
  // urgent frame's priority)
  c->prio = c->wbuf.empty() ? 0 : std::min(c->prio, 0);
  if (c->wbuf.empty()) {
    size_t off = 0;
    while (off < len) {
      ssize_t r = send(c->fd, data + off, len - off,
                       MSG_DONTWAIT | MSG_NOSIGNAL);
      if (r < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        shutdown(c->fd, SHUT_RDWR);  // owner reaps on the EOF event
        return true;
      }
      off += (size_t)r;
    }
    if (off < len) {
      nl_tail_staged(l, c, len - off, true);
      c->wbuf.append(data + off, len - off);
    }
  } else {
    // a tail is already staged: whole frames append behind it in order
    nl_tail_staged(l, c, len, false);
    c->wbuf.append(data, len);
  }
  if (!c->wbuf.empty() && !c->want_write) {
    c->want_write = true;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.ptr = c;
    epoll_ctl(t.epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
  return true;
}

// Bounded token sniff for conditional READ frames: extract the caller's
// known version (`"cond": <int>`) from the meta region without a JSON
// parser — the same discipline as nl_admit_token below. The token lives
// in `extra`, the LAST top-level meta region by the encoder contract,
// and the encoders place "cond" LAST within extra, so the scan walks a
// bounded TAIL window and takes the LAST occurrence (the sparse
// per-table `"conds":` map cannot shadow it: its quoted key does not
// match the `"cond":` literal). On success fills *v with the version
// and *dlo/*dhi with the digit run's [start, end) BODY offsets — the
// range both the serve-side lookup and the publish-side key excise, so
// readers at different known versions share one spliced cache key.
// Returns 0 when the frame carries no parseable token: the caller
// treats the frame as unconditional (exact-match semantics only).
int nl_cond_token(const char* body, uint64_t len, uint64_t* v,
                  uint64_t* dlo, uint64_t* dhi) {
  if (body == nullptr || len < 13) return 0;
  uint64_t mlen;
  memcpy(&mlen, body + 5, 8);
  if (mlen > len - 13) return 0;
  const char* meta = body + 13;
  uint64_t lo = mlen > kNlAdmitScan ? mlen - kNlAdmitScan : 0;
  static const char kCond[] = "\"cond\":";
  const int64_t cl = (int64_t)sizeof(kCond) - 1;
  int64_t ci = -1;
  for (int64_t i = (int64_t)mlen - cl; i >= (int64_t)lo; --i) {
    if (memcmp(meta + i, kCond, (size_t)cl) == 0) {
      ci = i;
      break;
    }
  }
  if (ci < 0) return 0;
  uint64_t i = (uint64_t)(ci + cl);
  while (i < mlen && meta[i] == ' ') ++i;
  uint64_t dstart = i;
  if (i >= mlen || meta[i] < '0' || meta[i] > '9') return 0;
  uint64_t val = 0;
  for (; i < mlen && meta[i] >= '0' && meta[i] <= '9'; ++i) {
    if (val > (~0ull - 9) / 10) return 0;  // implausible: not a token
    val = val * 10 + (uint64_t)(meta[i] - '0');
  }
  *v = val;
  *dlo = 13 + dstart;  // body offsets (13-byte header + meta offset)
  *dhi = 13 + i;
  return 1;
}

// Owner thread: answer one cacheable frame from the native read cache.
// Returns true when the frame was SERVED (reply written or staged — the
// caller frees the body and moves on); false = miss, queue it to Python
// as usual (the strict fallback: anything the cache cannot answer takes
// the pump path, so replies are bitwise identical by construction — the
// cache only ever echoes buffers Python published). Two lookup shapes:
// exact byte match (unconditional frames, and conditional repeats at
// the very same known version), then — for frames carrying a "cond"
// token — the version-floor path: the token's digits are excised from
// the body and the spliced key looked up among conditional entries; a
// sniffed version v at or above the entry's vfloor gets the cached
// NOT_MODIFIED reply, byte-identical to what the pump would produce
// (same comparison, and entry liveness proves the state unchanged).
bool nl_cache_serve(NlLoop* l, NlThread& t, NlConn* c) {
  std::shared_ptr<NlCacheEntry> e;
  bool cond_hit = false;
  {
    std::lock_guard<std::mutex> lock(l->cachemu);
    if (!l->cache_limit) return false;
    uint64_t hv = nl_cache_hash(c->body, c->body_len);
    auto it = l->cache.find(hv);
    if (it != l->cache.end()) {
      for (auto& cand : it->second) {
        if (!cand->cond && cand->key.size() == c->body_len &&
            memcmp(cand->key.data(), c->body, c->body_len) == 0) {
          e = cand;
          break;
        }
      }
    }
    if (!e) {
      uint64_t v = 0, dlo = 0, dhi = 0;
      if (nl_cond_token(c->body, c->body_len, &v, &dlo, &dhi)) {
        std::string spliced;
        spliced.reserve(c->body_len - (dhi - dlo));
        spliced.append(c->body, dlo);
        spliced.append(c->body + dhi, c->body_len - dhi);
        uint64_t hv2 = nl_cache_hash(spliced.data(), spliced.size());
        auto it2 = l->cache.find(hv2);
        if (it2 != l->cache.end()) {
          for (auto& cand : it2->second) {
            if (cand->cond && cand->key.size() == spliced.size() &&
                memcmp(cand->key.data(), spliced.data(),
                       spliced.size()) == 0 &&
                v >= cand->vfloor) {
              e = cand;
              cond_hit = true;
              break;
            }
          }
        }
      }
    }
    if (!e) {
      l->cache_miss.fetch_add(1, std::memory_order_relaxed);
      return false;
    }
  }
  if (!nl_serve_bytes(l, t, c, e->reply.data(), e->reply.size())) {
    // pipelining punt (see nl_serve_bytes): the pump answers it
    l->cache_miss.fetch_add(1, std::memory_order_relaxed);
    return false;
  }
  l->cache_hits.fetch_add(1, std::memory_order_relaxed);
  if (cond_hit)
    l->cache_cond_hits.fetch_add(1, std::memory_order_relaxed);
  return true;
}

// Bounded token sniff for admissible PUSH-kind frames: extract the
// worker's dedup token (`"pseq": <int>`, `"pnonce": "<str>"`) from the
// meta region without a JSON parser — the same discipline as
// nl_extract_tc. The token lives in `extra`, the LAST top-level meta
// key by the encoder contract, so the scan walks a bounded TAIL window
// of the meta and takes the LAST occurrence of each key (a tensor name
// embedding the literal text cannot shadow the real token). Returns the
// nonce length (> 0) with *pseq filled, or 0 when the frame carries no
// parseable token — the caller punts: the pump's full JSON decode is
// the oracle for every frame this scan cannot classify.
int nl_admit_token(const char* body, uint64_t len, uint64_t* pseq,
                   char* nonce) {
  if (body == nullptr || len < 13) return 0;
  uint64_t mlen;
  memcpy(&mlen, body + 5, 8);
  if (mlen > len - 13) return 0;
  const char* meta = body + 13;
  uint64_t lo = mlen > kNlAdmitScan ? mlen - kNlAdmitScan : 0;
  static const char kSeq[] = "\"pseq\":";
  static const char kNonce[] = "\"pnonce\":";
  const int64_t sl = (int64_t)sizeof(kSeq) - 1;
  const int64_t nl = (int64_t)sizeof(kNonce) - 1;
  int64_t si = -1, ni = -1;
  for (int64_t i = (int64_t)mlen - sl; i >= (int64_t)lo; --i) {
    if (memcmp(meta + i, kSeq, (size_t)sl) == 0) {
      si = i;
      break;
    }
  }
  if (si < 0) return 0;
  for (int64_t i = (int64_t)mlen - nl; i >= (int64_t)lo; --i) {
    if (memcmp(meta + i, kNonce, (size_t)nl) == 0) {
      ni = i;
      break;
    }
  }
  if (ni < 0) return 0;
  uint64_t i = (uint64_t)(si + sl);
  while (i < mlen && meta[i] == ' ') ++i;
  if (i >= mlen || meta[i] < '0' || meta[i] > '9') return 0;
  uint64_t v = 0;
  for (; i < mlen && meta[i] >= '0' && meta[i] <= '9'; ++i) {
    if (v > (~0ull - 9) / 10) return 0;  // implausible: not a token
    v = v * 10 + (uint64_t)(meta[i] - '0');
  }
  *pseq = v;
  i = (uint64_t)(ni + nl);
  while (i < mlen && meta[i] == ' ') ++i;
  if (i >= mlen || meta[i] != '"') return 0;  // null/non-string nonce
  ++i;
  int n = 0;
  while (i < mlen && meta[i] != '"') {
    // in-tree nonces are short uuid hex — an escape or an over-long
    // nonce is not one of ours: punt rather than guess
    if (meta[i] == '\\' || n >= kNlAdmitNonceMax) return 0;
    nonce[n++] = meta[i++];
  }
  if (i >= mlen || n == 0) return 0;
  return n;
}

// Owner thread: classify one admissible PUSH frame against the native
// ledger mirror. Returns 1 when the frame was SERVED natively (replay
// ack or role refusal written — the caller frees the body and moves
// on), 2 when it is FRESH (the caller queues it to the pump stamped
// with *admit_gen — the floor at classification time + 1, which Python
// trusts only while no apply has landed since), or 0 to PUNT: queue it
// unstamped, exactly the pre-admission path. The strict-fallback mirror
// of nl_cache_serve: anything this tier cannot prove takes the pump, so
// reply bytes stay identical by construction — the templates only ever
// echo frames Python published.
int nl_admit_serve(NlLoop* l, NlThread& t, NlConn* c,
                   uint64_t* admit_gen) {
  if (c->body_len < 13) return 0;
  uint32_t worker;
  memcpy(&worker, c->body + 1, 4);
  uint64_t pseq = 0;
  char nonce[kNlAdmitNonceMax];
  int nlen = nl_admit_token(c->body, c->body_len, &pseq, nonce);
  std::string reply;     // template copied out under admitmu: the send
  bool refusal = false;  // happens under the conn's wmu only
  {
    std::lock_guard<std::mutex> lock(l->admitmu);
    if (!l->admit_refusal.empty()) {
      // role refusal (backup / fenced zombie): every admissible frame
      // gets the typed ERR the pump would produce, token or not
      reply = l->admit_refusal;
      refusal = true;
    } else if (nlen <= 0) {
      l->admit_punts.fetch_add(1, std::memory_order_relaxed);
      return 0;
    } else {
      auto it = l->admit.find(worker);
      if (it == l->admit.end() ||
          it->second.nonce.size() != (size_t)nlen ||
          memcmp(it->second.nonce.data(), nonce, (size_t)nlen) != 0) {
        // unknown worker, or a restarted one (new nonce): the pump's
        // full ledger is the oracle until the next publish
        l->admit_punts.fetch_add(1, std::memory_order_relaxed);
        return 0;
      }
      NlAdmitEntry& e = it->second;
      if (pseq > e.hi) {
        // fresh: advance the pending bound NOW, so a racing duplicate
        // of the same seq punts instead of also stamping fresh
        e.hi = pseq;
        *admit_gen = l->admit_floor + 1;
        l->admit_fresh.fetch_add(1, std::memory_order_relaxed);
        return 2;
      }
      if (pseq > e.lo || l->admit_ack.empty()) {
        // in-window: a seq some key may not have settled yet (stamped
        // fresh, apply not yet published back) — only the pump's
        // per-key scan can answer it
        l->admit_punts.fetch_add(1, std::memory_order_relaxed);
        return 0;
      }
      reply = l->admit_ack;  // pure replay: every key settled <= lo
    }
  }
  // patch the requesting worker's id into the template (reply layout:
  // [u64 le length][kind u8][worker u32 le]...; templates are validated
  // >= 13 frame bytes at publish, so offset 9..13 is in bounds)
  memcpy(&reply[9], &worker, 4);
  if (!nl_serve_bytes(l, t, c, reply.data(), reply.size())) {
    // pipelining punt (see nl_serve_bytes): the pump answers it
    l->admit_punts.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  if (refusal)
    l->admit_refusals.fetch_add(1, std::memory_order_relaxed);
  else
    l->admit_acks.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

// Owner thread: read everything available on c; queue complete frames.
void nl_read(NlLoop* l, NlThread& t, NlConn* c) {
  while (true) {
    if (c->body == nullptr) {
      ssize_t r = recv(c->fd, c->lenbuf + c->lenoff, 8 - c->lenoff, 0);
      if (r == 0) { nl_destroy(l, t, c); return; }
      if (r < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        nl_destroy(l, t, c);
        return;
      }
      if (c->lenoff == 0 && l->stats_on.load(std::memory_order_relaxed))
        c->t_frame_ns = nl_now_ns();  // first byte of a new frame
      c->lenoff += (int)r;
      if (c->lenoff < 8) continue;
      uint64_t len;
      memcpy(&len, c->lenbuf, 8);
      c->lenoff = 0;
      if (len > kMaxFrame) { nl_destroy(l, t, c); return; }
      c->body = static_cast<char*>(malloc(len ? len : 1));
      if (!c->body) { nl_destroy(l, t, c); return; }
      c->body_len = len;
      c->body_off = 0;
    }
    while (c->body_off < c->body_len) {
      ssize_t r = recv(c->fd, c->body + c->body_off,
                       c->body_len - c->body_off, 0);
      if (r == 0) { nl_destroy(l, t, c); return; }
      if (r < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        nl_destroy(l, t, c);
        return;
      }
      c->body_off += (uint64_t)r;
    }
    // frame complete: the read-latency sample lands now; the completion
    // stamp rides the queue entry so the pump measures its own wait
    bool stats = l->stats_on.load(std::memory_order_relaxed) != 0;
    uint64_t done_ns = 0, read_ns = 0;
    if (stats) {
      done_ns = nl_now_ns();
      if (c->t_frame_ns) {
        read_ns = done_ns - c->t_frame_ns;
        nl_hist_add(l->stripes[t.idx].hist[kNlHistReadFrame], read_ns);
      }  // no first-byte stamp (stats were off then): skip the sample
    }
    // cleared UNCONDITIONALLY: a stamp taken before a stats toggle must
    // never survive into a later frame as a phantom multi-second sample
    c->t_frame_ns = 0;
    {
      int ck = l->cache_kind.load(std::memory_order_relaxed);
      if (ck >= 0 && c->body_len >= 1 && (uint8_t)c->body[0] == (uint8_t)ck
          && nl_cache_serve(l, t, c)) {
        // answered (or severed) natively: the frame never queued, so it
        // never counts as outstanding and Python never sees it. This is
        // the zero-upcall path — its service time is only visible here.
        if (stats) {
          uint64_t serve_ns = nl_now_ns() - done_ns;
          nl_hist_add(l->stripes[t.idx].hist[kNlHistReadHit], serve_ns);
          uint64_t thr = l->slow_ns.load(std::memory_order_relaxed);
          if (thr && read_ns + serve_ns > thr)
            nl_slow_record(l, c->id, c->body, c->body_len, read_ns, 0,
                           serve_ns);
        }
        // pslint: owns: body -- cache-hit frame answered on the owner
        // thread BEFORE the queue push: still thread-private, no
        // ownership ever transferred to Python
        free(c->body);
        c->body = nullptr;
        c->body_len = c->body_off = 0;
        continue;
      }
    }
    uint64_t admit_gen = 0;
    {
      int ak = l->admit_kind.load(std::memory_order_relaxed);
      if (ak >= 0 && c->body_len >= 1
          && (uint8_t)c->body[0] == (uint8_t)ak) {
        int rc = nl_admit_serve(l, t, c, &admit_gen);
        if (rc == 1) {
          // answered natively (replay ack or role refusal): the frame
          // never queued, Python never saw it — the zero-upcall push
          // path, same life cycle as a read-cache hit above
          if (stats) {
            uint64_t serve_ns = nl_now_ns() - done_ns;
            uint64_t thr = l->slow_ns.load(std::memory_order_relaxed);
            if (thr && read_ns + serve_ns > thr)
              nl_slow_record(l, c->id, c->body, c->body_len, read_ns, 0,
                             serve_ns);
          }
          // pslint: owns: body -- admission-served frame answered on
          // the owner thread BEFORE the queue push: still
          // thread-private, no ownership ever transferred to Python
          free(c->body);
          c->body = nullptr;
          c->body_len = c->body_off = 0;
          continue;
        }
      }
    }
    uint32_t out;
    {
      std::lock_guard<std::mutex> lock(c->wmu);
      out = ++c->outstanding;
    }
    if (out > kNlMaxOutstanding) {
      // pslint: owns: body -- abuse path, BEFORE the queue push: this
      // frame is still thread-private, nothing transferred yet
      free(c->body);
      c->body = nullptr;
      nl_destroy(l, t, c);
      return;
    }
    {
      std::lock_guard<std::mutex> lock(l->qmu);
      // pslint: transfers: body -- from this push the body is Python's,
      // nl_poll hands it out and ONLY nl_body_free may release it; the
      // UAF gate: any new native free of a body needs an owns: claim
      l->ready.push_back({c->id, c->body, c->body_len, read_ns, done_ns,
                          admit_gen});
    }
    l->requests.fetch_add(1, std::memory_order_relaxed);
    l->qcv.notify_one();
    c->body = nullptr;
    c->body_len = c->body_off = 0;
  }
}

// Owner thread: flush the buffered reply tail; returns false when the
// connection must be destroyed (hard error, or goodbye fully flushed).
bool nl_flush(NlLoop* l, NlThread& t, NlConn* c) {
  std::lock_guard<std::mutex> lock(c->wmu);
  while (c->woff < c->wbuf.size()) {
    ssize_t r = send(c->fd, c->wbuf.data() + c->woff,
                     c->wbuf.size() - c->woff, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
      return false;
    }
    c->woff += (size_t)r;
    l->tail_backlog.fetch_sub((uint64_t)r, std::memory_order_relaxed);
  }
  // tail fully drained: the EPOLLOUT stall this conn paid ends here
  if (c->t_stall_ns) {
    if (l->stats_on.load(std::memory_order_relaxed))
      nl_hist_add(l->stripes[t.idx].hist[kNlHistFlush],
                  nl_now_ns() - c->t_stall_ns);
    l->tail_flushes.fetch_add(1, std::memory_order_relaxed);
    c->t_stall_ns = 0;
  }
  if (c->wbuf.capacity() > (1u << 20)) {
    // release a large spill's capacity instead of pinning it for the
    // connection's lifetime (64 conns that each spilled once would
    // otherwise hold their high-water marks forever)
    std::string().swap(c->wbuf);
  } else {
    c->wbuf.clear();
  }
  c->woff = 0;
  if (c->close_after) return false;
  if (c->want_write) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = c;
    epoll_ctl(t.epfd, EPOLL_CTL_MOD, c->fd, &ev);
    c->want_write = false;
  }
  return true;
}

void nl_accept(NlLoop* l, NlThread& t0) {
  while (l->accepting.load(std::memory_order_relaxed)) {
    int fd = accept(l->listener->fd, nullptr, nullptr);
    if (fd < 0) return;  // EAGAIN (nonblocking listener) or closed
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto* c = new NlConn();
    c->fd = fd;
    int ti;
    {
      std::lock_guard<std::mutex> lock(l->tmu);
      c->id = l->next_id++;
      ti = (int)(l->rr++ % (uint64_t)l->nthreads);
      c->owner = ti;
      l->conns[c->id] = c;
    }
    l->accepted.fetch_add(1, std::memory_order_relaxed);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = c;
    if (ti == 0) {
      epoll_ctl(t0.epfd, EPOLL_CTL_ADD, fd, &ev);
    } else {
      NlThread& t = l->threads[ti];
      {
        std::lock_guard<std::mutex> lock(t.cmu);
        t.cmds.push_back([c](NlThread& th) {
          if (c->dead) return;
          epoll_event e{};
          e.events = EPOLLIN;
          e.data.ptr = c;
          epoll_ctl(th.epfd, EPOLL_CTL_ADD, c->fd, &e);
        });
      }
      nl_wake(t);
    }
  }
}

void nl_thread_run(NlLoop* l, int ti) {
  NlThread& t = l->threads[ti];
  epoll_event evs[64];
  std::vector<std::pair<int, NlConn*>> writable;  // (prio, conn) per batch
  while (!l->stop.load(std::memory_order_relaxed)) {
    int n = epoll_wait(t.epfd, evs, 64, 100);
    l->iters.fetch_add(1, std::memory_order_relaxed);
    {
      std::vector<std::function<void(NlThread&)>> cmds;
      {
        std::lock_guard<std::mutex> lock(t.cmu);
        cmds.swap(t.cmds);
      }
      for (auto& cmd : cmds) cmd(t);
    }
    for (int i = 0; i < n; ++i) {
      void* p = evs[i].data.ptr;
      if (p == (void*)&t) {  // eventfd wakeup: drain it
        uint64_t v;
        ssize_t r = read(t.evfd, &v, sizeof(v));
        (void)r;
        continue;
      }
      if (p == (void*)l) {  // listener (thread 0 only)
        nl_accept(l, t);
        continue;
      }
      auto* c = static_cast<NlConn*>(p);
      if (c->dead) continue;  // a command in this batch destroyed it
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        // flush what we can first: a goodbye OK may still be in the
        // tail while the peer half-closed its side
        if (!(evs[i].events & EPOLLOUT) || !nl_flush(l, t, c)) {
          nl_destroy(l, t, c);
          continue;
        }
      }
      if (evs[i].events & EPOLLIN) nl_read(l, t, c);
      if (!c->dead && (evs[i].events & EPOLLOUT)) {
        // defer the tail flush: writable conns in THIS batch drain in
        // priority order below, not epoll arrival order — the
        // ByteScheduler-style writev scheduler (a conn may appear once
        // per batch; epoll never duplicates an fd within one wait)
        writable.emplace_back(0, c);
      }
    }
    if (!writable.empty()) {
      // snapshot each conn's priority ONCE under its write mutex (never
      // inside the comparator — a sort must not take locks per compare),
      // then drain lowest-priority-number first; conn id breaks ties so
      // the order is reproducible across batches
      for (auto& w : writable) {
        std::lock_guard<std::mutex> lw(w.second->wmu);
        w.first = w.second->prio;
      }
      std::sort(writable.begin(), writable.end(),
                [](const std::pair<int, NlConn*>& a,
                   const std::pair<int, NlConn*>& b) {
                  return a.first != b.first ? a.first < b.first
                                            : a.second->id < b.second->id;
                });
      for (auto& w : writable) {
        NlConn* c = w.second;
        if (c->dead) continue;
        if (!nl_flush(l, t, c)) nl_destroy(l, t, c);
      }
      writable.clear();
    }
    for (auto* g : t.graveyard) delete g;
    t.graveyard.clear();
  }
}

}  // namespace

// Start the event loop over an existing tv_listen handle: the loop takes
// over accepting (the listener fd goes non-blocking and into thread 0's
// epoll set). `nthreads` loop threads serve connections round-robin.
// The listener handle stays owned by the caller — close it only AFTER
// nl_stop. Returns nullptr on failure.
void* nl_start(void* listener, int nthreads) {
  auto* lst = static_cast<Listener*>(listener);
  if (!lst || nthreads < 1 || nthreads > 64) return nullptr;
  auto* l = new NlLoop();
  l->listener = lst;
  l->nthreads = nthreads;
  // telemetry stripes: one per loop thread + one shared by the pump and
  // punted repliers (index nthreads) — allocated once, before any thread
  // can record, so the hot path never checks for them
  l->stripes.reset(new NlStripe[(size_t)nthreads + 1]());
  int fl = fcntl(lst->fd, F_GETFL, 0);
  fcntl(lst->fd, F_SETFL, fl | O_NONBLOCK);
  bool ok = true;
  for (int i = 0; i < nthreads; ++i) {
    l->threads.emplace_back();
    NlThread& t = l->threads.back();
    t.idx = i;
    t.epfd = epoll_create1(0);
    t.evfd = eventfd(0, EFD_NONBLOCK);
    if (t.epfd < 0 || t.evfd < 0) { ok = false; break; }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = (void*)&t;
    epoll_ctl(t.epfd, EPOLL_CTL_ADD, t.evfd, &ev);
  }
  if (ok) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = (void*)l;
    ok = epoll_ctl(l->threads[0].epfd, EPOLL_CTL_ADD, lst->fd, &ev) == 0;
  }
  if (!ok) {
    for (auto& t : l->threads) {
      if (t.epfd >= 0) close(t.epfd);
      if (t.evfd >= 0) close(t.evfd);
    }
    delete l;
    return nullptr;
  }
  for (int i = 0; i < nthreads; ++i)
    l->threads[i].th = std::thread([l, i] { nl_thread_run(l, i); });
  return l;
}

// Pump upcall: block (GIL released by ctypes) until >= 1 complete request
// is ready, then fill the out arrays with up to `cap` of them. Returns the
// batch size (0 = timeout), or -1 once the loop is stopping AND drained.
// Each body pointer is owned by the caller until nl_body_free. `admits`
// (nullable — nl_poll passes nullptr for the legacy shape) receives each
// frame's native admission stamp: 0 = not classified, otherwise the
// admission floor at classification time + 1 for a frame the owner
// thread proved FRESH — Python may skip its per-key dedup scan for it
// only while its _read_gen still equals stamp - 1 (no apply landed in
// between; see the NlLoop admit members).
int nl_poll2(void* h, uint64_t* conn_ids, void** bodies, uint64_t* lens,
             uint64_t* admits, int cap, int timeout_ms) {
  auto* l = static_cast<NlLoop*>(h);
  // claimed entries' telemetry stamps: captured during the pop, recorded
  // AFTER qmu is released (qmu is a hot lock — the histogram math and the
  // slow-frame classification happen outside it). Reserved before the
  // lock so the pop allocates nothing while holding it.
  std::vector<std::pair<uint64_t, uint64_t>> tel;  // (read_ns, ready_ns)
  tel.reserve((size_t)(cap > 0 ? cap : 0));
  std::unique_lock<std::mutex> lock(l->qmu);
  if (l->ready.empty()) {
    if (l->stop.load(std::memory_order_relaxed)) return -1;
    // wait_until(system_clock), NOT wait_for: libstdc++ 10 lowers
    // wait_for to pthread_cond_clockwait, which this toolchain's TSan
    // does not intercept — the wait's internal unlock/relock becomes
    // invisible and every later qmu use reports as a phantom race /
    // double lock. system_clock waits lower to the intercepted
    // pthread_cond_timedwait. (A wall-clock jump can stretch one 100ms
    // poll tick; the pump loops, so that is harmless.)
    l->qcv.wait_until(lock, std::chrono::system_clock::now()
                                + std::chrono::milliseconds(timeout_ms),
                      [l] { return !l->ready.empty()
                                 || l->stop.load(std::memory_order_relaxed); });
  }
  if (l->ready.empty())
    return l->stop.load(std::memory_order_relaxed) ? -1 : 0;
  int n = 0;
  while (n < cap && !l->ready.empty()) {
    NlReq& r = l->ready.front();
    conn_ids[n] = r.conn_id;
    bodies[n] = r.body;
    lens[n] = r.len;
    if (admits != nullptr) admits[n] = r.admit_gen;
    tel.emplace_back(r.read_ns, r.ready_ns);
    ++n;
    l->ready.pop_front();
  }
  l->popped.fetch_add((uint64_t)n, std::memory_order_relaxed);
  lock.unlock();
  if (l->stats_on.load(std::memory_order_relaxed)) {
    // ready-queue wait (frame complete -> claimed by THIS pump call),
    // recorded into the pump's own stripe; the slow-frame check covers
    // the whole in-loop life of a pump-bound frame (read + wait). The
    // bodies are still native-owned until nl_body_free, so the trace
    // sniff reads live memory.
    uint64_t now = nl_now_ns();
    uint64_t thr = l->slow_ns.load(std::memory_order_relaxed);
    NlHist& qh = l->stripes[l->nthreads].hist[kNlHistQueueWait];
    for (int i = 0; i < n; ++i) {
      if (!tel[i].second) continue;  // frame read while stats were off
      uint64_t wait = now > tel[i].second ? now - tel[i].second : 0;
      nl_hist_add(qh, wait);
      if (thr && tel[i].first + wait > thr)
        nl_slow_record(l, conn_ids[i], (const char*)bodies[i], lens[i],
                       tel[i].first, wait, 0);
    }
  }
  return n;
}

// The pre-admission pump upcall shape, kept for drivers that never read
// admission stamps (sanitizer harness legs, older pumps): exactly
// nl_poll2 with no admits out-array.
int nl_poll(void* h, uint64_t* conn_ids, void** bodies, uint64_t* lens,
            int cap, int timeout_ms) {
  return nl_poll2(h, conn_ids, bodies, lens, nullptr, cap, timeout_ms);
}

// Reply to one request: an immediate non-blocking scatter-gather writev of
// the u64 length prefix + the caller's live buffers; whatever the socket
// would not take NOW is copied to the connection's tail buffer and flushed
// by the owner loop thread on EPOLLOUT (the caller's buffers are NEVER
// referenced after this returns). `close_after` severs the connection once
// the reply is fully on the wire (SHUTDOWN goodbyes). `prio` tags any
// staged tail for the priority writev drain: when several conns await
// EPOLLOUT service in one epoll batch, lower-priority-number tails flush
// first (bucket replies pass their bucket index — front-of-model bytes
// leave before tail-layer bytes). Returns 1, or 0 when the connection is
// already gone (the worker vanished mid-reply).
int nl_reply_vec(void* h, uint64_t conn_id, const void** bufs,
                 const uint64_t* lens, int n, int close_after, int prio) {
  auto* l = static_cast<NlLoop*>(h);
  NlConn* c;
  {
    // pin under a BRIEF table lock, then write under the per-conn wmu
    // only: a multi-MB reply must not serialize accepts/destroys/other
    // repliers behind the global table. nl_destroy waits out the pin
    // before freeing, so the struct and fd stay valid for the write.
    std::lock_guard<std::mutex> tlock(l->tmu);
    auto it = l->conns.find(conn_id);
    if (it == l->conns.end()) return 0;
    c = it->second;
    ++c->pins;
  }
  std::unique_lock<std::mutex> wlock(c->wmu);
  if (c->outstanding) --c->outstanding;
  // a staged tail drains as one FIFO string: its priority is its most
  // urgent frame's (min), never simply the LAST reply's — a tiny
  // low-urgency ack appended behind a front-of-model tail must not
  // demote it (or promote a tail-layer payload it rides behind). A
  // fresh (empty-tail) reply starts the conn's priority over.
  c->prio = c->wbuf.empty() ? prio : std::min(c->prio, prio);
  uint64_t total = 0;
  for (int i = 0; i < n; ++i) total += lens[i];
  uint64_t len_le = total;
  bool fail = false;
  if (c->wbuf.empty()) {
    // fast path: hand the live buffers straight to the kernel
    std::vector<iovec> iov;
    iov.reserve((size_t)n + 1);
    iov.push_back({&len_le, sizeof(len_le)});
    for (int i = 0; i < n; ++i)
      if (lens[i])
        iov.push_back({const_cast<void*>(bufs[i]), (size_t)lens[i]});
    size_t idx = 0;
    while (idx < iov.size()) {
      size_t cnt = iov.size() - idx;
      if (cnt > (size_t)IOV_MAX) cnt = (size_t)IOV_MAX;
      msghdr mh{};
      mh.msg_iov = &iov[idx];
      mh.msg_iovlen = cnt;
      ssize_t r = sendmsg(c->fd, &mh, MSG_DONTWAIT | MSG_NOSIGNAL);
      if (r < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        fail = true;
        break;
      }
      while (r > 0 && idx < iov.size()) {
        if ((size_t)r >= iov[idx].iov_len) {
          r -= (ssize_t)iov[idx].iov_len;
          ++idx;
        } else {
          iov[idx].iov_base = (char*)iov[idx].iov_base + r;
          iov[idx].iov_len -= (size_t)r;
          r = 0;
        }
      }
    }
    // stage only the unsent tail (zero bytes in the common case)
    uint64_t staged = 0;
    for (; idx < iov.size(); ++idx) {
      c->wbuf.append((const char*)iov[idx].iov_base, iov[idx].iov_len);
      staged += iov[idx].iov_len;
    }
    if (staged) nl_tail_staged(l, c, staged, true);
  } else if (c->wbuf.size() - c->woff > kNlMaxWbufBacklog) {
    // the peer has stopped reading while pipelining more requests:
    // refusing to buffer further replies bounds server memory (the
    // conn is severed as protocol abuse, like the outstanding cap)
    fail = true;
  } else {
    // a tail is already queued: append whole frames behind it in order
    nl_tail_staged(l, c, sizeof(len_le) + total, false);
    c->wbuf.append((const char*)&len_le, sizeof(len_le));
    for (int i = 0; i < n; ++i)
      if (lens[i]) c->wbuf.append((const char*)bufs[i], (size_t)lens[i]);
  }
  int ret = 1;
  if (fail) {
    // hard send error: sever; the owner thread observes EOF and reaps
    shutdown(c->fd, SHUT_RDWR);
    ret = 0;
  } else {
    if (close_after) c->close_after = true;
    if ((!c->wbuf.empty() || c->close_after) && !c->want_write) {
      // arm EPOLLOUT so the owner flushes the tail (or reaps the
      // goodbye: a writable socket fires it immediately)
      c->want_write = true;
      epoll_event ev{};
      ev.events = EPOLLIN | EPOLLOUT;
      ev.data.ptr = c;
      epoll_ctl(l->threads[c->owner].epfd, EPOLL_CTL_MOD, c->fd, &ev);
    }
  }
  wlock.unlock();
  {
    std::lock_guard<std::mutex> tlock(l->tmu);
    if (--c->pins == 0) l->pin_cv.notify_all();
  }
  return ret;
}

// Release one request body handed out by nl_poll (after the reply — the
// reply buffers may alias the request's tensors).
// pslint: owns: body -- THE release endpoint of the transfer contract:
// Python (the owner since nl_poll) is the only caller
void nl_body_free(void* h, void* body) {
  auto* l = static_cast<NlLoop*>(h);
  free(body);
  l->freed.fetch_add(1, std::memory_order_relaxed);
}

// Detach a connection from the loop and return its raw fd (blocking mode
// restored) — the SHM_SETUP path: a negotiated shared-memory lane needs a
// dedicated serve thread (its ring wait is already GIL-free native code;
// epoll cannot wait on ring cursors). Runs ON the owner thread via the
// command queue so it cannot race the read path. Returns -1 if the
// connection is gone (or the loop is stopping).
int nl_detach(void* h, uint64_t conn_id) {
  auto* l = static_cast<NlLoop*>(h);
  NlConn* c = nullptr;
  int ti = 0;
  {
    std::lock_guard<std::mutex> lock(l->tmu);
    auto it = l->conns.find(conn_id);
    if (it == l->conns.end()) return -1;
    c = it->second;
    ti = c->owner;
  }
  struct DetachState {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    bool abandoned = false;  // caller timed out: the command must CLOSE
    int out_fd = -1;         // the fd instead of handing it to nobody
  };
  // shared_ptr, not stack refs: if this wait times out, the command may
  // still run later (nl_stop executes leftovers) and must not write to a
  // dead frame
  auto st = std::make_shared<DetachState>();
  NlThread& t = l->threads[ti];
  {
    std::lock_guard<std::mutex> lock(t.cmu);
    t.cmds.push_back([l, conn_id, st](NlThread& th) {
      NlConn* c2 = nullptr;
      {
        std::unique_lock<std::mutex> tl(l->tmu);
        auto it = l->conns.find(conn_id);
        if (it != l->conns.end()) {
          c2 = it->second;
          l->conns.erase(it);
          // a replier mid-write holds the struct and fd: wait it out
          // (same discipline as nl_destroy) before handing the fd away
          while (c2->pins > 0) l->pin_cv.wait(tl);
        }
      }
      int fd = -1;
      if (c2 != nullptr) {
        epoll_ctl(th.epfd, EPOLL_CTL_DEL, c2->fd, nullptr);
        fd = c2->fd;
        int fl = fcntl(fd, F_GETFL, 0);
        fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);
        // pslint: owns: body -- mid-read frame only (same as
        // nl_destroy): a queued frame's pointer already left c2->body
        free(c2->body);
        c2->body = nullptr;
        c2->dead = true;
        th.graveyard.push_back(c2);
      }
      std::lock_guard<std::mutex> dl(st->mu);
      if (st->abandoned) {
        // the caller gave up: the conn is already out of the table, so
        // nl_stop would never close this fd — do it here or it leaks
        // (and the peer hangs forever with no EOF)
        if (fd >= 0) close(fd);
      } else {
        st->out_fd = fd;
      }
      st->done = true;
      st->cv.notify_one();
    });
  }
  nl_wake(t);
  std::unique_lock<std::mutex> lock(st->mu);
  // bounded wait: if the loop stopped before running the command,
  // nl_stop executes leftovers after joining — done still flips.
  // wait_until(system_clock), not wait_for: see nl_poll (TSan does not
  // intercept the clockwait that wait_for lowers to on this toolchain)
  st->cv.wait_until(lock, std::chrono::system_clock::now()
                              + std::chrono::seconds(10),
                    [&st] { return st->done; });
  if (!st->done) st->abandoned = true;  // late command closes the fd
  return st->done ? st->out_fd : -1;
}

// Stop admitting connections (the first leg of the drain): the listener
// leaves thread 0's epoll set and pending accepts are abandoned.
void nl_stop_accept(void* h) {
  auto* l = static_cast<NlLoop*>(h);
  l->accepting.store(false, std::memory_order_relaxed);
  epoll_ctl(l->threads[0].epfd, EPOLL_CTL_DEL, l->listener->fd, nullptr);
}

// Sever every live connection NOW (stop()/kill()): each peer observes EOF
// and each owner thread reaps its conns on the resulting events.
void nl_shutdown_conns(void* h) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->tmu);
  for (auto& kv : l->conns) shutdown(kv.second->fd, SHUT_RDWR);
}

// Requests not yet fully answered: ready-queue frames + frames claimed by
// Python (nl_poll'd, not yet nl_body_free'd) + connections with an
// unflushed reply tail. The drain in stop() waits for 0.
uint64_t nl_pending(void* h) {
  auto* l = static_cast<NlLoop*>(h);
  uint64_t claimed = l->popped.load(std::memory_order_relaxed)
                     - l->freed.load(std::memory_order_relaxed);
  uint64_t unflushed = 0;
  {
    std::lock_guard<std::mutex> lock(l->tmu);
    for (auto& kv : l->conns) {
      std::lock_guard<std::mutex> wl(kv.second->wmu);
      if (!kv.second->wbuf.empty()) ++unflushed;
    }
  }
  uint64_t ready;
  {
    std::lock_guard<std::mutex> lock(l->qmu);
    ready = (uint64_t)l->ready.size();
  }
  return ready + claimed + unflushed;
}

int nl_conn_count(void* h) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->tmu);
  return (int)l->conns.size();
}

// out[6]: iterations, accepted, requests, live conns, pending, claimed.
void nl_stats(void* h, uint64_t* out) {
  auto* l = static_cast<NlLoop*>(h);
  out[0] = l->iters.load(std::memory_order_relaxed);
  out[1] = l->accepted.load(std::memory_order_relaxed);
  out[2] = l->requests.load(std::memory_order_relaxed);
  out[3] = (uint64_t)nl_conn_count(h);
  out[4] = nl_pending(h);
  out[5] = l->popped.load(std::memory_order_relaxed)
           - l->freed.load(std::memory_order_relaxed);
}

// Configure the in-loop telemetry: `stats_on` gates every histogram
// stamp (off = the pre-telemetry hot path plus one relaxed load per
// frame), `slow_frame_ns` arms the slow-frame watchdog (0 = off) — any
// frame whose in-loop latency (read + queue wait, or read + native serve)
// exceeds it records a bounded ring entry for nl_slow_drain. Safe at any
// time; normally called once at service start from the PS_NL_STATS /
// PS_NL_SLOW_FRAME_MS knobs.
void nl_telemetry_config(void* h, int stats_on, uint64_t slow_frame_ns) {
  auto* l = static_cast<NlLoop*>(h);
  l->stats_on.store(stats_on ? 1 : 0, std::memory_order_relaxed);
  l->slow_ns.store(slow_frame_ns, std::memory_order_relaxed);
}

// Aggregate one in-loop histogram across every stripe. `which`: 0 = frame
// read latency, 1 = ready-queue wait, 2 = native READ-hit service time,
// 3 = EPOLLOUT tail-flush latency. Fills out[0]=total, out[1]=sum_ns,
// out[2]=min_ns (~0 when empty), out[3]=max_ns, out[4..4+nb) = raw bucket
// counts in the exact geometry of ps_tpu/obs/metrics.Histogram's defaults
// (lo=1e-6 s, hi=3600 s, 4 sub-buckets/octave — mergeable via state_add).
// Returns the bucket count (the caller sizes `out` as 4 + that), or -1
// for an unknown `which`.
int nl_hist_snapshot(void* h, int which, uint64_t* out) {
  auto* l = static_cast<NlLoop*>(h);
  if (which < 0 || which >= kNlHistCount) return -1;
  uint64_t total = 0, sum = 0, mn = ~0ull, mx = 0;
  for (int b = 0; b < kNlHistBuckets; ++b) out[4 + b] = 0;
  for (int s = 0; s <= l->nthreads; ++s) {
    NlHist& hh = l->stripes[s].hist[which];
    total += hh.total.load(std::memory_order_relaxed);
    sum += hh.sum_ns.load(std::memory_order_relaxed);
    uint64_t smn = hh.min_ns.load(std::memory_order_relaxed);
    uint64_t smx = hh.max_ns.load(std::memory_order_relaxed);
    if (smn < mn) mn = smn;
    if (smx > mx) mx = smx;
    for (int b = 0; b < kNlHistBuckets; ++b)
      out[4 + b] += hh.counts[b].load(std::memory_order_relaxed);
  }
  out[0] = total;
  out[1] = sum;
  out[2] = mn;
  out[3] = mx;
  return kNlHistBuckets;
}

// out[8]: current staged-tail backlog bytes, cumulative bytes ever
// staged, tails drained to empty, slow frames recorded, slow-ring
// overwrites (pump fell behind), stats_on, the armed slow threshold (ns),
// reserved 0.
void nl_stats_snapshot(void* h, uint64_t* out) {
  auto* l = static_cast<NlLoop*>(h);
  out[0] = l->tail_backlog.load(std::memory_order_relaxed);
  out[1] = l->tail_staged.load(std::memory_order_relaxed);
  out[2] = l->tail_flushes.load(std::memory_order_relaxed);
  out[3] = l->slow_total.load(std::memory_order_relaxed);
  out[4] = l->slow_dropped.load(std::memory_order_relaxed);
  out[5] = (uint64_t)l->stats_on.load(std::memory_order_relaxed);
  out[6] = l->slow_ns.load(std::memory_order_relaxed);
  out[7] = 0;
}

// Drain up to `cap` slow-frame ring entries (oldest first). `vals` holds
// 7 u64 slots per entry: conn id, kind byte, body size, read_ns, wait_ns,
// serve_ns, age_ns (record -> this drain). `tids` holds 2*20 bytes per
// entry: the NUL-terminated trace id then the parent span id sniffed from
// the frame's `tc` header (empty strings when the request was untraced).
// Returns the entry count.
int nl_slow_drain(void* h, uint64_t* vals, char* tids, int cap) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->slowmu);
  uint64_t now = nl_now_ns();
  int n = 0;
  while (n < cap && !l->slow_ring.empty()) {
    NlSlowFrame& f = l->slow_ring.front();
    uint64_t* v = vals + (size_t)n * 7;
    v[0] = f.conn;
    v[1] = f.kind;
    v[2] = f.size;
    v[3] = f.read_ns;
    v[4] = f.wait_ns;
    v[5] = f.serve_ns;
    v[6] = now > f.mono_ns ? now - f.mono_ns : 0;
    char* t = tids + (size_t)n * (2 * kNlTidLen);
    memcpy(t, f.trace, kNlTidLen);
    memcpy(t + kNlTidLen, f.span, kNlTidLen);
    l->slow_ring.pop_front();
    ++n;
  }
  return n;
}

// Test seam: record one KNOWN duration into stripe 0 of histogram
// `which` through the exact bucket math the loop's hot path uses — the
// fleet-merge exactness test feeds controlled samples through the real
// native bucketing and diffs the merged quantiles against numpy.
void nl_hist_record(void* h, int which, uint64_t ns) {
  auto* l = static_cast<NlLoop*>(h);
  if (which < 0 || which >= kNlHistCount) return;
  nl_hist_add(l->stripes[0].hist[which], ns);
}

// Begin shutdown WITHOUT freeing: loop threads exit, nl_poll drains the
// remaining ready frames and then returns -1. The Python pump exits on
// that -1; only then may nl_stop run.
void nl_begin_stop(void* h) {
  auto* l = static_cast<NlLoop*>(h);
  l->stop.store(true, std::memory_order_relaxed);
  l->accepting.store(false, std::memory_order_relaxed);
  for (auto& t : l->threads) nl_wake(t);
  l->qcv.notify_all();
}

// Join + free. Contract: no nl_poll/nl_reply_vec/nl_detach caller may be
// inside the handle (the Python driver joins its pump first). Bodies still
// claimed by Python are NOT freed here (Python may hold live views into
// them); unclaimed ready-queue bodies are.
// pslint: owns: body -- only mid-read conn bodies and UNCLAIMED ready
// entries are freed; claimed bodies stay Python-owned until
// nl_body_free (the exact UAF window PR 9 closed)
void nl_stop(void* h) {
  auto* l = static_cast<NlLoop*>(h);
  nl_begin_stop(h);
  for (auto& t : l->threads)
    if (t.th.joinable()) t.th.join();
  for (auto& t : l->threads) {
    // leftover commands (e.g. a detach posted as the loop stopped) must
    // still resolve their waiters
    std::vector<std::function<void(NlThread&)>> cmds;
    {
      std::lock_guard<std::mutex> lock(t.cmu);
      cmds.swap(t.cmds);
    }
    for (auto& cmd : cmds) cmd(t);
    for (auto* g : t.graveyard) delete g;
    t.graveyard.clear();
  }
  {
    std::lock_guard<std::mutex> lock(l->tmu);
    for (auto& kv : l->conns) {
      close(kv.second->fd);
      free(kv.second->body);
      delete kv.second;
    }
    l->conns.clear();
  }
  {
    std::lock_guard<std::mutex> lock(l->qmu);
    for (auto& r : l->ready) free(r.body);
    l->ready.clear();
  }
  for (auto& t : l->threads) {
    close(t.epfd);
    close(t.evfd);
  }
  delete l;
}

// ---------------------------------------------------------------------------
// Native read cache ("hot-key serving"): Python publishes complete,
// version-stamped reply frames; the loop answers byte-identical cacheable
// requests without an upcall. See the NlLoop cache members for the
// invalidation-generation contract.

// Enable (or resize) the cache: frames whose FIRST body byte equals
// `kind` are cacheable; `max_bytes` bounds key+reply memory (0 disables
// and clears). Safe at any time; normally called once at service start.
void nl_cache_config(void* h, int kind, uint64_t max_bytes) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->cachemu);
  l->cache_kind.store(max_bytes ? kind : -1, std::memory_order_relaxed);
  l->cache_limit = max_bytes;
  if (!max_bytes) {
    l->cache.clear();
    l->cache_fifo.clear();
    l->cache_bytes = 0;
  }
}

// Shared store body of every publish flavor (cachemu taken here): floor
// refusal, same-key replace (cond flag included in the match — an exact
// entry never shadows a spliced one), FIFO eviction, byte budget.
// Buffers are copied, never retained. Internal — not ABI.
namespace {

int nl_cache_store(void* h, const void* key, uint64_t klen,
                   const void* buf, uint64_t len, uint64_t gen,
                   const uint64_t* tags, int ntags, bool cond,
                   uint64_t vfloor) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->cachemu);
  uint64_t need = klen + len + 8;
  if (!l->cache_limit || gen < l->cache_floor || need > l->cache_limit) {
    l->cache_rejects.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  uint64_t hv = nl_cache_hash((const char*)key, klen);
  // replace an existing entry for the same request (a republish after
  // an invalidation cleared the table is the common case; same-key
  // duplicates must not accumulate)
  auto it = l->cache.find(hv);
  if (it != l->cache.end()) {
    std::shared_ptr<NlCacheEntry> old;
    for (auto& cand : it->second) {
      if (cand->cond == cond && cand->key.size() == klen &&
          memcmp(cand->key.data(), key, klen) == 0) {
        old = cand;  // copy FIRST: cand aliases the slot erase destroys
        break;
      }
    }
    if (old) nl_cache_erase(l, old, hv);
  }
  while (l->cache_bytes + need > l->cache_limit && !l->cache_fifo.empty()) {
    auto victim = l->cache_fifo.front();
    nl_cache_erase(l, victim,
                   nl_cache_hash(victim->key.data(), victim->key.size()));
  }
  auto e = std::make_shared<NlCacheEntry>();
  e->key.assign((const char*)key, klen);
  uint64_t len_le = len;
  e->reply.reserve(len + 8);
  e->reply.append((const char*)&len_le, sizeof(len_le));
  e->reply.append((const char*)buf, len);
  e->gen = gen;
  e->cond = cond;
  e->vfloor = vfloor;
  if (ntags > 0 && tags != nullptr) {
    e->tags.assign(tags, tags + ntags);
    std::sort(e->tags.begin(), e->tags.end());
  }
  l->cache[hv].push_back(e);
  l->cache_fifo.push_back(e);
  l->cache_bytes += klen + e->reply.size();
  l->cache_puts.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

}  // namespace

// Publish one reply with per-key invalidation tags: `tags`/`ntags` name
// the state slice the reply covers (the sparse service hashes each
// (table, row id) of the cached id-set) so nl_cache_invalidate_tags can
// drop ONLY intersecting entries. ntags == 0 publishes an untagged entry
// — the pre-tag behavior: dropped by every invalidation, tagged or not.
// Everything else is nl_cache_put's contract (floor refusal, budget,
// FIFO eviction, buffers copied never retained).
int nl_cache_put_tagged(void* h, const void* key, uint64_t klen,
                        const void* buf, uint64_t len, uint64_t gen,
                        const uint64_t* tags, int ntags) {
  return nl_cache_store(h, key, klen, buf, len, gen, tags, ntags,
                        false, 0);
}

// Publish one conditional (NOT_MODIFIED) reply with a version floor:
// `key`/`klen` are the CONDITIONAL request's body bytes — the "cond"
// token is sniffed and its digits excised HERE, with the same bounded
// tail scan the serve side runs, so request and publish derive the
// spliced key by identical rules and can never disagree. `vfloor` is
// the server version the reply stamps: any later conditional request
// whose sniffed version >= vfloor gets this reply natively (the pump's
// own unchanged-target comparison). A key with no parseable token falls
// back to an exact-match publish — strictly conservative: byte-repeats
// still serve, no floor sharing. Floor refusal, budget, FIFO eviction
// and tag semantics are nl_cache_put_tagged's contract unchanged.
int nl_cache_put_cond(void* h, const void* key, uint64_t klen,
                      const void* buf, uint64_t len, uint64_t gen,
                      const uint64_t* tags, int ntags, uint64_t vfloor) {
  uint64_t v = 0, dlo = 0, dhi = 0;
  if (!nl_cond_token((const char*)key, klen, &v, &dlo, &dhi))
    return nl_cache_store(h, key, klen, buf, len, gen, tags, ntags,
                          false, 0);
  std::string spliced;
  spliced.reserve(klen - (dhi - dlo));
  spliced.append((const char*)key, dlo);
  spliced.append((const char*)key + dhi, klen - dhi);
  return nl_cache_store(h, spliced.data(), spliced.size(), buf, len,
                        gen, tags, ntags, true, vfloor);
}

// Publish one reply: `key`/`klen` are the request body bytes the entry
// answers (exact match), `buf`/`len` the reply frame (the length prefix
// is prepended here), `gen` the publish generation captured UNDER the
// engine lock with the snapshot. Returns 1 stored, 0 refused — gen below
// the invalidation floor (an apply superseded this snapshot), cache
// disabled, or the entry alone over budget. Oldest entries evict first
// when the budget would overflow. Caller's buffers are copied; never
// retained.
int nl_cache_put(void* h, const void* key, uint64_t klen, const void* buf,
                 uint64_t len, uint64_t gen) {
  return nl_cache_put_tagged(h, key, klen, buf, len, gen, nullptr, 0);
}

// Invalidation-on-apply: raise the publish floor to `gen` and drop every
// cached entry. Called by the engine (under its apply lock) on every
// committed state change a cached reply could observe — a put racing
// this call either lands first (cleared here) or arrives after with a
// pre-bump gen (refused at the floor). Entries mid-serve survive via
// their shared_ptr; new lookups miss immediately.
void nl_cache_invalidate(void* h, uint64_t gen) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->cachemu);
  if (gen > l->cache_floor) l->cache_floor = gen;
  if (!l->cache_fifo.empty()) {
    l->cache.clear();
    l->cache_fifo.clear();
    l->cache_bytes = 0;
  }
  l->cache_invals.fetch_add(1, std::memory_order_relaxed);
}

// Per-key invalidation (the sparse read path's ROADMAP follow-up): raise
// the publish floor to `gen` — exactly nl_cache_invalidate's race
// contract, so an in-flight pre-apply publish of ANY id-set is still
// refused — but drop only the entries whose tag set intersects
// `tags`/`ntags` (plus untagged entries, which claim nothing and must
// stay conservative). Cached replies for id-sets disjoint from the
// applied rows keep serving natively: their row bytes are untouched by
// this apply — only their version STAMP now trails, which the bounded-
// staleness contract already treats as grounds for fallback, never as a
// correctness violation.
void nl_cache_invalidate_tags(void* h, uint64_t gen, const uint64_t* tags,
                              int ntags) {
  auto* l = static_cast<NlLoop*>(h);
  std::vector<uint64_t> want(tags, tags + (ntags > 0 ? ntags : 0));
  std::sort(want.begin(), want.end());
  std::lock_guard<std::mutex> lock(l->cachemu);
  if (gen > l->cache_floor) l->cache_floor = gen;
  // ONE partition pass over the fifo (survivors keep their eviction
  // order), victims unlinked from their hash bucket directly — never
  // nl_cache_erase's per-victim fifo scan, which would make a mass
  // invalidation O(victims x entries) while every hit/publish waits on
  // cachemu
  std::deque<std::shared_ptr<NlCacheEntry>> keep;
  uint64_t freed = 0;
  for (auto& e : l->cache_fifo) {
    bool hit = e->tags.empty();
    if (!hit) {
      // both sides sorted: one linear merge pass per entry
      size_t i = 0, j = 0;
      while (i < e->tags.size() && j < want.size()) {
        if (e->tags[i] == want[j]) {
          hit = true;
          break;
        }
        if (e->tags[i] < want[j]) ++i;
        else ++j;
      }
    }
    if (!hit) {
      keep.push_back(e);
      continue;
    }
    freed += e->key.size() + e->reply.size();
    uint64_t hv = nl_cache_hash(e->key.data(), e->key.size());
    auto it = l->cache.find(hv);
    if (it != l->cache.end()) {
      auto& v = it->second;
      for (size_t i = 0; i < v.size(); ++i) {
        if (v[i] == e) {
          v.erase(v.begin() + i);
          break;
        }
      }
      if (v.empty()) l->cache.erase(it);
    }
  }
  l->cache_fifo.swap(keep);
  l->cache_bytes -= freed;
  l->cache_invals.fetch_add(1, std::memory_order_relaxed);
}

// out[9]: hits, misses, puts, rejects, invalidations, entries, bytes,
// floor, cond_hits. Hits are frames answered with zero upcalls; misses
// are cacheable-kind frames that fell through to the pump; cond_hits is
// the subset of hits served from a version-floor (NOT_MODIFIED) entry.
void nl_cache_stats(void* h, uint64_t* out) {
  auto* l = static_cast<NlLoop*>(h);
  out[0] = l->cache_hits.load(std::memory_order_relaxed);
  out[1] = l->cache_miss.load(std::memory_order_relaxed);
  out[2] = l->cache_puts.load(std::memory_order_relaxed);
  out[3] = l->cache_rejects.load(std::memory_order_relaxed);
  out[4] = l->cache_invals.load(std::memory_order_relaxed);
  out[8] = l->cache_cond_hits.load(std::memory_order_relaxed);
  std::lock_guard<std::mutex> lock(l->cachemu);
  out[5] = (uint64_t)l->cache_fifo.size();
  out[6] = l->cache_bytes;
  out[7] = l->cache_floor;
}

// ---------------------------------------------------------------------------
// Native push admission ("zero-upcall push plane"): Python mirrors each
// worker's dedup ledger plus the engine's replay-ack / role-refusal
// reply frames; the loop classifies PUSH frames on the owner thread —
// pure replays and refusals answered with zero upcalls, fresh frames
// stamped and queued. See the NlLoop admit members for the floor
// contract.

// Arm admission for frames whose FIRST body byte equals `kind` (the
// wire kind — tv.PUSH or tv.ROW_PUSH); kind < 0 disables and clears the
// ledger and both templates. Safe at any time; normally called once at
// service start.
void nl_admit_config(void* h, int kind) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->admitmu);
  l->admit_kind.store(kind < 0 ? -1 : kind, std::memory_order_relaxed);
  if (kind < 0) {
    l->admit.clear();
    l->admit_ack.clear();
    l->admit_refusal.clear();
  }
}

// Publish one worker's ledger mirror entry: `nonce` its CURRENT push
// nonce, `lo` the settled bound (every key the worker pushes settled at
// seq <= lo), `hi` the recorded bound (no recorded seq above hi), `gen`
// the publish generation captured under the engine lock AFTER the
// apply's invalidation bump. Returns 1 stored, 0 refused — admission
// off, gen below the floor (a later apply superseded this snapshot), or
// a malformed nonce/window. A same-nonce republish keeps the larger
// lo/hi (frames stamped fresh between the apply and this publish have
// already advanced the pending bound past the ledger's).
int nl_admit_put(void* h, uint32_t worker, const void* nonce,
                 uint64_t nonce_len, uint64_t lo, uint64_t hi,
                 uint64_t gen) {
  auto* l = static_cast<NlLoop*>(h);
  if (nonce == nullptr || nonce_len == 0 ||
      nonce_len > (uint64_t)kNlAdmitNonceMax || lo > hi)
    return 0;
  std::lock_guard<std::mutex> lock(l->admitmu);
  if (l->admit_kind.load(std::memory_order_relaxed) < 0 ||
      gen < l->admit_floor)
    return 0;
  NlAdmitEntry& e = l->admit[worker];
  if (e.nonce.size() == nonce_len &&
      memcmp(e.nonce.data(), nonce, nonce_len) == 0) {
    if (lo > e.lo) e.lo = lo;
    if (hi > e.hi) e.hi = hi;
  } else {
    e.nonce.assign((const char*)nonce, nonce_len);
    e.lo = lo;
    e.hi = hi;
  }
  return 1;
}

// Publish the replay-ack template: the COMPLETE reply frame (no length
// prefix; prepended here, like nl_cache_put) the pump would send for a
// full-dedup replay, captured under the engine lock with the version
// stamp the ledger's `lo` bounds cover. The worker id at frame bytes
// 1..5 is patched per serve. len == 0 clears. Returns 1 stored, 0
// refused — gen below the floor (an apply changed the version this
// template reports) or a frame too short to patch.
int nl_admit_set_ack(void* h, const void* buf, uint64_t len,
                     uint64_t gen) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->admitmu);
  if (len == 0) {
    l->admit_ack.clear();
    return 1;
  }
  if (buf == nullptr || len < 13 || gen < l->admit_floor) return 0;
  uint64_t len_le = len;
  l->admit_ack.clear();
  l->admit_ack.reserve(len + 8);
  l->admit_ack.append((const char*)&len_le, sizeof(len_le));
  l->admit_ack.append((const char*)buf, len);
  return 1;
}

// Publish (or clear, len == 0) the role-refusal template: the typed ERR
// every admissible frame gets while this shard must refuse pushes
// (backup role, fenced zombie). NOT floor-gated — role does not change
// on applies; promotion re-seeds through nl_admit_reset first.
int nl_admit_set_refusal(void* h, const void* buf, uint64_t len) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->admitmu);
  if (len == 0) {
    l->admit_refusal.clear();
    return 1;
  }
  if (buf == nullptr || len < 13) return 0;
  uint64_t len_le = len;
  l->admit_refusal.clear();
  l->admit_refusal.reserve(len + 8);
  l->admit_refusal.append((const char*)&len_le, sizeof(len_le));
  l->admit_refusal.append((const char*)buf, len);
  return 1;
}

// Invalidation-on-apply (the push twin of nl_cache_invalidate): raise
// the floor to `gen` and drop the version-stamped ack template. The
// LEDGER persists — its bounds only ever advance, so a stale entry is
// conservative (it punts frames a fresher mirror would ack, never the
// reverse), while dropping it would punt EVERY frame until the next
// publish.
void nl_admit_invalidate(void* h, uint64_t gen) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->admitmu);
  if (gen > l->admit_floor) l->admit_floor = gen;
  l->admit_ack.clear();
}

// Structural re-seed (promotion, fence, migrate, pause/resume): raise
// the floor and drop the ledger AND both templates. The caller
// republishes whatever the new role/state allows.
void nl_admit_reset(void* h, uint64_t gen) {
  auto* l = static_cast<NlLoop*>(h);
  std::lock_guard<std::mutex> lock(l->admitmu);
  if (gen > l->admit_floor) l->admit_floor = gen;
  l->admit.clear();
  l->admit_ack.clear();
  l->admit_refusal.clear();
}

// out[8]: acks (native replay OKs), refusals (native typed ERRs), fresh
// (frames stamped + queued), punts (admissible frames the pump had to
// classify), ledger entries, floor, ack armed, refusal armed.
void nl_admit_stats(void* h, uint64_t* out) {
  auto* l = static_cast<NlLoop*>(h);
  out[0] = l->admit_acks.load(std::memory_order_relaxed);
  out[1] = l->admit_refusals.load(std::memory_order_relaxed);
  out[2] = l->admit_fresh.load(std::memory_order_relaxed);
  out[3] = l->admit_punts.load(std::memory_order_relaxed);
  std::lock_guard<std::mutex> lock(l->admitmu);
  out[4] = (uint64_t)l->admit.size();
  out[5] = l->admit_floor;
  out[6] = l->admit_ack.empty() ? 0 : 1;
  out[7] = l->admit_refusal.empty() ? 0 : 1;
}

}  // extern "C"
