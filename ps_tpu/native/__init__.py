"""Native (C++) components, compiled on demand with the system toolchain.

The reference keeps its control-plane van in C++ (SURVEY.md §3 rows 9/12);
ps_tpu does the same for the heartbeat van — :func:`load` compiles
``van.cpp`` to a shared library once (cached beside the source, keyed on the
source hash) and returns a ``ctypes.CDLL``.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def _cache_dir() -> str:
    """Writable build-artifact cache OUTSIDE the package tree (the install
    may be read-only, and .so binaries do not belong in the source tree)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    d = os.path.join(base, "ps_tpu", "native")
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str = "van") -> ctypes.CDLL:
    """Compile (if needed) and dlopen the named native component."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        lib = os.path.join(_cache_dir(), f"lib{name}-{digest}.so")
        if not os.path.exists(lib):
            tmp = lib + f".tmp{os.getpid()}"
            cmd = [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                "-pthread", "-o", tmp, src,
            ]
            try:
                subprocess.run(
                    cmd, check=True, capture_output=True, text=True
                )
            except subprocess.CalledProcessError as e:
                raise NativeBuildError(
                    f"building {name}.cpp failed:\n{e.stderr}"
                ) from None
            os.replace(tmp, lib)  # atomic: concurrent builders race safely
        _cache[name] = ctypes.CDLL(lib)
        return _cache[name]
