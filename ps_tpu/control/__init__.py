"""Host control plane: the part of the reference's van that stays host-side.

Data-plane tensor traffic rides XLA collectives (SURVEY.md §3 row 9); what
this package keeps is liveness and failure detection — heartbeats between
the processes of a multi-process run, so a dead process surfaces as a typed
:class:`WorkerFailureError` instead of a hung collective.
"""

from ps_tpu.control.heartbeat import (
    FailureDetector,
    HeartbeatClient,
    HeartbeatServer,
    WorkerFailureError,
)

__all__ = [
    "FailureDetector",
    "HeartbeatClient",
    "HeartbeatServer",
    "WorkerFailureError",
]
