"""Python driver for the native epoll event-loop data plane.

The serve side of the van historically ran one Python thread per worker
connection (:class:`~ps_tpu.backends.van_service.VanService`). At fleet
scale the GIL and per-thread stacks become the ceiling — the reference
family (ps-lite's ZMQVan, BytePS's core) runs its receive/send pump as a
native event loop with the interpreter out of the hot path. This module
wraps that loop (the ``nl_*`` ABI in ps_tpu/native/van.cpp): accept,
frame reads, and scatter-gather reply writes run on a small fixed pool of
native threads (default 1) with the GIL untouched; Python's involvement
shrinks to ONE pump thread that calls :meth:`NativeEventLoop.poll` (GIL
released for the wait) and receives a BATCH of complete request frames to
decode/dispatch — one upcall per batch, not one thread per connection.

Ownership contract (mirrors the C side):

- a polled request's body buffer belongs to Python until :meth:`free`
  (replies may alias the request's tensors, so free AFTER the reply);
- :meth:`reply` never retains the caller's buffers — whatever the socket
  does not take immediately is copied to a native tail buffer and flushed
  by the loop on EPOLLOUT;
- :meth:`close` may only run after the pump thread exited (poll returned
  ``None``); the driver serializes that with ``begin_stop``.

Linux-only (epoll); :func:`available` gates the fallback to the classic
thread-per-connection serve path.
"""

from __future__ import annotations

import ctypes
import sys
import threading
from typing import List, Optional, Tuple

import numpy as np

from ps_tpu.native import load

#: max requests one poll() hands back — the upcall batch bound (also the
#: natural batch-size cap the ps_van_upcall_batch histogram observes)
MAX_BATCH = 64

#: in-loop histogram geometry — the EXACT mirror of
#: ps_tpu/obs/metrics.Histogram's defaults (lo=1e-6 s, hi=3600 s, 4
#: sub-buckets per octave), kept in lockstep with van.cpp's kNlHist*
#: constants so a native snapshot's raw buckets merge losslessly into
#: the registry and the coordinator's fleet quantiles
NL_HIST_LO = 1e-6
NL_HIST_HI = 3600.0
NL_HIST_BUCKETS = 129  # kNlHistNb + underflow + overflow

#: nl_hist_snapshot `which` index -> the TransportStats histogram key it
#: feeds (position-coupled with van.cpp's kNlHist* indices)
NL_HISTS = (
    (0, "nl_read_frame_s"),   # first byte -> frame complete
    (1, "nl_queue_wait_s"),   # frame complete -> claimed by the pump
    (2, "nl_read_hit_s"),     # frame complete -> native cache reply written
    (3, "nl_flush_s"),        # tail staged -> EPOLLOUT drain done
)

#: fixed per-entry layout of nl_slow_drain's out buffers
_SLOW_VALS = 7   # conn, kind, size, read_ns, wait_ns, serve_ns, age_ns
_SLOW_TID = 20   # NUL-terminated id slot (trace then span per entry)

_configured = None


def _lib():
    global _configured
    lib = load("van")
    if _configured is lib:
        return lib
    # one of THE three ctypes declaration sites (with heartbeat._lib and
    # tensor_van._lib): every argtypes/restype row here is machine-diffed
    # against van.cpp's extern "C" signatures by pslint PSL6xx
    lib.nl_start.restype = ctypes.c_void_p
    lib.nl_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.nl_poll.restype = ctypes.c_int
    lib.nl_poll.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int, ctypes.c_int,
    ]
    lib.nl_poll2.restype = ctypes.c_int
    lib.nl_poll2.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int,
    ]
    lib.nl_reply_vec.restype = ctypes.c_int
    lib.nl_reply_vec.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int,
        ctypes.c_int,
    ]
    lib.nl_body_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.nl_detach.restype = ctypes.c_int
    lib.nl_detach.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.nl_stop_accept.argtypes = [ctypes.c_void_p]
    lib.nl_shutdown_conns.argtypes = [ctypes.c_void_p]
    lib.nl_pending.restype = ctypes.c_uint64
    lib.nl_pending.argtypes = [ctypes.c_void_p]
    lib.nl_conn_count.restype = ctypes.c_int
    lib.nl_conn_count.argtypes = [ctypes.c_void_p]
    lib.nl_stats.argtypes = [ctypes.c_void_p,
                             ctypes.POINTER(ctypes.c_uint64)]
    lib.nl_begin_stop.argtypes = [ctypes.c_void_p]
    lib.nl_stop.argtypes = [ctypes.c_void_p]
    lib.nl_cache_config.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_uint64]
    lib.nl_cache_put.restype = ctypes.c_int
    lib.nl_cache_put.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.nl_cache_invalidate.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.nl_cache_stats.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.nl_cache_put_tagged.restype = ctypes.c_int
    lib.nl_cache_put_tagged.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.nl_cache_invalidate_tags.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.nl_cache_put_cond.restype = ctypes.c_int
    lib.nl_cache_put_cond.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_uint64,
    ]
    lib.nl_admit_config.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.nl_admit_put.restype = ctypes.c_int
    lib.nl_admit_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64,
    ]
    lib.nl_admit_set_ack.restype = ctypes.c_int
    lib.nl_admit_set_ack.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_uint64, ctypes.c_uint64]
    lib.nl_admit_set_refusal.restype = ctypes.c_int
    lib.nl_admit_set_refusal.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_uint64]
    lib.nl_admit_invalidate.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.nl_admit_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.nl_admit_stats.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.nl_telemetry_config.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_uint64]
    lib.nl_hist_snapshot.restype = ctypes.c_int
    lib.nl_hist_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
    lib.nl_stats_snapshot.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint64)]
    lib.nl_slow_drain.restype = ctypes.c_int
    lib.nl_slow_drain.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_void_p,
        ctypes.c_int,
    ]
    lib.nl_hist_record.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.c_uint64]
    lib.tv_adopt_fd.restype = ctypes.c_void_p
    lib.tv_adopt_fd.argtypes = [ctypes.c_int]
    _configured = lib
    return lib


def available() -> bool:
    """True when the native event loop can run here: Linux (epoll) and a
    van build exposing the ``nl_*`` symbols."""
    if not sys.platform.startswith("linux"):
        return False
    try:
        return hasattr(_lib(), "nl_start")
    except Exception:
        return False


class NativeEventLoop:
    """One running ``nl_*`` loop over an existing van Listener.

    The listener stays owned by the caller and must outlive :meth:`close`
    (the loop only borrows its fd). All methods are safe from the pump
    thread; :meth:`close` additionally requires the pump to have exited.
    """

    def __init__(self, listener, threads: int = 1):
        self._lib = _lib()
        self._lock = threading.Lock()
        # liveness pin, mirroring the C side's per-conn pin: reply() must
        # NOT hold the driver lock across its native call (a multi-MB
        # reply tail memcpy would serialize every other caller behind
        # it); instead callers pin the handle, run lock-free, unpin —
        # and close() waits out the pins before freeing
        self._cv = threading.Condition(self._lock)
        self._users = 0
        self._closed = False
        h = self._lib.nl_start(listener._h, int(threads))
        if not h:
            raise OSError("native event loop failed to start")
        self._h = h
        self.threads = int(threads)
        self._ids = (ctypes.c_uint64 * MAX_BATCH)()
        self._ptrs = (ctypes.c_void_p * MAX_BATCH)()
        self._lens = (ctypes.c_uint64 * MAX_BATCH)()
        self._admits = (ctypes.c_uint64 * MAX_BATCH)()
        self._stats_out = (ctypes.c_uint64 * 6)()
        self._cache_out = (ctypes.c_uint64 * 9)()
        self._admit_out = (ctypes.c_uint64 * 8)()
        self._hist_out = (ctypes.c_uint64 * (4 + NL_HIST_BUCKETS))()
        self._nl_out = (ctypes.c_uint64 * 8)()
        self._slow_vals = (ctypes.c_uint64 * (_SLOW_VALS * MAX_BATCH))()
        self._slow_tids = ctypes.create_string_buffer(
            2 * _SLOW_TID * MAX_BATCH)
        # bodies currently claimed by Python (poll handed them out, free
        # not yet called): makes free() IDEMPOTENT — an error-path caller
        # can release unconditionally without risking a double free
        self._claimed = set()

    # -- pump side -----------------------------------------------------------

    def poll(self, timeout_ms: int = 100
             ) -> Optional[List[Tuple[int, memoryview, int, int]]]:
        """Wait (GIL released) for ready requests. Returns a list of
        ``(conn_id, frame_view, body_ptr, admit_gen)`` — possibly empty
        on timeout — or None once the loop is stopping and fully drained
        (the pump's exit signal). ``admit_gen`` is the native admission
        stamp: 0 for an unclassified frame, otherwise floor + 1 for a
        PUSH frame the owner thread proved fresh (trust it only while
        the engine's read generation still equals ``admit_gen - 1``).
        The frame view aliases native memory owned by the caller until
        :meth:`free`."""
        if self._closed:  # racing close(): the loop is gone
            return None
        n = self._lib.nl_poll2(self._h, self._ids, self._ptrs, self._lens,
                               self._admits, MAX_BATCH, int(timeout_ms))
        if n < 0:
            return None
        out = []
        with self._lock:
            for i in range(n):
                ptr, ln = self._ptrs[i], self._lens[i]
                if ln:
                    view = memoryview(
                        (ctypes.c_char * ln).from_address(ptr)).cast("B")
                else:
                    view = memoryview(b"")
                self._claimed.add(int(ptr))
                out.append((int(self._ids[i]), view, int(ptr),
                            int(self._admits[i])))
        return out

    def reply(self, conn_id: int, payload, close_after: bool = False,
              priority: int = 0) -> bool:
        """Send one reply frame — a contiguous bytes/bytearray or the
        zero-copy ``(header, chunks)`` parts form. The buffers are used
        only for the duration of the call (an unsent tail is copied
        native-side). ``priority`` tags any staged tail for the loop's
        priority writev drain (lower flushes first; bucket replies pass
        their bucket index so front-of-model bytes leave before the tail
        layers'). False = the connection is gone."""
        if isinstance(payload, tuple):
            header, chunks = payload
            views = [np.frombuffer(header, np.uint8)]
            views += [np.frombuffer(c, np.uint8) for c in chunks if len(c)]
        else:
            views = [np.frombuffer(payload, np.uint8)]
        n = len(views)
        ptrs = (ctypes.c_void_p * n)(*(v.ctypes.data for v in views))
        lens = (ctypes.c_uint64 * n)(*(v.nbytes for v in views))
        if not self._pin():
            return False
        try:
            ok = self._lib.nl_reply_vec(self._h, conn_id, ptrs, lens, n,
                                        1 if close_after else 0,
                                        int(priority))
        finally:
            self._unpin()
        del views  # pinned the sources for exactly the call's duration
        return bool(ok)

    def _pin(self) -> bool:
        with self._cv:
            if self._closed:
                return False
            self._users += 1
            return True

    def _unpin(self) -> None:
        with self._cv:
            self._users -= 1
            if self._users == 0:
                self._cv.notify_all()

    def free(self, body_ptr: int) -> None:
        """Release one request body (AFTER the reply — it may alias).
        Idempotent: a body already freed (or never claimed) is a no-op,
        so error paths can release unconditionally."""
        with self._lock:
            if self._closed or body_ptr not in self._claimed:
                return
            self._claimed.discard(body_ptr)
            self._lib.nl_body_free(self._h, body_ptr)

    def detach(self, conn_id: int) -> int:
        """Pull a connection out of the loop; returns its raw fd in
        blocking mode (-1 = connection already gone). The SHM_SETUP
        upgrade path adopts the fd into a classic Channel + serve
        thread."""
        if not self._pin():  # detach can wait on the owner thread — it
            return -1        # must not hold the driver lock meanwhile
        try:
            return int(self._lib.nl_detach(self._h, conn_id))
        finally:
            self._unpin()

    # -- native read cache (zero-upcall pull serving) -------------------------

    def cache_config(self, kind: int, max_bytes: int) -> None:
        """Enable the native read cache: frames whose first body byte is
        ``kind`` (the wire kind — tv.READ) are answered inside the loop
        threads on an exact-byte match, with ``max_bytes`` bounding
        key+reply memory (0 disables)."""
        with self._lock:
            if not self._closed:
                self._lib.nl_cache_config(self._h, int(kind),
                                          int(max_bytes))

    def cache_put(self, key: bytes, reply, gen: int,
                  tags=None) -> bool:
        """Publish one reply frame for the request bytes ``key`` at
        publish generation ``gen`` (captured under the engine lock with
        the snapshot the reply serializes). ``tags`` optionally names the
        state slice the reply covers (u64s — the sparse service's
        per-(table, row) hashes) so :meth:`cache_invalidate` with tags
        can drop only intersecting entries; None publishes an untagged
        entry that every invalidation drops (the conservative default).
        False = refused: the cache is off, the entry is over budget, or —
        the invalidation race — an apply already raised the floor past
        ``gen``. Buffers are copied native-side; never retained."""
        kv = np.frombuffer(key, np.uint8)
        rv = np.frombuffer(reply, np.uint8)
        if not self._pin():
            return False
        try:
            if tags:
                arr = (ctypes.c_uint64 * len(tags))(*[int(t) for t in tags])
                ok = self._lib.nl_cache_put_tagged(
                    self._h, kv.ctypes.data, kv.nbytes, rv.ctypes.data,
                    rv.nbytes, int(gen), arr, len(tags))
            else:
                ok = self._lib.nl_cache_put(self._h, kv.ctypes.data,
                                            kv.nbytes, rv.ctypes.data,
                                            rv.nbytes, int(gen))
        finally:
            self._unpin()
        del kv, rv  # pinned the sources for exactly the call's duration
        return bool(ok)

    def cache_put_cond(self, key: bytes, reply, gen: int, tags=None,
                       vfloor: int = 0) -> bool:
        """Publish one conditional (NOT_MODIFIED) reply for the
        CONDITIONAL request bytes ``key``: the native side sniffs the
        request's ``"cond":`` token, excises its digits, and stores the
        spliced key with version floor ``vfloor`` (the server version the
        reply stamps) — any later conditional request whose sniffed known
        version >= ``vfloor`` is answered from this entry with zero
        upcalls, exactly the pump's unchanged-target comparison. Floor
        refusal, budget, eviction and ``tags`` semantics match
        :meth:`cache_put`."""
        kv = np.frombuffer(key, np.uint8)
        rv = np.frombuffer(reply, np.uint8)
        if not self._pin():
            return False
        try:
            arr, n = None, 0
            if tags:
                arr = (ctypes.c_uint64 * len(tags))(*[int(t) for t in tags])
                n = len(tags)
            ok = self._lib.nl_cache_put_cond(
                self._h, kv.ctypes.data, kv.nbytes, rv.ctypes.data,
                rv.nbytes, int(gen), arr, n, int(vfloor))
        finally:
            self._unpin()
        del kv, rv  # pinned the sources for exactly the call's duration
        return bool(ok)

    def cache_invalidate(self, gen: int, tags=None) -> None:
        """Invalidation-on-apply: raise the publish floor to ``gen`` and
        drop cached entries — every entry when ``tags`` is None, else
        only entries whose tag set intersects ``tags`` (untagged entries
        always drop: they claim nothing). Pin-based (not the driver
        lock): this runs on the engine apply path and must never queue
        behind a multi-MB reply."""
        if not self._pin():
            return
        try:
            if tags:
                arr = (ctypes.c_uint64 * len(tags))(*[int(t) for t in tags])
                self._lib.nl_cache_invalidate_tags(self._h, int(gen), arr,
                                                   len(tags))
            else:
                self._lib.nl_cache_invalidate(self._h, int(gen))
        finally:
            self._unpin()

    def cache_stats(self) -> dict:
        """Cumulative cache counters: hits (zero-upcall replies), misses
        (cacheable frames that took the pump path), puts, rejects,
        invalidations, live entries, bytes held, the invalidation floor,
        and cond_hits (the subset of hits served from a version-floor
        NOT_MODIFIED entry)."""
        with self._lock:
            if self._closed:
                return {"hits": 0, "misses": 0, "puts": 0, "rejects": 0,
                        "invalidations": 0, "entries": 0, "bytes": 0,
                        "floor": 0, "cond_hits": 0}
            self._lib.nl_cache_stats(self._h, self._cache_out)
            o = self._cache_out
            return {"hits": int(o[0]), "misses": int(o[1]),
                    "puts": int(o[2]), "rejects": int(o[3]),
                    "invalidations": int(o[4]), "entries": int(o[5]),
                    "bytes": int(o[6]), "floor": int(o[7]),
                    "cond_hits": int(o[8])}

    # -- native push admission (zero-upcall push plane) ------------------------

    def admit_config(self, kind: int) -> None:
        """Arm push admission: frames whose first body byte is ``kind``
        (the wire kind — tv.PUSH or tv.ROW_PUSH) are classified inside
        the loop threads against the ledger mirror (kind < 0 disables
        and clears the ledger and both reply templates)."""
        with self._lock:
            if not self._closed:
                self._lib.nl_admit_config(self._h, int(kind))

    def admit_put(self, worker: int, nonce: bytes, lo: int, hi: int,
                  gen: int) -> bool:
        """Publish one worker's ledger mirror entry: ``nonce`` its
        current push nonce, ``lo`` the settled dedup bound (every key
        the worker pushes settled at seq <= lo), ``hi`` the recorded
        bound, ``gen`` the publish generation captured under the engine
        lock. False = refused (admission off, an apply already raised
        the floor past ``gen``, or a malformed nonce/window). The nonce
        is copied native-side; never retained. A ``str`` nonce is
        UTF-8 encoded — the native sniffer matches the frame's raw JSON
        string bytes, and a nonce needing JSON escapes simply never
        matches (the frame punts to the pump, which is always safe)."""
        if isinstance(nonce, str):
            nonce = nonce.encode("utf-8")
        nv = np.frombuffer(nonce, np.uint8)
        if not self._pin():
            return False
        try:
            ok = self._lib.nl_admit_put(self._h, int(worker),
                                        nv.ctypes.data, nv.nbytes,
                                        int(lo), int(hi), int(gen))
        finally:
            self._unpin()
        del nv  # pinned the source for exactly the call's duration
        return bool(ok)

    def admit_set_ack(self, frame: bytes, gen: int) -> bool:
        """Publish the replay-ack template — the complete reply frame
        the pump would send for a full-dedup replay, captured under the
        engine lock with the version stamp the ledger covers (the worker
        id is patched per serve). ``b""`` clears. False = refused: an
        apply already raised the floor past ``gen``."""
        fv = np.frombuffer(frame, np.uint8)
        if not self._pin():
            return False
        try:
            ok = self._lib.nl_admit_set_ack(
                self._h, fv.ctypes.data if fv.nbytes else None, fv.nbytes,
                int(gen))
        finally:
            self._unpin()
        del fv  # pinned the source for exactly the call's duration
        return bool(ok)

    def admit_set_refusal(self, frame: bytes) -> bool:
        """Publish (or clear, ``b""``) the role-refusal template: the
        typed ERR every admissible PUSH frame gets while this shard must
        refuse pushes (backup role, fenced zombie)."""
        fv = np.frombuffer(frame, np.uint8)
        if not self._pin():
            return False
        try:
            ok = self._lib.nl_admit_set_refusal(
                self._h, fv.ctypes.data if fv.nbytes else None, fv.nbytes)
        finally:
            self._unpin()
        del fv  # pinned the source for exactly the call's duration
        return bool(ok)

    def admit_invalidate(self, gen: int) -> None:
        """Invalidation-on-apply (the push twin of
        :meth:`cache_invalidate`): raise the admission floor to ``gen``
        and drop the version-stamped ack template; the ledger persists
        (its bounds only ever advance, so stale entries punt — never
        mis-ack). Pin-based: runs on the engine apply path."""
        if not self._pin():
            return
        try:
            self._lib.nl_admit_invalidate(self._h, int(gen))
        finally:
            self._unpin()

    def admit_reset(self, gen: int) -> None:
        """Structural re-seed (promotion, fence, migrate, pause/resume):
        raise the floor and drop the ledger and BOTH templates; the
        caller republishes whatever the new role/state allows."""
        if not self._pin():
            return
        try:
            self._lib.nl_admit_reset(self._h, int(gen))
        finally:
            self._unpin()

    def admit_stats(self) -> dict:
        """Cumulative admission counters: acks (native replay OKs),
        refusals (native typed ERRs), fresh (stamped + queued), punts
        (admissible frames the pump classified), ledger entries, floor,
        and whether each template is armed."""
        with self._lock:
            if self._closed:
                return {"acks": 0, "refusals": 0, "fresh": 0, "punts": 0,
                        "entries": 0, "floor": 0, "ack_armed": False,
                        "refusal_armed": False}
            self._lib.nl_admit_stats(self._h, self._admit_out)
            o = self._admit_out
            return {"acks": int(o[0]), "refusals": int(o[1]),
                    "fresh": int(o[2]), "punts": int(o[3]),
                    "entries": int(o[4]), "floor": int(o[5]),
                    "ack_armed": bool(o[6]), "refusal_armed": bool(o[7])}

    # -- in-loop telemetry (README "Native observability") --------------------

    def telemetry_config(self, stats_on: bool, slow_frame_ns: int) -> None:
        """Arm/disarm the loop's own telemetry: ``stats_on`` gates every
        histogram stamp (off = the pre-telemetry hot path plus one
        relaxed load per frame), ``slow_frame_ns`` the slow-frame
        watchdog threshold (0 = off)."""
        with self._lock:
            if not self._closed:
                self._lib.nl_telemetry_config(
                    self._h, 1 if stats_on else 0, int(slow_frame_ns))

    def hist_snapshots(self) -> dict:
        """The in-loop histograms as obs.metrics raw-state dicts (same
        geometry as :class:`~ps_tpu.obs.metrics.Histogram`'s defaults, so
        the states merge losslessly via ``state_add``), keyed by their
        TransportStats histogram name (``nl_read_hit_s``, ...). Stripes
        are aggregated native-side; sums/extrema convert ns -> s here."""
        out = {}
        with self._lock:
            if self._closed:
                return out
            for which, key in NL_HISTS:
                nb = self._lib.nl_hist_snapshot(self._h, which,
                                                self._hist_out)
                if nb != NL_HIST_BUCKETS:
                    continue  # geometry drifted: skip rather than corrupt
                o = self._hist_out
                total = int(o[0])
                out[key] = {
                    "lo": NL_HIST_LO, "hi": NL_HIST_HI,
                    "c": [int(o[4 + b]) for b in range(nb)],
                    "n": total, "s": int(o[1]) / 1e9,
                    "mx": int(o[3]) / 1e9,
                    "mn": (int(o[2]) / 1e9 if total else None),
                }
        return out

    def stats_snapshot(self) -> dict:
        """The loop's non-histogram telemetry: staged-tail backlog/total
        bytes, tail drains, slow-frame counters, and the armed config."""
        with self._lock:
            if self._closed:
                return {"tail_backlog_bytes": 0, "tail_staged_bytes": 0,
                        "tail_flushes": 0, "slow_frames": 0,
                        "slow_dropped": 0, "stats_on": False,
                        "slow_frame_ns": 0}
            self._lib.nl_stats_snapshot(self._h, self._nl_out)
            o = self._nl_out
            return {"tail_backlog_bytes": int(o[0]),
                    "tail_staged_bytes": int(o[1]),
                    "tail_flushes": int(o[2]),
                    "slow_frames": int(o[3]),
                    "slow_dropped": int(o[4]),
                    "stats_on": bool(o[5]),
                    "slow_frame_ns": int(o[6])}

    def slow_drain(self) -> list:
        """Drain the slow-frame ring: one dict per over-threshold frame
        (conn, wire kind byte, size, per-stage ns, age since record, and
        the sniffed trace context — empty strings when untraced). The
        pump folds these into ``slow_frame`` flight events."""
        out = []
        with self._lock:
            if self._closed:
                return out
            n = self._lib.nl_slow_drain(self._h, self._slow_vals,
                                        self._slow_tids, MAX_BATCH)
            for i in range(n):
                v = self._slow_vals[i * _SLOW_VALS:(i + 1) * _SLOW_VALS]
                base = i * 2 * _SLOW_TID
                raw = self._slow_tids.raw
                trace = raw[base:base + _SLOW_TID].split(b"\0", 1)[0]
                span = raw[base + _SLOW_TID:base + 2 * _SLOW_TID].split(
                    b"\0", 1)[0]
                out.append({
                    "conn": int(v[0]), "kind": int(v[1]),
                    "size": int(v[2]), "read_ns": int(v[3]),
                    "wait_ns": int(v[4]), "serve_ns": int(v[5]),
                    "age_ns": int(v[6]),
                    "trace_id": trace.decode("ascii", "replace"),
                    "span_id": span.decode("ascii", "replace"),
                })
        return out

    def hist_record(self, which: int, ns: int) -> None:
        """Test seam: push one KNOWN duration through the native bucket
        math (the fleet-merge exactness test's ground truth injector)."""
        with self._lock:
            if not self._closed:
                self._lib.nl_hist_record(self._h, int(which), int(ns))

    # -- lifecycle / introspection -------------------------------------------

    def stop_accept(self) -> None:
        with self._lock:
            if not self._closed:
                self._lib.nl_stop_accept(self._h)

    def shutdown_conns(self) -> None:
        with self._lock:
            if not self._closed:
                self._lib.nl_shutdown_conns(self._h)

    def begin_stop(self) -> None:
        """Signal shutdown: loop threads exit, poll() drains then returns
        None. Does not free — call :meth:`close` after the pump joined."""
        with self._lock:
            if not self._closed:
                self._lib.nl_begin_stop(self._h)

    def pending(self) -> int:
        """Requests not yet fully answered (ready + claimed by Python +
        unflushed reply tails) — what stop()'s drain waits out."""
        with self._lock:
            if self._closed:
                return 0
            return int(self._lib.nl_pending(self._h))

    def conn_count(self) -> int:
        with self._lock:
            if self._closed:
                return 0
            return int(self._lib.nl_conn_count(self._h))

    def stats(self) -> dict:
        """Cumulative loop counters: epoll iterations, accepted
        connections, requests read, live connections, pending, claimed."""
        with self._lock:
            if self._closed:
                return {"iters": 0, "accepted": 0, "requests": 0,
                        "conns": 0, "pending": 0, "claimed": 0}
            self._lib.nl_stats(self._h, self._stats_out)
            o = self._stats_out
            return {"iters": int(o[0]), "accepted": int(o[1]),
                    "requests": int(o[2]), "conns": int(o[3]),
                    "pending": int(o[4]), "claimed": int(o[5])}

    def close(self) -> None:
        """Join the loop threads and free everything. The pump thread must
        have exited (poll returned None) before this runs; pinned callers
        (replies/detaches mid-call on punted threads) are waited out —
        their calls are bounded (non-blocking writes + memcpy)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True  # no NEW pin can be taken
            while self._users > 0:
                self._cv.wait()
            self._lib.nl_stop(self._h)
            self._h = None


def adopt_channel(fd: int):
    """Wrap a detached raw fd as a blocking :class:`tensor_van.Channel`."""
    from ps_tpu.control import tensor_van as tv

    h = _lib().tv_adopt_fd(int(fd))
    return tv.Channel(h, tv._lib())
