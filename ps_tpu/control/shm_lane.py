"""Same-host shared-memory lane for the tensor van.

On TPU VMs (and in every test/bench here) the dominant PS topology is
worker and server processes on ONE host — yet every frame still traverses
the kernel TCP stack twice (send syscall + copy in, recv syscall + copy
out). This module replaces that data plane with two single-producer/
single-consumer ring buffers in a ``multiprocessing.shared_memory``
segment pair: a frame is written ONCE into the ring by the sender and
decoded IN PLACE by the receiver (``tensor_van.decode`` already takes a
``memoryview``), with no syscalls on the hot path at all.

Negotiation (:func:`try_upgrade`): after the TCP connect + HELLO, the
worker creates the two segments and sends a ``SHM_SETUP`` frame naming
them plus its boot id. The server (``VanService``) attaches and replies
OK only when the boot ids match — same kernel, therefore same host, same
/dev/shm. Any failure (cross-host, segment creation refused, server
predates the lane) falls back to plain TCP with identical semantics.

The TCP connection stays open underneath and keeps three jobs: liveness
(a dying peer's kernel closes the socket — the poll loops watch for EOF,
so a peer death mid-frame surfaces as the same :class:`~ps_tpu.control.
tensor_van.VanError` the TCP lane raises), oversize spill (a frame larger
than half the ring travels TCP instead of wedging the ring), and the
pre-upgrade control traffic.

Ring layout (one per direction; ``cap`` data bytes)::

    [0:8)    tail   — producer cursor, absolute u64 (monotonic)
    [8:16)   head   — consumer cursor, absolute u64
    [16:24)  closed — producer sets 1 on clean close
    [64:64+cap) data

A frame in the ring is ``[u64 length][length bytes]`` and NEVER wraps:
when the contiguous remainder cannot hold the frame the producer writes a
wrap sentinel (length = 2**64-1) and restarts at offset 0, so consumers
always see contiguous frames they can decode in place.

The hot path runs OUTSIDE the interpreter lock: frame bytes move through
the native ``tv_memcpy`` (ctypes releases the GIL — copies overlap the
peer thread's work even in the same-process worker+server topology every
test and bench here uses), cursors are published/read through native
release/acquire atomics (a real ordering contract, not a TSO accident),
and blocking is the native futex-free ``tv_wait_u64`` — a bounded hot
spin that decays to short sleeps, GIL-free for the whole wait, with
spin-vs-sleep wakeups counted in ``TransportStats``.
"""

from __future__ import annotations

import os
import struct
import uuid
from typing import Optional

import numpy as np

from ps_tpu.control import tensor_van as tv

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_TAIL = 0
_HEAD = 8
_CLOSED = 16
_DATA = 64
_WRAP = (1 << 64) - 1

#: default ring capacity per direction (Config.shm_bytes): holds several
#: 4 MiB default fusion buckets (frames up to cap/2 ride the ring), yet
#: small enough that the ring's working set stays largely cache-resident —
#: measured on 2-core hosts, walking a 64 MiB ring costs ~3x the copy time
#: of a 16 MiB one (every frame lands in cold DRAM instead of LLC)
DEFAULT_SHM_BYTES = 16 << 20

# one native wait slice: tv_wait_u64 spins hot, then nanosleeps doubling
# to 2 ms, returning after at most ~this long so the Python loop can
# re-check closed flags and probe the TCP side for spills/peer death
_WAIT_SLICE_US = 5000
# ring copies below this size stay in Python (a memoryview slice store);
# above it the ~1 µs ctypes hop into the GIL-free tv_memcpy pays for
# itself many times over
_NATIVE_COPY_MIN = 4096


def boot_id() -> str:
    """This kernel's boot id — equal between two processes iff they share
    a kernel, which is exactly "same host, same /dev/shm"."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket

        return f"host:{socket.gethostname()}"


class _Segment:
    """POSIX shared-memory segment with exact lifecycle control.

    ``multiprocessing.shared_memory.SharedMemory`` is the obvious tool but
    (before 3.13) registers ATTACHES with the resource tracker too — the
    attaching server's exit would unlink segments the worker still owns —
    and its ``__del__`` retries ``mmap.close()`` loudly while decoded
    in-place views still pin the mapping. This wrapper talks to
    ``_posixshmem`` directly: only the CREATOR registers with the tracker
    (so a SIGKILLed worker's segments are still reaped), close never
    raises (a pinned mapping is simply left for the GC — the segment is
    already unlinked, so the memory goes with the last mapping), and
    attach adopts nothing."""

    def __init__(self, name: str, size: Optional[int] = None):
        import _posixshmem
        import mmap as _mmap

        self.name = name
        self._tracked = False
        create = size is not None
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        fd = _posixshmem.shm_open("/" + name, flags, mode=0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            self._mmap = _mmap.mmap(fd, os.fstat(fd).st_size)
        finally:
            os.close(fd)
        self.buf = memoryview(self._mmap)
        # fault the whole mapping in NOW (GIL-free), while we are still in
        # negotiation: lazily-faulted ring pages would otherwise cost a
        # page fault per 4 KiB on the first pass around each ring — an
        # order of magnitude over the copy itself on sandboxed kernels.
        # Creator zero-fills (allocates pages, zeroes the cursors in one
        # go); attacher rewrites a byte per page (write-maps the existing
        # pages — safe: no traffic flows until the OK reply).
        base = np.frombuffer(self._mmap, np.uint8).ctypes.data
        tv._lib().tv_prefault(base, len(self._mmap), 1 if create else 2)
        if create:
            try:
                from multiprocessing import resource_tracker

                resource_tracker.register("/" + name, "shared_memory")
                self._tracked = True
            except Exception:
                pass
        # keep the tracker's own unlink from racing a clean one: unlink()
        # below unregisters first

    def close(self) -> None:
        try:
            self.buf.release()
        except Exception:
            pass
        try:
            self._mmap.close()
        except Exception:
            pass  # in-place frame views still pin it; GC finishes the job

    def unlink(self) -> None:
        import _posixshmem

        if self._tracked:
            self._tracked = False
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister("/" + self.name, "shared_memory")
            except Exception:
                pass
        try:
            _posixshmem.shm_unlink("/" + self.name)
        except FileNotFoundError:
            pass  # tracker or peer beat us to it


def _create(size: int) -> _Segment:
    return _Segment(f"psvan-{uuid.uuid4().hex[:16]}", size=size)


def _attach(name: str) -> _Segment:
    return _Segment(name)


class ShmRing:
    """One SPSC byte ring over a shared-memory buffer. Each side is
    driven by one thread (the van's one-driving-thread-per-channel rule);
    the producer owns ``tail``/``closed``, the consumer owns ``head``.
    Cursor publishes are native release stores, cursor reads native
    acquire loads, bulk copies the native GIL-free memcpy."""

    def __init__(self, buf: memoryview):
        self.cap = len(buf) - _DATA
        if self.cap <= 0:
            raise ValueError("shm segment too small for a ring")
        self._buf = buf
        self._data = buf[_DATA:]
        self._lib = tv._lib()
        # numpy wraps the mapping zero-copy; .ctypes.data is the base
        # address the native cursor/copy primitives work on
        self._np = np.frombuffer(buf, np.uint8)
        base = self._np.ctypes.data
        self._tail_addr = base + _TAIL
        self._head_addr = base + _HEAD
        self._data_addr = base + _DATA
        # cursor caches: each side re-reads only the OTHER side's cursor
        self._tail = int(self._lib.tv_load_u64(self._tail_addr))
        self._head = int(self._lib.tv_load_u64(self._head_addr))

    # -- shared ---------------------------------------------------------------

    @property
    def closed(self) -> bool:
        return _U32.unpack_from(self._buf, _CLOSED)[0] != 0

    def close(self) -> None:
        """Producer-side clean close: consumers drain, then see EOF."""
        _U32.pack_into(self._buf, _CLOSED, 1)

    def max_frame(self) -> int:
        """Largest frame accepted; bigger ones spill to TCP. Half the
        ring, so a frame never has to wait for a COMPLETELY empty ring."""
        return self.cap // 2 - 8

    def _copy_in(self, off: int, part) -> int:
        n = len(part)
        if n >= _NATIVE_COPY_MIN:
            src = np.frombuffer(part, np.uint8)
            self._lib.tv_memcpy(self._data_addr + off, src.ctypes.data, n)
        else:
            self._data[off:off + n] = part
        return n

    # -- producer -------------------------------------------------------------

    def try_send(self, parts, total: int) -> bool:
        """Copy ``parts`` (byte views summing to ``total``) into the ring
        as one frame; False when there is no room yet (caller waits on
        :meth:`wait_head`)."""
        cap = self.cap
        pos = self._tail % cap
        contig = cap - pos
        need = 8 + total
        skip = contig if contig < need else 0
        head = int(self._lib.tv_load_u64(self._head_addr))
        self._seen_head = head  # what a full-ring wait should wait past
        if cap - (self._tail - head) < skip + need:
            return False
        if skip:
            if contig >= 8:
                _U64.pack_into(self._data, pos, _WRAP)
            self._tail += skip
            pos = 0
        _U64.pack_into(self._data, pos, total)
        off = pos + 8
        for p in parts:
            off += self._copy_in(off, p)
        self._tail += need
        # release store: every byte above is visible before the cursor
        self._lib.tv_store_u64(self._tail_addr, self._tail)
        return True

    def wait_head(self, last_head: int, timeout_us: int = _WAIT_SLICE_US,
                  skip_spin: bool = False) -> int:
        """Producer-side block (native, GIL-free) until the consumer moves
        ``head`` past ``last_head``; 1 = spun, 2 = slept, 0 = timeout."""
        return self._lib.tv_wait_u64(self._head_addr, last_head, timeout_us,
                                     int(skip_spin))

    # -- consumer -------------------------------------------------------------

    def try_peek(self) -> Optional[tuple]:
        """``(frame_view, advance)`` for the next frame, decoded in place
        — the view aliases ring memory and stays valid until
        :meth:`consume`; None when the ring is empty."""
        cap = self.cap
        tail = int(self._lib.tv_load_u64(self._tail_addr))
        while True:
            if self._head == tail:
                return None
            pos = self._head % cap
            contig = cap - pos
            if contig < 8:
                self._head += contig
                self._lib.tv_store_u64(self._head_addr, self._head)
                continue
            n = _U64.unpack_from(self._data, pos)[0]
            if n == _WRAP:
                self._head += contig
                self._lib.tv_store_u64(self._head_addr, self._head)
                continue
            return self._data[pos + 8:pos + 8 + n], 8 + n

    def copy_out(self, view: memoryview, dst) -> None:
        """Copy a peeked frame out of the ring into ``dst`` (a writable
        buffer) through the GIL-free native memcpy."""
        n = len(view)
        if n >= _NATIVE_COPY_MIN:
            src = np.frombuffer(view, np.uint8)
            d = np.frombuffer(dst, np.uint8)
            self._lib.tv_memcpy(d.ctypes.data, src.ctypes.data, n)
        else:
            dst[:n] = view

    def wait_tail(self, last_tail: int, timeout_us: int = _WAIT_SLICE_US,
                  skip_spin: bool = False) -> int:
        """Consumer-side block (native, GIL-free) until the producer
        publishes past ``last_tail``; 1 = spun, 2 = slept, 0 = timeout."""
        return self._lib.tv_wait_u64(self._tail_addr, last_tail, timeout_us,
                                     int(skip_spin))

    def consume(self, advance: int) -> None:
        """Release the last peeked frame's bytes back to the producer."""
        self._head += advance
        self._lib.tv_store_u64(self._head_addr, self._head)


class _Endpoint:
    """Shared mechanics of both lane ends: one tx ring, one rx ring, the
    underlying TCP channel for liveness/spill, and the poll loops."""

    lane = "shm"

    def __init__(self, ch, tx: ShmRing, rx: ShmRing, stats=None):
        self._ch = ch
        self._tx = tx
        self._rx = rx
        self.stats = stats
        self.pool = None
        self._closed = False

    # -- send -----------------------------------------------------------------

    def _send_frame(self, parts, total: int, chunk_bytes: int = 0) -> None:
        """One frame into the tx ring (polling while full), spilled to TCP
        when it cannot fit a half-empty ring."""
        if self._closed:
            raise tv.VanError("channel is closed")
        if total > self._tx.max_frame():
            if self.stats is not None:
                self.stats.record_shm_spill()
            from ps_tpu import obs

            obs.record_event("shm_spill", bytes=int(total),
                             max_frame=self._tx.max_frame())
            if len(parts) == 1:
                self._ch.send(parts[0])
            else:
                self._ch.send_parts(parts[0], parts[1:])
            return
        while not self._tx.try_send(parts, total):
            if self._closed or self._tx.closed:
                raise tv.VanError("shm lane closed mid-send")
            # ring full: wait (natively, GIL-free) for the consumer to
            # drain; each timeout slice re-checks liveness
            if self._tx.wait_head(self._tx._seen_head) == 0 \
                    and self._peer_dead():
                self.close()
                raise tv.VanError("send failed: peer closed")
        if self.stats is not None:
            self.stats.record_shm_frame(total)
            if chunk_bytes:
                # the ring write is the frame's ONE copy — the legacy
                # path's staging bytearray never existed
                self.stats.record_vec_send(chunk_bytes)

    def send(self, payload) -> None:
        self._send_frame([payload], len(payload))

    def send_parts(self, header, chunks) -> None:
        parts = [header] + [c for c in chunks if len(c)]
        chunk_bytes = sum(len(c) for c in chunks)
        self._send_frame(parts, len(header) + chunk_bytes, chunk_bytes)

    # -- receive --------------------------------------------------------------

    def _peer_dead(self) -> bool:
        """EOF/err pending on the TCP side with no spilled frame racing?
        Peek the socket: readable + nothing in flight means the peer's
        kernel closed it. A genuine spilled frame is ALSO 'readable' —
        the callers that can receive spills use _poll_recv instead; this
        probe is only consulted mid-send, where request/reply framing
        guarantees the peer owes us nothing."""
        try:
            return self._ch.poll_readable(0)
        except tv.VanError:
            return True

    def _poll_recv(self, stop=None):
        """Next frame from the rx ring (in place: ``(view, advance)``,
        consume later) or from TCP spill (``memoryview`` already copied
        out by Channel.recv, advance None). Raises VanError on peer death
        or ``stop()``. The wait itself is the native futex-free
        spin→sleep (GIL-free); between timeout slices this loop re-checks
        closed flags and probes the TCP side for spills and peer death."""
        slept = False
        misses = 0  # wait slices that timed out with nothing arriving
        while True:
            got = self._rx.try_peek()
            if got is not None:
                if self.stats is not None:
                    self.stats.record_wakeup(spun=not slept)
                    self.stats.record_shm_frame(len(got[0]))
                return got[0], got[1]
            if self._closed:
                raise tv.VanError("channel is closed")
            if self._rx.closed:
                raise tv.VanError("recv failed: peer closed shm lane")
            if stop is not None and stop():
                raise tv.VanError("recv aborted: local stop")
            # the TCP probe is a real syscall (tens of µs on sandboxed
            # kernels): only pay it once the ring has stayed quiet for a
            # whole wait slice — spills and peer death are rare events a
            # few ms of discovery latency cannot hurt
            if misses and self._ch.poll_readable(0):
                # spilled oversize frame, or EOF (recv raises VanError)
                return self._ch.recv(), None
            st = self._rx.wait_tail(self._rx._head,
                                    skip_spin=misses > 0)
            if st != 1:
                slept = True
            misses = misses + 1 if st == 0 else 0

    # -- lifecycle ------------------------------------------------------------

    def shutdown(self) -> None:
        """Sever without freeing: the peer (and any thread blocked in a
        poll loop here) wakes with EOF. Safe from any thread."""
        self._tx.close()
        self._ch.shutdown()

    def close(self) -> None:
        self._closed = True
        try:
            self._tx.close()
        except Exception:
            pass  # the mapping may already be gone
        self._ch.close()


class ShmChannel(_Endpoint):
    """Worker-side upgraded channel: drop-in for
    :class:`~ps_tpu.control.tensor_van.Channel` on the request/reply
    paths (``send``/``send_parts``/``recv``/``request``/
    ``request_parts``/``shutdown``/``close``).

    ``recv`` COPIES the reply out of the ring (into the receive-buffer
    pool when one is attached): replies flow through futures to consumers
    whose lifetimes the lane cannot see, so in-place views would be a
    use-after-consume hazard. The asymmetric win stands: the worker→server
    direction (gradient pushes — the hot, big direction) is written once
    and decoded in place server-side.
    """

    def __init__(self, ch, tx: ShmRing, rx: ShmRing, segs, stats=None):
        super().__init__(ch, tx, rx, stats)
        self._segs = segs  # owned segments: closed AND unlinked here

    def recv(self) -> memoryview:
        got, advance = self._poll_recv()
        if advance is None:
            return got  # TCP spill: Channel.recv already owns the bytes
        n = len(got)
        buf = self.pool.borrow(n) if self.pool is not None else None
        if buf is None:
            buf = bytearray(n)
        self._rx.copy_out(got, buf)  # GIL-free bulk copy
        self._rx.consume(advance)
        return memoryview(buf)[:n]

    def request(self, payload) -> memoryview:
        self.send(payload)
        return self.recv()

    def request_parts(self, header, chunks) -> memoryview:
        self.send_parts(header, chunks)
        return self.recv()

    def poll_readable(self, timeout_ms: int = 0) -> bool:
        return self._rx.try_peek() is not None \
            or self._ch.poll_readable(timeout_ms)

    def close(self) -> None:
        super().close()
        for seg in self._segs:
            try:
                seg.close()
            except Exception:
                pass
            try:
                seg.unlink()
            except Exception:
                pass  # already unlinked (double close is fine)
        self._segs = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ServerShmLane(_Endpoint):
    """Server-side lane: the serve loop's view of an upgraded connection.

    ``recv`` hands out the request frame IN PLACE (zero copy — this is
    the lane's whole point for pushes) and defers the ring-space release
    to the NEXT ``recv`` call: the van's serve loop always finishes
    handling + replying before it asks for the next frame, so the frame's
    bytes are provably dead by then. The attached segments are closed but
    NOT unlinked on close — the worker owns them.
    """

    def __init__(self, ch, tx: ShmRing, rx: ShmRing, segs, stats=None):
        super().__init__(ch, tx, rx, stats)
        self._segs = segs  # attached (not owned): closed, never unlinked
        self._pending_advance = 0

    def recv(self, stop=None) -> memoryview:
        if self._pending_advance:
            self._rx.consume(self._pending_advance)
            self._pending_advance = 0
        got, advance = self._poll_recv(stop=stop)
        if advance is None:
            return got  # TCP spill (already copied out)
        self._pending_advance = advance
        return got

    def close(self) -> None:
        super().close()
        for seg in self._segs:
            try:
                seg.close()
            except Exception:
                pass
        self._segs = []


# -- negotiation --------------------------------------------------------------


def try_upgrade(ch, worker: int, shm_bytes: int = DEFAULT_SHM_BYTES,
                stats=None):
    """Offer the server a shared-memory lane over connected channel
    ``ch``; returns the upgraded :class:`ShmChannel` or — on ANY
    negotiation failure (cross-host boot id, segment creation refused,
    server predates the lane) — ``ch`` unchanged, so callers can call
    this unconditionally. Only a DEAD channel raises (VanError), exactly
    like any other request on it.

    ``PS_SHM_BOOT_ID`` overrides the advertised boot id (tests force a
    cross-host-shaped mismatch with it)."""
    size = _DATA + max(int(shm_bytes), 1 << 16)
    segs = []
    try:
        # _Segment's create-path prefault zero-fills the whole mapping,
        # cursors and flags included
        for _ in range(2):
            segs.append(_create(size))
    except Exception:
        for seg in segs:
            seg.close()
            seg.unlink()
        return ch
    c2s, s2c = segs
    from ps_tpu.config import env_str

    bid = env_str("PS_SHM_BOOT_ID") or boot_id()
    try:
        reply = ch.request(tv.encode(tv.SHM_SETUP, worker, None, extra={
            "boot_id": bid, "c2s": c2s.name, "s2c": s2c.name,
            "bytes": size,
        }))
        kind, _, _, extra = tv.decode(reply)
    except BaseException:  # dead channel / garbage reply: don't leak segs
        for seg in segs:
            seg.close()
            seg.unlink()
        raise
    if kind != tv.OK or not extra.get("shm"):
        for seg in segs:
            seg.close()
            seg.unlink()
        return ch
    return ShmChannel(ch, tx=ShmRing(c2s.buf), rx=ShmRing(s2c.buf),
                      segs=segs, stats=stats)


def accept_upgrade(ch, extra: dict, stats=None) -> ServerShmLane:
    """Server half of the negotiation: validate the boot id and attach the
    worker's segments. Raises on any mismatch/failure — the caller turns
    that into an ERR reply and the connection stays plain TCP."""
    if extra.get("boot_id") != boot_id():
        raise ValueError(
            f"shm lane refused: peer boot id {extra.get('boot_id')!r} is "
            f"not this host's — cross-host connections ride TCP"
        )
    c2s = _attach(str(extra["c2s"]))
    try:
        s2c = _attach(str(extra["s2c"]))
    except Exception:
        c2s.close()
        raise
    # the offer names the segment size it created; the attach must see
    # exactly that (a truncated/raced segment would corrupt ring framing
    # at the first wrap) — this is also what keeps the advertised
    # "bytes" header field honest (pslint PSL203: produced AND consumed)
    want = int(extra.get("bytes") or 0)
    if want and (len(c2s.buf) != want or len(s2c.buf) != want):
        c2s.close()
        s2c.close()
        raise ValueError(
            f"shm lane refused: segment size mismatch (offer says {want} "
            f"bytes, attached {len(c2s.buf)}/{len(s2c.buf)})"
        )
    try:
        return ServerShmLane(ch, tx=ShmRing(s2c.buf), rx=ShmRing(c2s.buf),
                             segs=[c2s, s2c], stats=stats)
    except Exception:  # e.g. a segment too small for a ring
        c2s.close()
        s2c.close()
        raise
