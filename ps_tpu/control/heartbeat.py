"""Heartbeat liveness over the native control-plane van.

The reference's scheduler watches worker/server heartbeats and declares
silent nodes dead (SURVEY.md §2 "Transport/van" row, §6 "Failure
detection"). ps_tpu keeps the same shape, symmetric instead of
scheduler-centric: every process runs a monitor (:class:`HeartbeatServer`)
and beats every peer (:class:`HeartbeatClient`), so each process detects any
peer's death locally — no single point of failure watching the watchers.

The beat/recv loops live in C++ threads (ps_tpu/native/van.cpp) so a Python
GIL pause — a long jit trace, a blocking collective — cannot stop a process
from *beating*; only real death does. Detection polls from Python.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

from ps_tpu.native import load


class WorkerFailureError(RuntimeError):
    """A peer process stopped heartbeating (dead or partitioned)."""

    def __init__(self, dead: List[int]):
        self.dead = sorted(dead)
        super().__init__(
            f"peer process(es) {self.dead} stopped heartbeating — "
            f"declared dead by the failure detector"
        )


def _lib():
    # one of THE three ctypes declaration sites (the tv_*/nl_* _lib
    # twins are the others): every argtypes/restype row here is
    # machine-diffed against van.cpp's extern "C" signatures by pslint
    # PSL6xx, so a C-side signature change cannot silently
    # truncate/corrupt at this boundary
    lib = load("van")
    lib.hb_server_start.restype = ctypes.c_void_p
    lib.hb_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.hb_server_port.restype = ctypes.c_int
    lib.hb_server_port.argtypes = [ctypes.c_void_p]
    lib.hb_server_poll.restype = ctypes.c_int
    lib.hb_server_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
    ]
    lib.hb_server_seq.restype = ctypes.c_uint64
    lib.hb_server_seq.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.hb_server_age_ms.restype = ctypes.c_int64
    lib.hb_server_age_ms.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.hb_server_stop.argtypes = [ctypes.c_void_p]
    lib.hb_client_start.restype = ctypes.c_void_p
    lib.hb_client_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_int,
    ]
    lib.hb_client_goodbye.argtypes = [ctypes.c_void_p]
    lib.hb_client_stop.argtypes = [ctypes.c_void_p]
    return lib


class HeartbeatServer:
    """Liveness monitor: tracks every node that has ever beaten this port.

    A node is *alive* while its beats arrive within ``timeout_ms``, *dead*
    once seen-then-silent longer than that, and *left* — permanently, never
    dead — once its goodbye arrives (clean membership change ≠ failure).

    ``bind`` is the listen address: "0.0.0.0" accepts beats from any host
    (pod deployments), "127.0.0.1" restricts to this host (tests).
    """

    def __init__(self, port: int = 0, timeout_ms: int = 1000,
                 bind: str = "0.0.0.0"):
        import socket

        self._lib = _lib()
        addr = socket.gethostbyname(bind)  # names ok; native side wants IPv4
        self._h = self._lib.hb_server_start(addr.encode(), port, timeout_ms)
        if not self._h:
            raise OSError(
                f"heartbeat server failed to bind {bind} ({addr}):{port}"
            )

    def _require(self):
        if not self._h:
            raise RuntimeError("heartbeat server is closed")
        return self._h

    @property
    def port(self) -> int:
        return self._lib.hb_server_port(self._require())

    def _poll(self, state: int) -> List[int]:
        cap = 1024
        buf = (ctypes.c_uint32 * cap)()
        n = self._lib.hb_server_poll(self._require(), state, buf, cap)
        return sorted(buf[i] for i in range(n))

    def alive(self) -> List[int]:
        return self._poll(0)

    def dead(self) -> List[int]:
        """Seen, then silent past the horizon, with no goodbye."""
        return self._poll(1)

    def left(self) -> List[int]:
        """Nodes that announced a clean leave (goodbye received)."""
        return self._poll(2)

    def seq(self, node_id: int) -> int:
        """Beats received from node_id (0 = never seen)."""
        return int(self._lib.hb_server_seq(self._require(), node_id))

    def age_ms(self, node_id: int) -> Optional[int]:
        """Milliseconds since this node's last beat (None = never seen).
        The per-peer freshness the coordinator's membership view and
        ps_top render — 'alive' says a peer beat within the horizon,
        the age says HOW fresh, which is what an operator watching a
        wobbly member actually needs."""
        age = int(self._lib.hb_server_age_ms(self._require(), node_id))
        return None if age < 0 else age

    def state(self, node_id: Optional[int] = None):
        """One node's liveness, or the whole monitor's view.

        With ``node_id``: the liveness string — 'left' (clean goodbye —
        permanent), 'dead' (seen-then-silent past the horizon), 'alive',
        or 'unseen' (never beat — indistinguishable from
        not-started-yet). The promotion watch (ps_tpu/replica/watch.py)
        keys its goodbye-vs-timeout distinction off this.

        Without: ``{node: {"state", "age_ms", "seq"}}`` for every node
        that ever beat — the per-peer last-beat ages included, so the
        coordinator's liveness view (ps_tpu/elastic) rides this ONE
        detector instead of growing a second one."""
        if node_id is not None:
            if node_id in self.left():
                return "left"
            if node_id in self.dead():
                return "dead"
            if node_id in self.alive():
                return "alive"
            return "unseen"
        out: Dict[int, dict] = {}
        for st, nodes in (("alive", self.alive()), ("dead", self.dead()),
                          ("left", self.left())):
            for n in nodes:
                out[n] = {"state": st, "age_ms": self.age_ms(n),
                          "seq": self.seq(n)}
        return out

    def close(self) -> None:
        if self._h:
            self._lib.hb_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HeartbeatClient:
    """Beats ``node_id`` at ``host:port`` every ``interval_ms`` from a C++
    thread until closed."""

    def __init__(self, host: str, port: int, node_id: int,
                 interval_ms: int = 100):
        import socket

        self._lib = _lib()
        # the native side takes dotted-quad only; resolve names here so a
        # bad hostname is a loud error, never a silent localhost fallback
        addr = socket.gethostbyname(host)
        self._h = self._lib.hb_client_start(
            addr.encode(), port, node_id, interval_ms
        )
        if not self._h:
            raise OSError(f"heartbeat client to {host} ({addr}):{port} failed")

    def close(self, goodbye: bool = False) -> None:
        """Stop beating. ``goodbye=True`` first announces a clean leave so
        the peer marks this node *left* instead of eventually *dead*."""
        if self._h:
            if goodbye:
                self._lib.hb_client_goodbye(self._h)
            self._lib.hb_client_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FailureDetector:
    """Symmetric peer liveness for one process of a multi-process run.

    Args:
      node_id: this process's id.
      peers: ``{node_id: (host, port)}`` of every OTHER process's monitor.
      port: local monitor port (0 = ephemeral; see :attr:`server`).
      bind: local monitor listen address ("0.0.0.0" for multi-host pods,
        "127.0.0.1" to restrict to this host).
      interval_ms / timeout_ms: beat cadence and death horizon.

    Usage: construct everywhere, then call :meth:`check` between training
    steps — it raises :class:`WorkerFailureError` naming the dead peers
    instead of letting the next collective hang. A peer that closed with
    ``goodbye=True`` is *left*, not dead: :meth:`check` stays silent.
    """

    def __init__(self, node_id: int, peers: Dict[int, Tuple[str, int]],
                 port: int = 0, interval_ms: int = 100,
                 timeout_ms: int = 1000, bind: str = "0.0.0.0"):
        self.node_id = node_id
        self.expected = sorted(peers)
        self.server = HeartbeatServer(port=port, timeout_ms=timeout_ms,
                                      bind=bind)
        self._clients = [
            HeartbeatClient(host, p, node_id, interval_ms)
            for _, (host, p) in sorted(peers.items())
        ]

    def check(self) -> None:
        """Raise if any peer that ever beat us has gone silent (a clean
        goodbye-leave never raises). The death lands in the flight
        recorder BEFORE the raise — the black box must hold the first
        detection even if the raise takes the process down."""
        dead = self.server.dead()
        if dead:
            from ps_tpu import obs

            obs.record_event("peer_dead", node=self.node_id,
                             dead=sorted(dead))
            raise WorkerFailureError(dead)

    def left(self) -> List[int]:
        """Peers that announced a clean leave."""
        return self.server.left()

    def wait_for_peers(self, timeout_s: float = 30.0) -> None:
        """Block until every expected peer's first beat arrives (rendezvous
        barrier for the control plane)."""
        import time

        deadline = time.monotonic() + timeout_s
        want = set(self.expected)
        while time.monotonic() < deadline:
            seen = set(self.server.alive()) | set(self.server.dead())
            if want <= seen:
                return
            time.sleep(0.02)
        missing = sorted(want - (set(self.server.alive()) | set(self.server.dead())))
        raise TimeoutError(
            f"peers {missing} never started heartbeating within {timeout_s}s"
        )

    def close(self, goodbye: bool = False) -> None:
        """``goodbye=True`` announces a clean leave to every peer before
        stopping (so survivors see *left*, not an eventual *dead*)."""
        for c in self._clients:
            c.close(goodbye=goodbye)
        self._clients = []
        self.server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
