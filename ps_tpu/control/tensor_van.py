"""Framed tensor messages over the native TCP van.

The async data plane (SURVEY.md §4d): async workers are separate,
deliberately unsynchronized OS processes, so their grad/param exchange with
the server process cannot ride an XLA collective — it travels as framed byte
messages over the native van's TCP layer (``tv_*`` in ps_tpu/native/van.cpp;
this module does the encoding). A message is::

    [u8 kind][u32 worker_id][u64 meta_len][meta json][raw buffers...]

where the json carries each tensor's (name, dtype, shape, nbytes) in order,
followed by the concatenated raw row-major buffers — no pickling, no copies
beyond the single send buffer.

Channel/Listener are thin blocking wrappers over the C ABI; ctypes releases
the GIL during sends/recvs, so a multi-MB push never stalls other Python
threads (the server serves each connection from its own thread).
"""

from __future__ import annotations

import contextlib
import ctypes
import json
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ps_tpu.native import load

# message kinds (u8)
HELLO = 0       # worker announces itself; server replies SERVER_INFO
PULL = 1        # -> params + version
PUSH = 2        # grads -> ack (applied with DC; version advances)
PUSH_PULL = 3   # grads -> params + version (one round trip per cycle)
STATS = 4       # -> json: version, staleness_hist, apply_log
SHUTDOWN = 5    # server drains and stops serving this connection
OK = 6
ERR = 7
# sparse-table kinds (SURVEY.md §4c over §4d: workers exchange
# (row_ids, row_grads) with the servers owning those row ranges)
ROW_PULL = 8       # {"<table>/ids"} -> {"<table>/rows"} + versions
ROW_PUSH = 9       # {"<table>/ids", "<table>/grads"} -> ack + versions
ROW_PUSH_PULL = 10  # push + pull in one round trip per server
CHECKPOINT = 11    # {"dir"} -> server saves its shard; ack + version(s)
# bucketed transport (backends/common.py BucketPlan): a logical push/pull
# travels as fixed-size fusion buckets striped over a pool of connections
BUCKET_PUSH = 12   # one slice-bucket of a multi-bucket push; the bucket
#                    completing the epoch commits the WHOLE tree atomically
BUCKET_PULL = 13   # bucket 0 snapshots the tree server-side; buckets 1..n-1
#                    stream the remaining slices of that same snapshot
ROW_BUCKET_PUSH = 14  # sparse twin: row chunks staged per epoch, applied
#                    as ONE atomic multi-table push when the epoch completes
SHM_SETUP = 15     # same-host shared-memory lane negotiation: the worker
#                    names two ring segments + its boot id; an OK reply
#                    switches the connection's data plane to the rings
#                    (ps_tpu/control/shm_lane.py), ERR keeps plain TCP
# shard replication (ps_tpu/replica): a primary service streams its
# committed updates to a warm backup that can be promoted on primary death
REPLICA_HELLO = 16    # primary -> backup: attach the replication stream
#                       (topology + state-point validation; ERR = the pair
#                       did not start from the same state)
REPLICA_APPEND = 17   # primary -> backup: ONE sequenced committed event
#                       (push tensors or a pull record); the ack reply is
#                       what sync-mode push replies wait on
REPLICA_PROMOTE = 18  # operator/watchdog -> backup: promote to primary now
#                       (bumps the shard-table epoch; workers re-route)
REPLICA_STATE = 19    # -> any service: role/epoch/replication-lag probe
#                       (reply also carries the server's wall clock "now" —
#                       the NTP-style probe ps_tpu/obs/clock.py rides for
#                       cross-process trace-timeline alignment)
# elastic membership (ps_tpu/elastic): a coordinator role owns the
# authoritative versioned shard table; servers register and report load,
# workers fetch the table, and the coordinator drives live key-range
# migrations between shards (no worker restart, no global pause)
COORD_HELLO = 20      # member -> coordinator: join (servers advertise
#                       their uri + key range; the reply carries the
#                       current table, a heartbeat port, and a node id)
COORD_TABLE = 21      # -> coordinator: the current shard table, plus the
#                       membership/liveness view ps_top renders
COORD_REPORT = 22     # server -> coordinator: periodic load report
#                       (keys, bytes, push/pull QPS from TransportStats)
COORD_REBALANCE = 23  # operator -> coordinator: plan + execute a
#                       rebalance (explicit moves, a target member set,
#                       or a drain); replies when the table committed
# live key-range migration (donor shard -> recipient shard), driven by
# the coordinator's MIGRATE_OUT command; rows ride the PR-4 replica-
# stream machinery (sequenced entries over one channel, per-entry acks)
MIGRATE_OUT = 24      # coordinator -> donor: stream these keys to the
#                       target shard; replies once the move committed
MIGRATE_BEGIN = 25    # donor -> recipient: open the migration intake
#                       (key list + topology validation; ERR = refused)
MIGRATE_ROW = 26      # donor -> recipient: ONE sequenced row — param +
#                       optimizer state + stale snapshots travel together;
#                       later rows for a key supersede earlier (the
#                       double-write catch-up during live traffic)
MIGRATE_COMMIT = 27   # donor -> recipient: cut over — the recipient
#                       installs the staged rows and starts serving them
MIGRATE_ABORT = 28    # donor -> recipient: discard the staged range
#                       (the move failed; the donor keeps serving)
# fleet telemetry (ps_tpu/obs/tsdb.py, served by the elastic coordinator):
# members ship delta-encoded metric snapshots on the COORD_REPORT cadence;
# this kind is the QUERY side — windowed fleet quantiles computed from
# losslessly merged raw log2 histogram buckets (never averaged
# percentiles), the per-step critical-path breakdown, straggler suspects,
# and SLO rule states (tools/ps_top.py --fleet, tools/ps_doctor.py)
COORD_TELEMETRY = 29  # -> coordinator: fleet telemetry query/report
# high-QPS read path (README "Read path"): a side-effect-free pull of
# committed state — no event-log record, no replication, no DC stale
# snapshot, and the request/reply carry a FIXED worker id 0, so
# byte-identical requests get byte-identical replies. That determinism is
# what makes READ frames servable from the native loop's read cache with
# zero upcalls (nl_cache_* in van.cpp), shareable across readers, and
# answerable by backup replicas under the bounded-staleness contract
# (PS_READ_STALENESS) — the serving path of a read-dominated deployment.
READ = 30       # dense: -> whole-subtree params + version;
#                 sparse: {"<table>/ids"} -> {"<table>/rows"} + versions
# conditional-read reply (README "Read path"): a READ carrying the
# caller's known version ("cond"/"conds" in extra) whose target is
# UNCHANGED gets this tiny version-stamp-only frame instead of the full
# payload — the steady-state revalidation of a read-mostly deployment.
# Deterministic like READ itself (fixed worker id 0, no side effects),
# so byte-identical conditional requests stay servable from the native
# read cache with zero upcalls.
NOT_MODIFIED = 31  # -> reader: target unchanged since "cond"; stamp only
# autopilot (ps_tpu/elastic/policy.py): the coordinator's policy engine
# turns sustained telemetry signals into planned elastic actions; these
# kinds are its audit/query surface and the replica re-seed action path
COORD_POLICY = 32  # -> coordinator: policy-engine state + action audit
#                    log (rule arm/streak/cooldown, last decisions) —
#                    ps_top --coord's "policy" column and the chaos soak's
#                    zero-operator-actions proof read this
RESEED = 33        # coordinator -> primary: re-seed replication onto the
#                    named spare backup — quiesce under the apply lock,
#                    ship the full state point (REPLICA_SEED), re-attach
REPLICA_SEED = 34  # primary -> EMPTY backup: the full per-key state
#                    (rows + engine meta + dedup ledgers) installed
#                    atomically so the pair stands at one state point and
#                    the deltas-only REPLICA stream can attach

#: human names per kind — span labels (ps_tpu/obs/trace.py), ps_top, and
#: flight-recorder events all resolve through here so a new kind gets a
#: readable name in every surface at once
KIND_NAMES = {
    HELLO: "hello", PULL: "pull", PUSH: "push", PUSH_PULL: "push_pull",
    STATS: "stats", SHUTDOWN: "shutdown", OK: "ok", ERR: "err",
    ROW_PULL: "row_pull", ROW_PUSH: "row_push",
    ROW_PUSH_PULL: "row_push_pull", CHECKPOINT: "checkpoint",
    BUCKET_PUSH: "bucket_push", BUCKET_PULL: "bucket_pull",
    ROW_BUCKET_PUSH: "row_bucket_push", SHM_SETUP: "shm_setup",
    REPLICA_HELLO: "replica_hello", REPLICA_APPEND: "replica_append",
    REPLICA_PROMOTE: "replica_promote", REPLICA_STATE: "replica_state",
    COORD_HELLO: "coord_hello", COORD_TABLE: "coord_table",
    COORD_REPORT: "coord_report", COORD_REBALANCE: "coord_rebalance",
    MIGRATE_OUT: "migrate_out", MIGRATE_BEGIN: "migrate_begin",
    MIGRATE_ROW: "migrate_row", MIGRATE_COMMIT: "migrate_commit",
    MIGRATE_ABORT: "migrate_abort", COORD_TELEMETRY: "coord_telemetry",
    READ: "read", NOT_MODIFIED: "not_modified",
    COORD_POLICY: "coord_policy", RESEED: "reseed",
    REPLICA_SEED: "replica_seed",
}


def kind_name(kind: int) -> str:
    return KIND_NAMES.get(kind, f"kind{kind}")


_HDR = struct.Struct("<BIQ")  # kind, worker_id, meta_len


def _lib():
    # one of THE three ctypes declaration sites (with heartbeat._lib and
    # native_loop._lib): every argtypes/restype row here is machine-diffed
    # against van.cpp's extern "C" signatures by pslint PSL6xx (arity,
    # pointer width, missing-restype-defaults-to-c_int truncation)
    lib = load("van")
    lib.tv_listen.restype = ctypes.c_void_p
    lib.tv_listen.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tv_listener_port.restype = ctypes.c_int
    lib.tv_listener_port.argtypes = [ctypes.c_void_p]
    lib.tv_accept.restype = ctypes.c_void_p
    lib.tv_accept.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tv_listener_close.argtypes = [ctypes.c_void_p]
    lib.tv_connect.restype = ctypes.c_void_p
    lib.tv_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tv_send.restype = ctypes.c_int
    # second arg is c_void_p (not c_char_p) so zero-copy bytearray frames
    # from encode() can be handed over via from_buffer
    lib.tv_send.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
    lib.tv_send_vec.restype = ctypes.c_int
    lib.tv_send_vec.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.tv_poll_readable.restype = ctypes.c_int
    lib.tv_poll_readable.argtypes = [ctypes.c_void_p, ctypes.c_int]
    # shm-ring primitives (GIL-free copies, acquire/release cursors, and
    # the futex-free adaptive wait) — ps_tpu/control/shm_lane.py
    lib.tv_memcpy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_uint64]
    lib.tv_prefault.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                ctypes.c_int]
    lib.tv_load_u64.restype = ctypes.c_uint64
    lib.tv_load_u64.argtypes = [ctypes.c_void_p]
    lib.tv_store_u64.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.tv_wait_u64.restype = ctypes.c_int
    lib.tv_wait_u64.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                ctypes.c_int, ctypes.c_int]
    lib.tv_recv_size.restype = ctypes.c_int64
    lib.tv_recv_size.argtypes = [ctypes.c_void_p]
    lib.tv_recv_into.restype = ctypes.c_int
    lib.tv_recv_into.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64]
    lib.tv_shutdown.argtypes = [ctypes.c_void_p]
    lib.tv_close.argtypes = [ctypes.c_void_p]
    return lib


# -- tensor-tree codec -------------------------------------------------------


def encode_parts(kind: int, worker: int,
                 tensors: Optional[Dict[str, np.ndarray]],
                 extra: Optional[dict] = None):
    """The zero-copy form of :func:`encode`: returns ``(header, chunks)``
    where ``header`` is the packed frame header + json meta (a bytearray)
    and ``chunks`` are byte ``memoryview``s of the LIVE tensors, in frame
    order. ``header + b"".join(chunks)`` is byte-identical to
    :func:`encode`'s frame — asserted by the frame-parity property tests —
    but nothing is staged: :meth:`Channel.send_parts` hands the views
    straight to the kernel (writev) and the shm lane writes them once into
    its ring. The views pin their source arrays for the send's duration."""
    names = sorted(tensors) if tensors else []
    arrays = [np.ascontiguousarray(np.asarray(tensors[n])) for n in names]
    meta = {
        "tensors": [
            {"name": n, "dtype": a.dtype.str, "shape": list(a.shape)}
            for n, a in zip(names, arrays)
        ],
        "extra": extra or {},
    }
    mj = json.dumps(meta).encode()
    header = bytearray(_HDR.size + len(mj))
    _HDR.pack_into(header, 0, kind, worker, len(mj))
    header[_HDR.size:] = mj
    # zero-size arrays can't cast("B") (zeros in shape); they contribute
    # no bytes, only their meta entry
    return header, [memoryview(a).cast("B") if a.nbytes else memoryview(b"")
                    for a in arrays]


def encode_chunks_parts(kind: int, worker: int, chunks,
                        extra: Optional[dict] = None):
    """Zero-copy twin of :func:`encode_chunks`: ``(header, chunks)`` with
    the caller's byte views passed through untouched (the bucketed
    transport's frame, minus its staging copy)."""
    total = sum(len(c) for c in chunks)
    meta = {
        "tensors": [{"name": "raw", "dtype": "|u1", "shape": [total]}],
        "extra": extra or {},
    }
    mj = json.dumps(meta).encode()
    header = bytearray(_HDR.size + len(mj))
    _HDR.pack_into(header, 0, kind, worker, len(mj))
    header[_HDR.size:] = mj
    return header, list(chunks)


def assemble(header, chunks) -> bytearray:
    """Stage ``(header, chunks)`` parts into one contiguous legacy frame
    (each chunk copied exactly once) — the fallback when a channel cannot
    send vectored, and the definition the parity tests hold the vectored
    path to."""
    buf = bytearray(len(header) + sum(len(c) for c in chunks))
    buf[:len(header)] = header
    off = len(header)
    for c in chunks:
        n = len(c)
        buf[off:off + n] = c
        off += n
    return buf


def encode(kind: int, worker: int, tensors: Optional[Dict[str, np.ndarray]],
           extra: Optional[dict] = None) -> bytearray:
    """One message: header + json meta (+ optional 'extra' json fields) +
    concatenated raw buffers. Keys are encoded in sorted order.

    Exactly ONE copy of each tensor's bytes is made — straight into the
    preallocated frame (no per-array ``tobytes`` temporaries, no join copy).
    Defined as ``assemble(*encode_parts(...))`` so the legacy single-buffer
    framing and the vectored path can never drift apart."""
    return assemble(*encode_parts(kind, worker, tensors, extra))


def encode_chunks(kind: int, worker: int, chunks, extra: Optional[dict] = None
                  ) -> bytearray:
    """One message whose single tensor ``raw`` (uint8 ``[total]``) is the
    concatenation of ``chunks`` — buffer-protocol byte views, typically
    ``memoryview`` slices of live tensors (the bucketed-transport frame of
    :class:`ps_tpu.backends.common.BucketPlan`). Staged form of
    :func:`encode_chunks_parts`."""
    return assemble(*encode_chunks_parts(kind, worker, chunks, extra))


def decode(buf: memoryview) -> Tuple[int, int, Dict[str, np.ndarray], dict]:
    """Inverse of :func:`encode`; tensor buffers are zero-copy views."""
    kind, worker, mlen = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    meta = json.loads(bytes(buf[off:off + mlen]))
    off += mlen
    tensors = {}
    for t in meta["tensors"]:
        dt = np.dtype(t["dtype"])
        n = int(np.prod(t["shape"], dtype=np.int64)) * dt.itemsize
        tensors[t["name"]] = np.frombuffer(
            buf[off:off + n], dtype=dt
        ).reshape(t["shape"])
        off += n
    return kind, worker, tensors, meta.get("extra", {})


# -- blocking channel / listener ---------------------------------------------


class VanError(ConnectionError):
    """The peer closed or the frame was invalid."""


class RecvBufferPool:
    """Size-bucketed borrow/return pool for receive frames.

    ``Channel.recv`` allocates a fresh bytearray per frame; on the hot pull
    path that is one multi-MB allocation per bucket per cycle, all churned
    through the allocator. Owners whose frame lifetimes are explicit — the
    serve loop (frame dead once the reply is sent) and the pump-reply
    consumers (frame dead once decoded/assembled) — borrow here instead and
    return the buffer when done. Buffers are allocated at the requested
    size (never pow2-rounded — the recurring workload is same-size bucket
    frames, so rounding would only zero-fill and pin up to 2x the bytes)
    and filed by next-power-of-two class; a borrow scans its class for a
    buffer with enough capacity. Frames under ``min_bytes`` are not worth
    pooling, and frames over ``max_bytes`` are not worth RETAINING (a
    pooled serial BERT-size frame would pin hundreds of MB for the
    process lifetime) — both fall through to a plain allocation (not
    counted as misses). Thread-safe; a buffer returned twice, or one the
    pool never issued, is ignored.
    """

    def __init__(self, min_bytes: int = 1 << 16,
                 max_bytes: int = 64 << 20,
                 max_per_class: int = 8, stats=None):
        import threading

        self.min_bytes = int(min_bytes)
        self.max_bytes = int(max_bytes)
        self.max_per_class = int(max_per_class)
        self.stats = stats  # TransportStats with record_pool(hit)
        self._lock = threading.Lock()
        self._free: Dict[int, list] = {}
        self._out: set = set()  # id() of buffers currently borrowed

    def borrow(self, n: int):
        """A bytearray of capacity >= n, or None (caller allocates)."""
        if n < self.min_bytes or n > self.max_bytes:
            return None
        cls = max(n - 1, 1).bit_length()  # next power of two >= n
        buf = None
        with self._lock:
            free = self._free.get(cls)
            if free:
                # same-class buffers may be slightly smaller than n (they
                # are request-sized, not pow2): take the first that fits
                for i, b in enumerate(free):
                    if len(b) >= n:
                        buf = b
                        del free[i]
                        break
            hit = buf is not None
            if buf is None:
                buf = bytearray(n)
            self._out.add(id(buf))
        if self.stats is not None:
            self.stats.record_pool(hit)
        return buf

    def ret(self, frame) -> None:
        """Return a borrowed buffer. Accepts the memoryview ``recv``
        handed out (its ``.obj`` is the pooled buffer) or the buffer
        itself; anything else is a no-op, so callers can return every
        frame unconditionally."""
        buf = getattr(frame, "obj", frame)
        if not isinstance(buf, bytearray) \
                or not (self.min_bytes <= len(buf) <= self.max_bytes):
            return  # never issued a buffer outside the pooling range
        cls = max(len(buf) - 1, 1).bit_length()
        with self._lock:
            if id(buf) not in self._out:
                return
            self._out.discard(id(buf))
            free = self._free.setdefault(cls, [])
            if len(free) < self.max_per_class:
                free.append(buf)


class Channel:
    """One framed TCP connection (blocking; one driving thread at a time —
    except :meth:`shutdown`/:meth:`close`, which are cross-thread safe).

    Cross-thread close is made safe by refcounting native access: close()
    severs the socket immediately (waking any thread blocked in recv) but
    defers the ``tv_close`` free until the last thread inside a native call
    exits, so no peer thread can dereference a freed Conn."""

    #: set by owners that account per-lane transport (a TransportStats);
    #: send_parts records its staging-copy-avoided bytes here
    stats = None
    #: set by owners with explicit frame lifetimes (a RecvBufferPool);
    #: recv borrows receive buffers from it instead of allocating
    pool = None
    #: lane tag for accounting ("tcp" here; the shm lane overrides)
    lane = "tcp"

    def __init__(self, handle, lib):
        import threading

        self._h = handle
        self._lib = lib
        self._hlock = threading.Lock()  # guards the handle's lifecycle
        self._users = 0       # threads currently inside a native call
        self._closed = False  # close() requested; free deferred to last user

    @classmethod
    def connect(cls, host: str, port: int, timeout_ms: int = 10_000,
                retries: int = 50, retry_delay_s: float = 0.1,
                max_wait_s: Optional[float] = None) -> "Channel":
        """Dial host:port, retrying while the server comes up.

        The hostname is re-resolved on EVERY attempt (a restarted server —
        or a k8s service — may come back at a new address; resolving once
        outside the loop would retry a stale A record 50 times), and the
        delay between attempts is jittered exponential backoff capped at
        ~2 s so a thundering herd of reconnecting workers decorrelates
        instead of hammering the listener in lockstep. ``max_wait_s``
        bounds the TOTAL time spent sleeping between attempts, so capped
        backoff cannot turn ``retries`` into minutes against a
        fast-refusing dead address. ``None`` resolves the default dial
        budget from PS_CONNECT_MAX_WAIT_MS (15 s) — the knob read-path
        failover tuning turns down so a dead replica costs milliseconds,
        not the full patience meant for servers still booting."""
        import random
        import socket as pysocket
        import time

        if max_wait_s is None:
            from ps_tpu.config import env_float

            # validated service-level read (pslint PSL406): the one
            # default every dial site inherits — previously a hardcoded
            # operator-invisible 15 s
            max_wait_s = env_float("PS_CONNECT_MAX_WAIT_MS", 15_000.0,
                                   lo=0.0) / 1e3
        lib = _lib()
        delay = max(float(retry_delay_s), 1e-3)
        slept = 0.0  # only SLEEP counts against max_wait_s: a peer that
        # drops SYNs already self-limits via timeout_ms per dial, and its
        # dial time must not eat the retry budget of the dead-fast-refusal
        # case the cap exists for
        err: Optional[Exception] = None
        dials = 0
        for attempt in range(retries):
            if attempt:
                if slept >= max_wait_s:
                    break
                d = min(delay * (0.5 + random.random()),  # 0.5x..1.5x
                        max_wait_s - slept)
                time.sleep(d)
                slept += d
                delay = min(delay * 2, 2.0)
            dials += 1
            try:
                addr = pysocket.gethostbyname(host)
            except OSError as e:  # transient DNS failure: retry like a dial
                err = e
                continue
            h = lib.tv_connect(addr.encode(), port, timeout_ms)
            if h:
                return cls(h, lib)
        raise VanError(f"could not connect to {host}:{port} "
                       f"after {dials} attempts"
                       + (f" (last resolve error: {err})" if err else ""))

    @contextlib.contextmanager
    def _native(self):
        """Pin the handle for a native call; the last user performs a
        deferred free if close() ran meanwhile."""
        with self._hlock:
            if self._closed or not self._h:
                raise VanError("channel is closed")
            self._users += 1
            h = self._h
        try:
            yield h
        finally:
            with self._hlock:
                self._users -= 1
                if self._closed and self._users == 0 and self._h:
                    self._lib.tv_close(self._h)
                    self._h = None

    def send(self, payload) -> None:
        """Send one frame. ``payload`` is bytes or a bytearray (the
        zero-extra-copy form :func:`encode` returns)."""
        n = len(payload)
        if isinstance(payload, bytearray):
            payload = (ctypes.c_char * n).from_buffer(payload)
        with self._native() as h:
            ok = self._lib.tv_send(h, payload, n)
        if not ok:
            self.close()  # half-sent frame: the stream is unusable
            raise VanError("send failed: peer closed")

    def send_parts(self, header, chunks) -> None:
        """Send one frame gathered from ``header`` + ``chunks`` (byte
        views of live tensors) with NO staging copy: the views go straight
        to the kernel through ``tv_send_vec`` (sendmsg scatter-gather).
        Byte-identical on the wire to ``send(assemble(header, chunks))``."""
        views = [np.frombuffer(header, np.uint8)]
        views += [np.frombuffer(c, np.uint8) for c in chunks if len(c)]
        n = len(views)
        ptrs = (ctypes.c_void_p * n)(*(v.ctypes.data for v in views))
        lens = (ctypes.c_uint64 * n)(*(v.nbytes for v in views))
        with self._native() as h:
            ok = self._lib.tv_send_vec(h, ptrs, lens, n)
        del views  # pinned the sources for exactly the call's duration
        if not ok:
            self.close()  # half-sent frame: the stream is unusable
            raise VanError("send failed: peer closed")
        if self.stats is not None:
            self.stats.record_vec_send(
                sum(len(c) for c in chunks))  # staging copy avoided

    def poll_readable(self, timeout_ms: int = 0) -> bool:
        """True when ``recv`` would not block (data pending or EOF)."""
        with self._native() as h:
            return bool(self._lib.tv_poll_readable(h, int(timeout_ms)))

    def recv(self) -> memoryview:
        buf = None
        with self._native() as h:
            n = self._lib.tv_recv_size(h)
            if n >= 0:
                buf = (self.pool.borrow(n) if self.pool is not None
                       else None)
                if buf is None:
                    buf = bytearray(n)
                ok = (not n) or self._lib.tv_recv_into(
                    h, (ctypes.c_char * n).from_buffer(buf), n)
        if n < 0:
            # EOF, or an insane length word — either way the framing is
            # gone; poison the channel so a caught error can't silently
            # misparse the next bytes as a fresh frame
            self.close()
            raise VanError("recv failed: peer closed" if n == -1
                           else "recv failed: oversized frame")
        if not ok:
            if self.pool is not None:
                self.pool.ret(buf)  # don't strand a borrow on the error path
            self.close()
            raise VanError("recv failed mid-frame: peer closed")
        # pooled buffers may exceed the frame; the slice's .obj is still
        # the buffer, so RecvBufferPool.ret(view) finds its way home
        return memoryview(buf)[:n]

    def request(self, payload: bytes) -> memoryview:
        self.send(payload)
        return self.recv()

    def request_parts(self, header, chunks) -> memoryview:
        self.send_parts(header, chunks)
        return self.recv()

    def shutdown(self) -> None:
        """Sever the connection without freeing: a thread blocked in
        :meth:`recv` on this channel wakes with EOF and runs its own
        :meth:`close`. Safe to call from any thread."""
        with self._hlock:
            if self._h and not self._closed:
                self._lib.tv_shutdown(self._h)

    def close(self) -> None:
        """Sever and free. Safe from any thread: if another thread is inside
        a native call, the socket is shut down now (unblocking it) and the
        free happens when that thread exits :meth:`_native`."""
        with self._hlock:
            if self._closed or not self._h:
                self._closed = True
                return
            self._closed = True
            self._lib.tv_shutdown(self._h)  # wake any blocked native call
            if self._users == 0:
                self._lib.tv_close(self._h)
                self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Listener:
    """Accept loop handle for the server side."""

    def __init__(self, port: int = 0, bind: str = "0.0.0.0",
                 backlog: int = 64):
        import socket as pysocket

        self._lib = _lib()
        addr = pysocket.gethostbyname(bind)
        self._h = self._lib.tv_listen(addr.encode(), port, backlog)
        if not self._h:
            raise OSError(f"tensor van failed to listen on {bind}:{port}")

    @property
    def port(self) -> int:
        return self._lib.tv_listener_port(self._h)

    def accept(self, timeout_ms: int = -1) -> Optional[Channel]:
        h = self._lib.tv_accept(self._h, timeout_ms)
        return Channel(h, self._lib) if h else None

    def close(self) -> None:
        if self._h:
            self._lib.tv_listener_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
