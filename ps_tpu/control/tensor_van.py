"""Framed tensor messages over the native TCP van.

The async data plane (SURVEY.md §4d): async workers are separate,
deliberately unsynchronized OS processes, so their grad/param exchange with
the server process cannot ride an XLA collective — it travels as framed byte
messages over the native van's TCP layer (``tv_*`` in ps_tpu/native/van.cpp;
this module does the encoding). A message is::

    [u8 kind][u32 worker_id][u64 meta_len][meta json][raw buffers...]

where the json carries each tensor's (name, dtype, shape, nbytes) in order,
followed by the concatenated raw row-major buffers — no pickling, no copies
beyond the single send buffer.

Channel/Listener are thin blocking wrappers over the C ABI; ctypes releases
the GIL during sends/recvs, so a multi-MB push never stalls other Python
threads (the server serves each connection from its own thread).
"""

from __future__ import annotations

import contextlib
import ctypes
import json
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ps_tpu.native import load

# message kinds (u8)
HELLO = 0       # worker announces itself; server replies SERVER_INFO
PULL = 1        # -> params + version
PUSH = 2        # grads -> ack (applied with DC; version advances)
PUSH_PULL = 3   # grads -> params + version (one round trip per cycle)
STATS = 4       # -> json: version, staleness_hist, apply_log
SHUTDOWN = 5    # server drains and stops serving this connection
OK = 6
ERR = 7
# sparse-table kinds (SURVEY.md §4c over §4d: workers exchange
# (row_ids, row_grads) with the servers owning those row ranges)
ROW_PULL = 8       # {"<table>/ids"} -> {"<table>/rows"} + versions
ROW_PUSH = 9       # {"<table>/ids", "<table>/grads"} -> ack + versions
ROW_PUSH_PULL = 10  # push + pull in one round trip per server
CHECKPOINT = 11    # {"dir"} -> server saves its shard; ack + version(s)
# bucketed transport (backends/common.py BucketPlan): a logical push/pull
# travels as fixed-size fusion buckets striped over a pool of connections
BUCKET_PUSH = 12   # one slice-bucket of a multi-bucket push; the bucket
#                    completing the epoch commits the WHOLE tree atomically
BUCKET_PULL = 13   # bucket 0 snapshots the tree server-side; buckets 1..n-1
#                    stream the remaining slices of that same snapshot
ROW_BUCKET_PUSH = 14  # sparse twin: row chunks staged per epoch, applied
#                    as ONE atomic multi-table push when the epoch completes

_HDR = struct.Struct("<BIQ")  # kind, worker_id, meta_len


def _lib():
    lib = load("van")
    lib.tv_listen.restype = ctypes.c_void_p
    lib.tv_listen.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tv_listener_port.restype = ctypes.c_int
    lib.tv_listener_port.argtypes = [ctypes.c_void_p]
    lib.tv_accept.restype = ctypes.c_void_p
    lib.tv_accept.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tv_listener_close.argtypes = [ctypes.c_void_p]
    lib.tv_connect.restype = ctypes.c_void_p
    lib.tv_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.tv_send.restype = ctypes.c_int
    # second arg is c_void_p (not c_char_p) so zero-copy bytearray frames
    # from encode() can be handed over via from_buffer
    lib.tv_send.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
    lib.tv_recv_size.restype = ctypes.c_int64
    lib.tv_recv_size.argtypes = [ctypes.c_void_p]
    lib.tv_recv_into.restype = ctypes.c_int
    lib.tv_recv_into.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64]
    lib.tv_shutdown.argtypes = [ctypes.c_void_p]
    lib.tv_close.argtypes = [ctypes.c_void_p]
    return lib


# -- tensor-tree codec -------------------------------------------------------


def encode(kind: int, worker: int, tensors: Optional[Dict[str, np.ndarray]],
           extra: Optional[dict] = None) -> bytearray:
    """One message: header + json meta (+ optional 'extra' json fields) +
    concatenated raw buffers. Keys are encoded in sorted order.

    Exactly ONE copy of each tensor's bytes is made — straight into the
    preallocated frame (no per-array ``tobytes`` temporaries, no join copy).
    At BERT-size trees (~0.4 GB/frame) the removed copies were a measurable
    slice of serve latency (tools/bench_van.py)."""
    names = sorted(tensors) if tensors else []
    arrays = [np.ascontiguousarray(np.asarray(tensors[n])) for n in names]
    meta = {
        "tensors": [
            {"name": n, "dtype": a.dtype.str, "shape": list(a.shape)}
            for n, a in zip(names, arrays)
        ],
        "extra": extra or {},
    }
    mj = json.dumps(meta).encode()
    buf = bytearray(_HDR.size + len(mj) + sum(a.nbytes for a in arrays))
    _HDR.pack_into(buf, 0, kind, worker, len(mj))
    off = _HDR.size
    buf[off:off + len(mj)] = mj
    off += len(mj)
    for a in arrays:
        n = a.nbytes
        buf[off:off + n] = memoryview(a).cast("B")
        off += n
    return buf


def encode_chunks(kind: int, worker: int, chunks, extra: Optional[dict] = None
                  ) -> bytearray:
    """One message whose single tensor ``raw`` (uint8 ``[total]``) is the
    concatenation of ``chunks`` — buffer-protocol byte views, typically
    ``memoryview`` slices of live tensors (the bucketed-transport frame of
    :class:`ps_tpu.backends.common.BucketPlan`).

    Same zero-extra-copy discipline as :func:`encode`: each chunk's bytes
    are copied exactly once, straight into the preallocated frame — no
    intermediate concatenation buffer.
    """
    total = sum(len(c) for c in chunks)
    meta = {
        "tensors": [{"name": "raw", "dtype": "|u1", "shape": [total]}],
        "extra": extra or {},
    }
    mj = json.dumps(meta).encode()
    buf = bytearray(_HDR.size + len(mj) + total)
    _HDR.pack_into(buf, 0, kind, worker, len(mj))
    off = _HDR.size
    buf[off:off + len(mj)] = mj
    off += len(mj)
    for c in chunks:
        n = len(c)
        buf[off:off + n] = c
        off += n
    return buf


def decode(buf: memoryview) -> Tuple[int, int, Dict[str, np.ndarray], dict]:
    """Inverse of :func:`encode`; tensor buffers are zero-copy views."""
    kind, worker, mlen = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    meta = json.loads(bytes(buf[off:off + mlen]))
    off += mlen
    tensors = {}
    for t in meta["tensors"]:
        dt = np.dtype(t["dtype"])
        n = int(np.prod(t["shape"], dtype=np.int64)) * dt.itemsize
        tensors[t["name"]] = np.frombuffer(
            buf[off:off + n], dtype=dt
        ).reshape(t["shape"])
        off += n
    return kind, worker, tensors, meta.get("extra", {})


# -- blocking channel / listener ---------------------------------------------


class VanError(ConnectionError):
    """The peer closed or the frame was invalid."""


class Channel:
    """One framed TCP connection (blocking; one driving thread at a time —
    except :meth:`shutdown`/:meth:`close`, which are cross-thread safe).

    Cross-thread close is made safe by refcounting native access: close()
    severs the socket immediately (waking any thread blocked in recv) but
    defers the ``tv_close`` free until the last thread inside a native call
    exits, so no peer thread can dereference a freed Conn."""

    def __init__(self, handle, lib):
        import threading

        self._h = handle
        self._lib = lib
        self._hlock = threading.Lock()  # guards the handle's lifecycle
        self._users = 0       # threads currently inside a native call
        self._closed = False  # close() requested; free deferred to last user

    @classmethod
    def connect(cls, host: str, port: int, timeout_ms: int = 10_000,
                retries: int = 50, retry_delay_s: float = 0.1) -> "Channel":
        """Dial host:port, retrying while the server comes up."""
        import socket as pysocket
        import time

        lib = _lib()
        addr = pysocket.gethostbyname(host)
        for attempt in range(retries):
            h = lib.tv_connect(addr.encode(), port, timeout_ms)
            if h:
                return cls(h, lib)
            time.sleep(retry_delay_s)
        raise VanError(f"could not connect to {host}:{port} "
                       f"after {retries} attempts")

    @contextlib.contextmanager
    def _native(self):
        """Pin the handle for a native call; the last user performs a
        deferred free if close() ran meanwhile."""
        with self._hlock:
            if self._closed or not self._h:
                raise VanError("channel is closed")
            self._users += 1
            h = self._h
        try:
            yield h
        finally:
            with self._hlock:
                self._users -= 1
                if self._closed and self._users == 0 and self._h:
                    self._lib.tv_close(self._h)
                    self._h = None

    def send(self, payload) -> None:
        """Send one frame. ``payload`` is bytes or a bytearray (the
        zero-extra-copy form :func:`encode` returns)."""
        n = len(payload)
        if isinstance(payload, bytearray):
            payload = (ctypes.c_char * n).from_buffer(payload)
        with self._native() as h:
            ok = self._lib.tv_send(h, payload, n)
        if not ok:
            self.close()  # half-sent frame: the stream is unusable
            raise VanError("send failed: peer closed")

    def recv(self) -> memoryview:
        with self._native() as h:
            n = self._lib.tv_recv_size(h)
            if n >= 0:
                buf = bytearray(n)
                ok = (not n) or self._lib.tv_recv_into(
                    h, (ctypes.c_char * n).from_buffer(buf), n)
        if n < 0:
            # EOF, or an insane length word — either way the framing is
            # gone; poison the channel so a caught error can't silently
            # misparse the next bytes as a fresh frame
            self.close()
            raise VanError("recv failed: peer closed" if n == -1
                           else "recv failed: oversized frame")
        if not ok:
            self.close()
            raise VanError("recv failed mid-frame: peer closed")
        return memoryview(buf)

    def request(self, payload: bytes) -> memoryview:
        self.send(payload)
        return self.recv()

    def shutdown(self) -> None:
        """Sever the connection without freeing: a thread blocked in
        :meth:`recv` on this channel wakes with EOF and runs its own
        :meth:`close`. Safe to call from any thread."""
        with self._hlock:
            if self._h and not self._closed:
                self._lib.tv_shutdown(self._h)

    def close(self) -> None:
        """Sever and free. Safe from any thread: if another thread is inside
        a native call, the socket is shut down now (unblocking it) and the
        free happens when that thread exits :meth:`_native`."""
        with self._hlock:
            if self._closed or not self._h:
                self._closed = True
                return
            self._closed = True
            self._lib.tv_shutdown(self._h)  # wake any blocked native call
            if self._users == 0:
                self._lib.tv_close(self._h)
                self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Listener:
    """Accept loop handle for the server side."""

    def __init__(self, port: int = 0, bind: str = "0.0.0.0",
                 backlog: int = 64):
        import socket as pysocket

        self._lib = _lib()
        addr = pysocket.gethostbyname(bind)
        self._h = self._lib.tv_listen(addr.encode(), port, backlog)
        if not self._h:
            raise OSError(f"tensor van failed to listen on {bind}:{port}")

    @property
    def port(self) -> int:
        return self._lib.tv_listener_port(self._h)

    def accept(self, timeout_ms: int = -1) -> Optional[Channel]:
        h = self._lib.tv_accept(self._h, timeout_ms)
        return Channel(h, self._lib) if h else None

    def close(self) -> None:
        if self._h:
            self._lib.tv_listener_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
