"""Codec frames ⇄ one contiguous uint8 buffer — the transport adapter.

The bucketed transport (``backends/common.py``) moves ``{key: ndarray}``
dicts as byte-sliced fusion buckets; it neither knows nor cares what the
bytes mean. This module makes an encoded tensor LOOK like a plain tensor:
:func:`pack_frames` serializes a codec's frame dict into one uint8 array
(magic + json header naming the codec and each frame's dtype/shape + raw
buffers), so it buckets/stripes/reassembles exactly like raw data. The
list of packed keys travels in the bucket header (``extra["enc"]``) and
:func:`decode_tree` reverses the whole thing on the receiving side.

:class:`GradCompressor` is the worker-side driver: policy selection,
packing, and the codec accounting (ratio / seconds / residual norm) that
TrainMetrics and StepLogger surface.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ps_tpu.compress.codecs import make_codec
from ps_tpu.compress.policy import CompressPolicy

_MAGIC = b"PSC1"
_HDR = struct.Struct("<4sI")  # magic, meta_len


def _dtype_token(dt: np.dtype) -> str:
    """A dtype spelling that survives json + ``np.dtype(...)`` — custom
    ml_dtypes (bfloat16 et al) stringify to void under ``.str``, but their
    NAME round-trips once ml_dtypes is imported."""
    return dt.name if dt.str.lstrip("<>|=").startswith("V") else dt.str


def pack_frames(codec: str, frames: Dict[str, np.ndarray]) -> np.ndarray:
    """Serialize one codec's frame dict into a single uint8 array."""
    names = sorted(frames)
    # reshape preserves 0-d shapes that ascontiguousarray would promote
    arrays = [np.ascontiguousarray(np.asarray(frames[n])).reshape(
        np.asarray(frames[n]).shape) for n in names]
    meta = {
        "codec": codec,
        "frames": [
            {"name": n, "dtype": _dtype_token(a.dtype),
             "shape": list(a.shape)}
            for n, a in zip(names, arrays)
        ],
    }
    mj = json.dumps(meta).encode()
    buf = np.empty(_HDR.size + len(mj) + sum(a.nbytes for a in arrays),
                   np.uint8)
    _HDR.pack_into(buf, 0, _MAGIC, len(mj))
    off = _HDR.size
    buf[off:off + len(mj)] = np.frombuffer(mj, np.uint8)
    off += len(mj)
    for a in arrays:
        n = a.nbytes
        # ndarray.view sidesteps the buffer protocol, which cannot express
        # custom dtypes (ml_dtypes bfloat16)
        buf[off:off + n] = a.reshape(-1).view(np.uint8)
        off += n
    return buf


def unpack_frames(buf) -> Tuple[str, Dict[str, np.ndarray]]:
    """Inverse of :func:`pack_frames`; frame buffers are zero-copy views."""
    buf = np.asarray(buf).reshape(-1).view(np.uint8)
    magic, mlen = _HDR.unpack_from(buf, 0)
    if magic != _MAGIC:
        raise ValueError("not a packed codec buffer (bad magic)")
    off = _HDR.size
    meta = json.loads(bytes(buf[off:off + mlen]))
    off += mlen
    frames: Dict[str, np.ndarray] = {}
    for f in meta["frames"]:
        dt = np.dtype(f["dtype"])
        n = int(np.prod(f["shape"], dtype=np.int64)) * dt.itemsize
        frames[f["name"]] = (buf[off:off + n].view(dt)
                             .reshape(f["shape"]))
        off += n
    return meta["codec"], frames


# stateless decoder singletons, keyed by wire name — decode never needs
# the sender's construction params (frames are self-describing)
_DECODERS: Dict[str, object] = {}


def decode_packed(buf) -> np.ndarray:
    """Packed uint8 buffer -> the original tensor."""
    name, frames = unpack_frames(buf)
    codec = _DECODERS.get(name)
    if codec is None:
        codec = _DECODERS[name] = make_codec(name)
    return codec.decode(frames)


def decode_tree(arrays: Dict[str, np.ndarray], enc_keys,
                stats=None) -> Dict[str, np.ndarray]:
    """Decode the ``enc_keys`` entries of a received ``{key: tensor}`` tree
    in place (unlisted keys pass through untouched). The server half of the
    wire negotiation: ``enc_keys`` is the bucket header's ``extra["enc"]``.
    """
    if not enc_keys:
        return arrays
    t0 = time.perf_counter()
    enc_bytes = 0
    raw_bytes = 0
    for k in enc_keys:
        if k not in arrays:
            raise KeyError(f"enc key {k!r} absent from the received tree")
        enc_bytes += arrays[k].nbytes
        arrays[k] = decode_packed(arrays[k])
        raw_bytes += arrays[k].nbytes
    if stats is not None:
        stats.record_codec(raw_bytes, enc_bytes, time.perf_counter() - t0)
    return arrays


class GradCompressor:
    """Worker-side tree encoder: apply the policy key-by-key, pack what
    compresses, account for it.

    ``stats`` (a :class:`~ps_tpu.utils.metrics.TransportStats`) receives
    raw/encoded byte counts, codec seconds, and the error-feedback residual
    norm — the numbers TrainMetrics reports as ``compress_ratio`` /
    ``codec_s`` / ``residual_norm``.
    """

    def __init__(self, policy: CompressPolicy, stats=None):
        self.policy = policy
        self.stats = stats

    def encode_tree(self, arrays: Dict[str, np.ndarray]
                    ) -> Tuple[Dict[str, np.ndarray], List[str]]:
        """``{key: tensor}`` -> (wire tree, keys that were packed)."""
        if not self.policy.enabled:
            return arrays, []
        t0 = time.perf_counter()
        out: Dict[str, np.ndarray] = {}
        enc: List[str] = []
        raw_bytes = 0
        enc_bytes = 0
        for k, a in arrays.items():
            codec = self.policy.select(k, a)
            if codec.name == "none":
                out[k] = a
                continue
            a = np.asarray(a)
            packed = pack_frames(codec.name, codec.encode(k, a))
            out[k] = packed
            enc.append(k)
            raw_bytes += a.nbytes
            enc_bytes += packed.nbytes
        if enc and self.stats is not None:
            self.stats.record_codec(raw_bytes, enc_bytes,
                                    time.perf_counter() - t0)
            self.stats.record_residual_norm(self.policy.residual_norm())
        return out, enc
