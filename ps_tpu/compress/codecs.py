"""Gradient codecs: one ``encode/decode`` contract, four implementations.

A codec turns one tensor into a dict of named numpy ``frames`` that fully
determine the decoded tensor (self-describing — decode needs no out-of-band
state), and back. Lossy codecs bound their error per encode; ``topk``
additionally keeps worker-local error-feedback residuals so what is not
sent this step is sent later instead of lost — the property that keeps
asynchronous training convergent under aggressive sparsification.

Every codec passes through (frame ``"raw"``) any tensor it cannot
represent — non-float dtypes, and for ``cast16``/``int8``/``topk``
anything but float32 — so a codec is always safe to apply; the
:class:`~ps_tpu.compress.policy.CompressPolicy` merely decides where it is
*worth* applying.

Non-finite payloads: ``cast16`` preserves NaN/Inf exactly (IEEE subsets);
``int8`` saturates ±Inf to the chunk's ±max and maps NaN to 0 (scales are
computed over the finite entries only, so one NaN cannot poison a chunk);
``topk`` ranks by magnitude with NaN treated as 0. Gradients with NaN/Inf
mean the run is already broken — the codecs just guarantee they never
crash or corrupt framing.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

try:  # jax always ships ml_dtypes; guard anyway so the codec core is pure
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover - ml_dtypes is a jax dependency
    _BF16 = None


def _contig(arr) -> np.ndarray:
    # ascontiguousarray alone would promote 0-d scalars to 1-d
    a = np.asarray(arr)
    return np.ascontiguousarray(a).reshape(a.shape)


class Codec:
    """One gradient codec: ``encode(key, ndarray) -> frames`` and
    ``decode(frames) -> ndarray``.

    ``frames`` is ``{name: np.ndarray}`` and is self-describing: the frame
    set alone reconstructs the tensor (dtype, shape, values). ``key`` lets
    stateful codecs (``topk`` error feedback) keep per-tensor state;
    ``decode`` is stateless for every codec, so the receiving side needs
    only the codec registry, never the sender's state.
    """

    name = "?"
    #: True when decode(encode(x)) == x exactly for every input
    lossless = False

    def encode(self, key: str, arr) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def decode(self, frames: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError

    def residual_norm(self) -> float:
        """L2 norm of this codec's error-feedback state (0 if stateless)."""
        return 0.0

    # -- shared passthrough (any codec may fall back to it) -------------------

    def _raw(self, arr) -> Dict[str, np.ndarray]:
        return {"raw": _contig(arr)}

    def _is_raw(self, frames) -> Optional[np.ndarray]:
        return frames.get("raw")


class NoneCodec(Codec):
    """Identity codec — the explicit 'do not compress' spelling, and the
    fallback every lossy codec uses for dtypes it cannot represent."""

    name = "none"
    lossless = True

    def encode(self, key: str, arr) -> Dict[str, np.ndarray]:
        return self._raw(arr)

    def decode(self, frames: Dict[str, np.ndarray]) -> np.ndarray:
        return frames["raw"]


class Cast16Codec(Codec):
    """Float32 → 16-bit downcast (2×). ``mode='bf16'`` (default: same
    exponent range as f32 — the safe choice for grads) or ``'fp16'``.

    bf16 payloads travel as uint16 bit patterns (the bf16 dtype string does
    not round-trip through plain numpy); fp16 is a native numpy dtype.
    Lossless whenever the values already lie on the 16-bit grid — which is
    exactly the case for grads produced by bf16 compute.
    """

    name = "cast16"

    def __init__(self, mode: str = "bf16"):
        if mode not in ("bf16", "fp16"):
            raise ValueError(f"cast16 mode {mode!r}; use 'bf16' or 'fp16'")
        if mode == "bf16" and _BF16 is None:  # pragma: no cover
            mode = "fp16"
        self.mode = mode

    def encode(self, key: str, arr) -> Dict[str, np.ndarray]:
        arr = _contig(arr)
        if arr.dtype != np.float32:
            return self._raw(arr)
        if self.mode == "bf16":
            # astype rounds to nearest-even; ship the bit pattern
            return {"bf16": arr.astype(_BF16).view(np.uint16)}
        return {"fp16": arr.astype(np.float16)}

    def decode(self, frames: Dict[str, np.ndarray]) -> np.ndarray:
        raw = self._is_raw(frames)
        if raw is not None:
            return raw
        if "bf16" in frames:
            return frames["bf16"].view(_BF16).astype(np.float32)
        return frames["fp16"].astype(np.float32)


class Int8Codec(Codec):
    """Per-chunk scale quantization to int8 (~4×), QSGD-style.

    Each ``chunk``-element run gets its own scale ``max|x| / 127``; values
    quantize stochastically (``floor(x/scale + u)``, ``u ~ U[0,1)``) so the
    quantizer is unbiased — E[decode] == x — which is what lets SGD average
    the noise away across steps and workers. Per-encode error is bounded by
    one quantization step: ``|x - decode(encode(x))| <= max|chunk| / 127``.
    Frames: int8 values + one f32 scale per chunk + shape/chunk meta.
    """

    name = "int8"

    def __init__(self, chunk: int = 1024, stochastic: bool = True,
                 seed: int = 0):
        self.chunk = max(int(chunk), 1)
        self.stochastic = bool(stochastic)
        self._rng = np.random.default_rng(seed)

    def encode(self, key: str, arr) -> Dict[str, np.ndarray]:
        arr = _contig(arr)
        if arr.dtype != np.float32:
            return self._raw(arr)
        flat = arr.reshape(-1)
        n = flat.size
        nchunks = -(-n // self.chunk) if n else 0
        if nchunks:
            pad = np.zeros(nchunks * self.chunk, np.float32)
            np.absolute(flat, out=pad[:n], where=np.isfinite(flat))
            scales = (pad.reshape(nchunks, self.chunk).max(axis=1)
                      / 127.0).astype(np.float32)
        else:
            scales = np.zeros(0, np.float32)
        safe = np.where(scales > 0, scales, 1.0)
        r = flat / np.repeat(safe, self.chunk)[:n]
        r = np.nan_to_num(r, nan=0.0, posinf=127.0, neginf=-127.0)
        if self.stochastic and n:
            q = np.floor(r + self._rng.random(n, dtype=np.float32))
        else:
            q = np.rint(r)
        q = np.clip(q, -127, 127).astype(np.int8)
        return {
            "q8": q,
            "scale": scales,
            "shape": np.asarray(arr.shape, np.int64),
            "chunk": np.asarray([self.chunk], np.int64),
        }

    def decode(self, frames: Dict[str, np.ndarray]) -> np.ndarray:
        raw = self._is_raw(frames)
        if raw is not None:
            return raw
        q = frames["q8"]
        scales = frames["scale"].astype(np.float32)
        chunk = int(frames["chunk"][0])
        shape = tuple(int(s) for s in frames["shape"])
        n = q.size
        x = q.astype(np.float32) * np.repeat(scales, chunk)[:n]
        return x.reshape(shape)


class TopKCodec(Codec):
    """Per-tensor top-k sparsification with error feedback (DGC-style).

    Sends only the ``k = ceil(fraction * n)`` largest-magnitude entries
    (exact values — support-exact: what is sent arrives bit-for-bit); the
    rest accumulate in a worker-local per-key residual that is added to the
    next gradient before selection, so every coordinate's mass is
    eventually transmitted — the property that keeps training convergent
    at fractions far below 1. Disable with ``error_feedback=False`` for a
    pure (lossy-forever) sparsifier. Wire cost ≈ ``fraction * 2`` of raw
    (int32 index + f32 value per kept entry).
    """

    name = "topk"

    def __init__(self, fraction: float = 0.01, error_feedback: bool = True):
        if not (0.0 < fraction <= 1.0):
            raise ValueError(f"topk fraction {fraction} outside (0, 1]")
        self.fraction = float(fraction)
        self.error_feedback = bool(error_feedback)
        self._residual: Dict[str, np.ndarray] = {}

    def encode(self, key: str, arr) -> Dict[str, np.ndarray]:
        arr = _contig(arr)
        if arr.dtype != np.float32 or arr.size >= 2 ** 31:
            return self._raw(arr)
        flat = arr.reshape(-1).copy()
        res = self._residual.get(key)
        if self.error_feedback and res is not None and res.size == flat.size:
            flat += res
        n = flat.size
        k = min(n, max(1, math.ceil(self.fraction * n))) if n else 0
        if k and k < n:
            mag = np.abs(np.nan_to_num(flat, nan=0.0))
            idx = np.argpartition(mag, n - k)[n - k:]
            idx.sort()  # deterministic order; also friendlier to scatter
        else:
            idx = np.arange(n)
        val = flat[idx]
        if self.error_feedback:
            flat[idx] = 0.0
            self._residual[key] = flat
        return {
            "idx": idx.astype(np.int32),
            "val": val,
            "shape": np.asarray(arr.shape, np.int64),
        }

    def decode(self, frames: Dict[str, np.ndarray]) -> np.ndarray:
        raw = self._is_raw(frames)
        if raw is not None:
            return raw
        shape = tuple(int(s) for s in frames["shape"])
        out = np.zeros(int(np.prod(shape, dtype=np.int64)), np.float32)
        out[frames["idx"]] = frames["val"]
        return out.reshape(shape)

    def residual_norm(self) -> float:
        if not self._residual:
            return 0.0
        return float(math.sqrt(sum(
            float(np.dot(r, r)) for r in self._residual.values()
        )))


_REGISTRY = {
    "none": NoneCodec,
    "cast16": Cast16Codec,
    "int8": Int8Codec,
    "topk": TopKCodec,
}


def available_codecs():
    return sorted(_REGISTRY)


def make_codec(name: str, **kwargs) -> Codec:
    """Instantiate a codec by wire name (kwargs go to its constructor)."""
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown codec {name!r}; available: {available_codecs()}"
        ) from None
    return cls(**kwargs)
