"""Pluggable gradient-compression codecs for the van transport.

The reference family treats worker↔server bandwidth as the scaling
bottleneck (PAPER.md §2); this subsystem cuts push/pull bytes 2–16× with
the math preserved: ``cast16`` (bf16/fp16 downcast), ``int8`` (per-chunk
stochastic scale-quantization, QSGD-style), and ``topk`` (per-tensor
top-k sparsification with worker-local error-feedback residuals,
Deep-Gradient-Compression-style) — all behind one :class:`Codec`
``encode(key, ndarray) -> frames / decode(frames) -> ndarray`` contract.

Wire shape: an encoded tensor travels as ONE packed uint8 buffer
(:func:`pack_frames` — self-describing: codec id + per-frame dtype/shape
in a json header), so it rides the existing bucketed transport unchanged;
the list of packed keys rides the bucket header (``extra["enc"]``) and the
server decodes with :func:`decode_tree` before aggregation. Which keys get
which codec is the :class:`CompressPolicy`'s call (compress large dense
float grads; never small / integer / excluded tensors), applied worker-
side by :class:`GradCompressor`.
"""

from ps_tpu.compress.codecs import (
    Cast16Codec,
    Codec,
    Int8Codec,
    NoneCodec,
    TopKCodec,
    available_codecs,
    make_codec,
)
from ps_tpu.compress.policy import CompressPolicy, resolve_spec
from ps_tpu.compress.wire import (
    GradCompressor,
    decode_packed,
    decode_tree,
    pack_frames,
    unpack_frames,
)

__all__ = [
    "Codec", "NoneCodec", "Cast16Codec", "Int8Codec", "TopKCodec",
    "available_codecs", "make_codec",
    "CompressPolicy", "resolve_spec",
    "GradCompressor", "decode_tree", "decode_packed",
    "pack_frames", "unpack_frames",
]
