"""Per-key codec selection: compress where it pays, never where it hurts.

The policy owns ONE shared instance of its lossy codec (so ``topk``
error-feedback residuals persist across steps) plus the identity codec,
and answers "which codec for this (key, tensor)?" with three gates:

- size: tensors under ``min_bytes`` stay raw — small tensors are exactly
  the optimizer-critical ones (biases, norms, scalars) where quantization
  noise is all pain and the wire saving is noise;
- dtype: only float32 compresses (integers are ids/masks; 16-bit floats
  are already compressed);
- exclusion: keys matching any ``exclude`` regex stay raw regardless of
  size (e.g. ``exclude=["bias", "scale"]`` for norm-sensitive params).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Union

import numpy as np

from ps_tpu.compress.codecs import Codec, NoneCodec, make_codec

#: default size floor — below this, framing overhead and optimizer
#: sensitivity both say "don't"
DEFAULT_MIN_BYTES = 1 << 16

Spec = Union[None, str, dict]


def resolve_spec(spec: Spec, *, topk: Optional[float] = None,
                 min_bytes: Optional[int] = None,
                 pull: Optional[bool] = None) -> Optional[dict]:
    """Normalize a compression spec to a dict (or None for 'off').

    ``spec`` may be a codec name (``"int8"``), a dict
    (``{"codec": "topk", "topk": 0.02, "min_bytes": 4096, "pull": False}``),
    or None/"none"/"" for off. Keyword overrides win over dict fields —
    they are the Config/env knobs (PS_COMPRESS_TOPK etc.).
    """
    if spec is None or spec == "" or spec == "none":
        return None
    out = dict(spec) if isinstance(spec, dict) else {"codec": str(spec)}
    if out.get("codec") in (None, "", "none"):
        return None
    if topk is not None:
        out["topk"] = float(topk)
    if min_bytes is not None:
        out["min_bytes"] = int(min_bytes)
    if pull is not None:
        out["pull"] = bool(pull)
    return out


class CompressPolicy:
    """Pick the codec for each (key, tensor); see the module docstring.

    Args:
      codec: wire codec name ('none'/'cast16'/'int8'/'topk').
      min_bytes: size floor below which tensors stay raw.
      topk: kept fraction for the 'topk' codec.
      exclude: regexes; matching keys stay raw.
      error_feedback: topk residual accumulation (on by default).
      seed: int8 stochastic-rounding seed.
    """

    def __init__(self, codec: str = "none",
                 min_bytes: int = DEFAULT_MIN_BYTES,
                 topk: float = 0.01,
                 exclude: Sequence[str] = (),
                 error_feedback: bool = True,
                 seed: int = 0):
        self.min_bytes = max(int(min_bytes), 0)
        self._exclude = [re.compile(p) for p in exclude]
        kwargs: Dict = {}
        if codec == "topk":
            kwargs = {"fraction": topk, "error_feedback": error_feedback}
        elif codec == "int8":
            kwargs = {"seed": seed}
        self.codec: Codec = make_codec(codec, **kwargs)
        self._none = NoneCodec()

    @classmethod
    def from_spec(cls, spec: Spec, **kwargs) -> Optional["CompressPolicy"]:
        """Build from a normalized spec dict / name; None when off."""
        spec = resolve_spec(spec)
        if spec is None:
            return None
        return cls(
            codec=spec["codec"],
            min_bytes=spec.get("min_bytes", DEFAULT_MIN_BYTES),
            topk=spec.get("topk", 0.01),
            exclude=spec.get("exclude", ()),
            error_feedback=spec.get("error_feedback", True),
            seed=spec.get("seed", 0),
            **kwargs,
        )

    @property
    def enabled(self) -> bool:
        return self.codec.name != "none"

    def select(self, key: str, arr) -> Codec:
        if not self.enabled:
            return self._none
        arr = np.asarray(arr)
        if arr.nbytes < self.min_bytes or arr.dtype != np.float32:
            return self._none
        if any(p.search(key) for p in self._exclude):
            return self._none
        return self.codec

    def residual_norm(self) -> float:
        return self.codec.residual_norm()
