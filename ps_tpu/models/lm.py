"""Minimal causal transformer LM — the long-context workload.

Written TPU-first as pure functions over a flat-friendly param dict (the
same tree the PS store shards by key), so the Megatron partition rules in
:func:`lm_partition_rules` apply verbatim and the attention op is pluggable:
``attn='full'`` for single-device/small contexts, ``'ring'`` or ``'ulysses'``
(ps_tpu/parallel/ring_attention.py) when activations are sharded over a
'seq' mesh axis. Pre-norm blocks, learned positions, weight-tied readout —
small on purpose: the model is the vehicle for the parallelism, the PS
protocol around it is identical to every other workload.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def init_params(rng: np.random.Generator, *, vocab: int, d_model: int,
                n_heads: int, n_layers: int, d_ff: Optional[int] = None,
                max_len: int = 2048) -> Dict:
    """He/scaled-normal init of the full parameter tree."""
    d_ff = d_ff or 4 * d_model

    def t(*shape, scale=None):
        scale = scale if scale is not None else (1.0 / math.sqrt(shape[0]))
        return jnp.asarray(rng.normal(0, scale, shape).astype(np.float32))

    params: Dict = {
        "embed": {"tokens": t(vocab, d_model, scale=0.02),
                  "positions": t(max_len, d_model, scale=0.02)},
        "final_norm": {"scale": jnp.ones((d_model,))},
    }
    for i in range(n_layers):
        params[f"layer{i}"] = {
            "ln1": {"scale": jnp.ones((d_model,))},
            "attn": {
                "qkv": {"kernel": t(d_model, 3 * d_model)},
                "out": {"kernel": t(d_model, d_model)},
            },
            "ln2": {"scale": jnp.ones((d_model,))},
            "mlp": {
                "in": {"kernel": t(d_model, d_ff)},
                "out": {"kernel": t(d_ff, d_model)},
            },
        }
    return params


def lm_partition_rules():
    """Megatron placement for every layer (regexes match all layer indices):
    in-projections column-parallel, out-projections row-parallel, embeddings
    vocab/position-sharded by the default heuristic (left unruled)."""
    return [
        (r"attn/qkv/kernel$", (None, "model")),
        (r"attn/out/kernel$", ("model", None)),
        (r"mlp/in/kernel$", (None, "model")),
        (r"mlp/out/kernel$", ("model", None)),
    ]


def _rmsnorm(x, scale):
    return x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * scale


def _full_attention(q, k, v, causal=True, **_):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)


def make_attn_fn(attn: str = "full", mesh=None, **kw) -> Callable:
    """'full' | 'ring' | 'ulysses' — the latter two need a 'seq' mesh axis
    and activations sharded P(batch, 'seq')."""
    if attn == "full":
        return _full_attention
    from ps_tpu.parallel import ring_attention, ulysses_attention

    op = {"ring": ring_attention, "ulysses": ulysses_attention}[attn]

    def fn(q, k, v, causal=True):
        return op(q, k, v, mesh, causal=causal, **kw)

    return fn


def apply(params: Dict, tokens: jax.Array, *, n_heads: int,
          attn_fn: Callable = _full_attention) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab]."""
    b, t = tokens.shape
    d_model = params["embed"]["tokens"].shape[1]
    dh = d_model // n_heads
    x = (jnp.take(params["embed"]["tokens"], tokens, axis=0)
         + params["embed"]["positions"][:t][None])
    i = 0
    while f"layer{i}" in params:
        lp = params[f"layer{i}"]
        h = _rmsnorm(x, lp["ln1"]["scale"])
        qkv = (h @ lp["attn"]["qkv"]["kernel"]).reshape(b, t, 3 * n_heads, dh)
        q, k, v = jnp.split(qkv, 3, axis=2)
        a = attn_fn(q, k, v, causal=True).reshape(b, t, d_model)
        x = x + a @ lp["attn"]["out"]["kernel"]
        h = _rmsnorm(x, lp["ln2"]["scale"])
        h = jax.nn.gelu(h @ lp["mlp"]["in"]["kernel"])
        x = x + h @ lp["mlp"]["out"]["kernel"]
        i += 1
    x = _rmsnorm(x, params["final_norm"]["scale"])
    return x @ params["embed"]["tokens"].T  # tied readout


def make_loss_fn(*, n_heads: int, attn_fn: Callable = _full_attention):
    """Next-token cross entropy, meaned over the global batch. The batch
    carries pre-shifted ``inputs``/``targets`` [B, T] (T divisible by the
    'seq' axis, so both shard cleanly — see :func:`lm_batches`)."""

    def loss_fn(params, batch):
        logits = apply(params, batch["inputs"], n_heads=n_heads,
                       attn_fn=attn_fn)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        ll = jnp.take_along_axis(logp, batch["targets"][..., None], -1)[..., 0]
        return -jnp.mean(ll)

    return loss_fn


def lm_batches(batch_size: int, seq_len: int, *, vocab: int = 256,
               seed: int = 0, steps: Optional[int] = None):
    """Deterministic synthetic token streams with LEARNABLE structure:
    next token = (3·start + 7·position) mod vocab, plus noise tokens — a
    causal model's loss decreases fast, random guessing doesn't. Yields
    pre-shifted ``{"inputs": [B, T], "targets": [B, T]}``.
    """
    rng = np.random.default_rng(seed)
    i = 0
    while steps is None or i < steps:
        start = rng.integers(0, vocab, size=(batch_size, 1))
        ramp = np.arange(seq_len + 1)[None, :]
        toks = (start * 3 + ramp * 7) % vocab
        noise = rng.random((batch_size, seq_len + 1)) < 0.05
        toks = np.where(noise, rng.integers(0, vocab, toks.shape), toks)
        toks = toks.astype(np.int32)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        i += 1
