"""Minimal causal transformer LM — the long-context workload.

Written TPU-first as pure functions over a flat-friendly param dict (the
same tree the PS store shards by key), so the Megatron partition rules in
:func:`lm_partition_rules` apply verbatim and the attention op is pluggable:
``attn='full'`` for single-device/small contexts, ``'ring'`` or ``'ulysses'``
(ps_tpu/parallel/ring_attention.py) when activations are sharded over a
'seq' mesh axis. Pre-norm blocks, learned positions, weight-tied readout —
small on purpose: the model is the vehicle for the parallelism, the PS
protocol around it is identical to every other workload.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def init_params(rng: np.random.Generator, *, vocab: int, d_model: int,
                n_heads: int, n_layers: int, d_ff: Optional[int] = None,
                max_len: int = 2048) -> Dict:
    """He/scaled-normal init of the full parameter tree."""
    d_ff = d_ff or 4 * d_model

    def t(*shape, scale=None):
        scale = scale if scale is not None else (1.0 / math.sqrt(shape[0]))
        return jnp.asarray(rng.normal(0, scale, shape).astype(np.float32))

    params: Dict = {
        "embed": {"tokens": t(vocab, d_model, scale=0.02),
                  "positions": t(max_len, d_model, scale=0.02)},
        "final_norm": {"scale": jnp.ones((d_model,))},
    }
    for i in range(n_layers):
        params[f"layer{i}"] = {
            "ln1": {"scale": jnp.ones((d_model,))},
            "attn": {
                "qkv": {"kernel": t(d_model, 3 * d_model)},
                "out": {"kernel": t(d_model, d_model)},
            },
            "ln2": {"scale": jnp.ones((d_model,))},
            "mlp": {
                "in": {"kernel": t(d_model, d_ff)},
                "out": {"kernel": t(d_ff, d_model)},
            },
        }
    return params


def lm_partition_rules():
    """Megatron placement for every layer (regexes match all layer indices):
    in-projections column-parallel, out-projections row-parallel, embeddings
    vocab/position-sharded by the default heuristic (left unruled)."""
    return [
        (r"attn/qkv/kernel$", (None, "model")),
        (r"attn/out/kernel$", ("model", None)),
        (r"mlp/in/kernel$", (None, "model")),
        (r"mlp/out/kernel$", ("model", None)),
    ]


def _rmsnorm(x, scale):
    return x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * scale


def _full_attention(q, k, v, causal=True, **_):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        t = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None, None], s, -1e30)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)


def make_attn_fn(attn: str = "full", mesh=None, **kw) -> Callable:
    """'full' | 'flash' | 'ring' | 'ulysses'. 'flash' is the single-device
    Pallas kernel (O(S) attention memory; seq must be a multiple of 128);
    'ring'/'ulysses' need a 'seq' mesh axis and activations sharded
    P(batch, 'seq')."""
    if attn == "full":
        return _full_attention
    if attn == "flash":
        from ps_tpu.ops import flash_attention

        def flash_fn(q, k, v, causal=True):
            return flash_attention(q, k, v, causal=causal, **kw)

        return flash_fn
    from ps_tpu.parallel import ring_attention, ulysses_attention

    op = {"ring": ring_attention, "ulysses": ulysses_attention}[attn]

    def fn(q, k, v, causal=True):
        return op(q, k, v, mesh, causal=causal, **kw)

    return fn


def block_apply(lp: Dict, x: jax.Array, *, n_heads: int,
                attn_fn: Callable = _full_attention) -> jax.Array:
    """One pre-norm transformer block: activations [B, T, D] -> [B, T, D].
    The homogeneous unit the pipeline trunk repeats."""
    b, t, d_model = x.shape
    dh = d_model // n_heads
    h = _rmsnorm(x, lp["ln1"]["scale"])
    qkv = (h @ lp["attn"]["qkv"]["kernel"]).reshape(b, t, 3 * n_heads, dh)
    q, k, v = jnp.split(qkv, 3, axis=2)
    a = attn_fn(q, k, v, causal=True).reshape(b, t, d_model)
    x = x + a @ lp["attn"]["out"]["kernel"]
    h = _rmsnorm(x, lp["ln2"]["scale"])
    h = jax.nn.gelu(h @ lp["mlp"]["in"]["kernel"])
    return x + h @ lp["mlp"]["out"]["kernel"]


def embed_apply(params: Dict, tokens: jax.Array) -> jax.Array:
    """The heterogeneous FIRST stage: tokens [B, T] -> activations [B, T, D]."""
    t = tokens.shape[-1]
    return (jnp.take(params["embed"]["tokens"], tokens, axis=0)
            + params["embed"]["positions"][:t][None])


def readout_apply(params: Dict, x: jax.Array) -> jax.Array:
    """The heterogeneous LAST stage: final norm + weight-tied readout,
    activations [B, T, D] -> logits [B, T, vocab]."""
    x = _rmsnorm(x, params["final_norm"]["scale"])
    return x @ params["embed"]["tokens"].T


def apply(params: Dict, tokens: jax.Array, *, n_heads: int,
          attn_fn: Callable = _full_attention) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab]."""
    x = embed_apply(params, tokens)
    i = 0
    while f"layer{i}" in params:
        x = block_apply(params[f"layer{i}"], x, n_heads=n_heads,
                        attn_fn=attn_fn)
        i += 1
    return readout_apply(params, x)


def split_pipeline_params(params: Dict, num_stages: int) -> Dict:
    """Rearrange an :func:`init_params` tree for dp x pp training.

    Heterogeneous-stage layout (VERDICT r4 item 9): the embed and readout
    params — whose shapes differ from the trunk blocks — stay as ordinary
    (data-parallel / ZeRO) tensors under their own keys, while the
    ``n_layers`` homogeneous blocks are stacked ``[S, k, ...]`` under
    ``"stages"`` (S pipeline stages of k layers each) for ``P('pipe', ...)``
    placement. In the SPMD-stacked GPipe formulation every device executes
    every tick anyway, so placing embed/readout *inside* stage 0 / S-1
    would not save compute — it would only replicate their work across all
    M+S-1 ticks and force a union param structure (the vocab table stacked
    S times). Outside the trunk they run once per microbatch, sharded over
    'data' like any dense tensor — the TPU-native spelling of "first/last
    stages may differ".
    """
    n_layers = 0
    while f"layer{n_layers}" in params:
        n_layers += 1
    if n_layers == 0 or n_layers % num_stages:
        raise ValueError(
            f"{n_layers} layers do not split into {num_stages} equal stages"
        )
    k = n_layers // num_stages
    stages = []
    for s in range(num_stages):
        group = [params[f"layer{s * k + j}"] for j in range(k)]
        stages.append(jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *group
        ))
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *stages
    )
    return {"embed": params["embed"], "final_norm": params["final_norm"],
            "stages": stacked}


def pipeline_lm_partition_rules(extra=()):
    """Partition rules for a :func:`split_pipeline_params` tree: every
    ``stages/`` leaf's leading dim on 'pipe' (via the generic
    pipeline-rule generator); embed/readout left to the default (data)
    heuristic or to ``extra`` rules."""
    from ps_tpu.parallel.pipeline import pipeline_partition_rules

    return pipeline_partition_rules(max_rank=5, pattern=r"^stages/") \
        + list(extra)


def make_pipelined_loss_fn(*, n_heads: int, num_stages: int,
                           microbatches: int, mesh=None,
                           attn_fn: Callable = _full_attention):
    """Next-token CE through the dp x pp pipeline.

    The composite step: embed (heterogeneous first stage, once per
    microbatch, data-sharded) -> GPipe trunk over the 'pipe' axis
    (ps_tpu/parallel/pipeline.py) -> final-norm + tied readout
    (heterogeneous last stage). Parity vs the non-pipelined
    :func:`make_loss_fn` is asserted in tests/test_pipeline.py.
    ``params`` must be a :func:`split_pipeline_params` tree placed with
    :func:`pipeline_lm_partition_rules`.
    """
    from ps_tpu.parallel.pipeline import make_pipeline_fn, microbatch

    def stage_fn(stage_params, x):
        # stage_params leaves are [k, ...]: k layers of this stage,
        # statically unrolled
        k = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        for j in range(k):
            lp = jax.tree_util.tree_map(lambda l, _j=j: l[_j], stage_params)
            x = block_apply(lp, x, n_heads=n_heads, attn_fn=attn_fn)
        return x

    pipe_fn = make_pipeline_fn(stage_fn, mesh, microbatches=microbatches)

    def loss_fn(params, batch):
        x = embed_apply(params, batch["inputs"])       # [B, T, D]
        h = pipe_fn(params["stages"], microbatch(x, microbatches))
        h = h.reshape((-1,) + h.shape[2:])             # [B, T, D]
        logits = readout_apply(params, h)
        return token_ce(logits, batch["targets"])

    return loss_fn


def make_loss_fn(*, n_heads: int, attn_fn: Callable = _full_attention):
    """Next-token cross entropy, meaned over the global batch. The batch
    carries pre-shifted ``inputs``/``targets`` [B, T] (T divisible by the
    'seq' axis, so both shard cleanly — see :func:`lm_batches`)."""

    def loss_fn(params, batch):
        logits = apply(params, batch["inputs"], n_heads=n_heads,
                       attn_fn=attn_fn)
        return token_ce(logits, batch["targets"])

    return loss_fn


def token_ce(logits, targets):
    """Mean next-token CE in logsumexp form — no [B, T, V] f32
    log-probability tensor is materialized (see bert.mlm_loss)."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), -1)
    tok = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
    return jnp.mean(lse - tok.astype(jnp.float32))


def lm_batches(batch_size: int, seq_len: int, *, vocab: int = 256,
               seed: int = 0, steps: Optional[int] = None):
    """Deterministic synthetic token streams with LEARNABLE structure:
    next token = (3·start + 7·position) mod vocab, plus noise tokens — a
    causal model's loss decreases fast, random guessing doesn't. Yields
    pre-shifted ``{"inputs": [B, T], "targets": [B, T]}``.
    """
    rng = np.random.default_rng(seed)
    i = 0
    while steps is None or i < steps:
        start = rng.integers(0, vocab, size=(batch_size, 1))
        ramp = np.arange(seq_len + 1)[None, :]
        toks = (start * 3 + ramp * 7) % vocab
        noise = rng.random((batch_size, seq_len + 1)) < 0.05
        toks = np.where(noise, rng.integers(0, vocab, toks.shape), toks)
        toks = toks.astype(np.int32)
        yield {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        i += 1
