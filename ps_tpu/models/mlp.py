"""2-layer MLP — the reference's MNIST smoke-test model (BASELINE.json
config 1: "dense push/pull: 2-layer MLP on MNIST").
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """784 → hidden → 10 classifier."""

    hidden: int = 256
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, name="dense1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, name="dense2")(x)
        return x


def cross_entropy_loss(logits, labels):
    """Mean softmax cross-entropy with integer labels."""
    logp = jnp.take_along_axis(
        nn.log_softmax(logits), labels[:, None], axis=-1
    ).squeeze(-1)
    return -logp.mean()
