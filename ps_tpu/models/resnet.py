"""ResNet — the reference's headline benchmark model (BASELINE.json config 2:
"ResNet-50 / ImageNet (dense allreduce path, sync data-parallel)"; SURVEY.md
§3 row 14). The reference was unreadable (SURVEY.md §0) so this is a standard
ResNet-v1.5 written TPU-first:

- bfloat16 compute / float32 params by default: convs and the final matmul
  hit the MXU at full rate; BatchNorm batch statistics are still accumulated
  in float32 (flax's force_float32_reductions) but its *output* stays in the
  compute dtype — an r3 profiler trace showed f32 BN outputs doubled every
  activation/gradient byte on an HBM-bound chip (88% of device time was
  HBM-bound; see BASELINE.md). The softmax/loss stays float32.
- BatchNorm under GSPMD jit: with the batch sharded over the 'data' mesh
  axis, the batch-mean/variance reductions are *global* means — XLA inserts
  the cross-device collectives, so this is synchronized BatchNorm with no
  explicit axis_name plumbing (the TPU equivalent of the reference family's
  per-GPU BN + NCCL allreduce of grads).
- NHWC layout throughout (TPU-native conv layout).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """Two 3x3 convs — ResNet-18/34 block (used by tests as a small stand-in)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck — the ResNet-50/101/152 block (v1.5: the
    stride lives on the 3x3, matching the variant every modern benchmark
    reports)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale: residual branch starts as identity,
        # the standard trick for large-batch ResNet convergence
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Generic ResNet over NHWC inputs.

    Attributes:
      stage_sizes: blocks per stage, e.g. (3, 4, 6, 3) for ResNet-50.
      block_cls: BasicBlock or BottleneckBlock.
      num_classes: classifier width.
      num_filters: stem width (64 for the standard family).
      dtype: compute dtype (bfloat16 default — MXU-native).
      small_inputs: replace the 7x7/stride-2 stem + maxpool with a 3x3/stride-1
        stem for CIFAR-sized images (used by tests/tiny dry-runs).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_inputs: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            padding="SAME",
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    conv=conv, norm=norm, act=act, strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
                     name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)


def make_loss_fn(model, label_smoothing: float = 0.0):
    """Standard PS-step loss closure for a BatchNorm model.

    Returns ``loss_fn(params, batch, model_state) -> (loss, new_model_state)``
    for use with ``KVStore.make_step(loss_fn, has_aux=True)``: the mutable
    ``batch_stats`` collection threads through the fused step as aux state.
    """

    def loss_fn(params, batch, model_state):
        images, labels = batch
        logits, mutated = model.apply(
            {"params": params, "batch_stats": model_state},
            images, train=True, mutable=["batch_stats"],
        )
        loss = cross_entropy_loss(logits, labels, label_smoothing)
        return loss, mutated["batch_stats"]

    return loss_fn


def cross_entropy_loss(logits, labels, label_smoothing: float = 0.0):
    """Mean softmax cross-entropy over integer labels, float32 numerics."""
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if label_smoothing:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / num_classes
    logp = nn.log_softmax(logits.astype(jnp.float32))
    return -(onehot * logp).sum(axis=-1).mean()
