"""Wide-&-Deep for Criteo-style CTR — reference workload config 4
(BASELINE.json: "sparse push/pull: Wide-&-Deep on Criteo (row-sparse
embedding tables)"; SURVEY.md §3 row 16).

The module holds only the DENSE parameters (wide linear + deep MLP); the
embedding tables live in ps_tpu SparseEmbedding stores and their gathered
rows come in as inputs — mirroring the reference split where tables are
server-resident and workers hold only activations. All 26 categorical
features share one row space via per-feature id offsets (the standard
hashed-Criteo layout), so one sharded table serves the deep side (dim D)
and one the wide side (dim 1).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class WideDeepConfig:
    num_dense: int = 13
    num_sparse: int = 26
    per_feature_vocab: int = 100_000
    embed_dim: int = 16
    mlp: Sequence[int] = (256, 128, 64)

    @property
    def total_rows(self) -> int:
        return self.num_sparse * self.per_feature_vocab

    def global_ids(self, sparse_ids):
        """Map per-feature ids [B, F] into the shared row space."""
        offsets = jnp.arange(self.num_sparse, dtype=jnp.int32) * self.per_feature_vocab
        return sparse_ids + offsets[None, :]


class WideDeep(nn.Module):
    """Dense half of Wide-&-Deep: ``(dense, deep_rows, wide_rows) -> logit``.

    deep_rows: [B, F, D] gathered deep-embedding rows.
    wide_rows: [B, F, 1] gathered wide (per-id weight) rows.
    """

    cfg: WideDeepConfig

    @nn.compact
    def __call__(self, dense, deep_rows, wide_rows):
        cfg = self.cfg
        # wide: linear over dense features + sum of per-id weights
        wide = nn.Dense(1, name="wide_dense")(dense) + wide_rows.sum(axis=1)
        # deep: MLP over [dense ; flattened embeddings]
        x = jnp.concatenate(
            [dense, deep_rows.reshape(deep_rows.shape[0], -1)], axis=-1
        )
        for i, width in enumerate(cfg.mlp):
            x = nn.relu(nn.Dense(width, name=f"mlp_{i}")(x))
        deep = nn.Dense(1, name="deep_out")(x)
        return (wide + deep)[..., 0]


def bce_loss(logits, labels):
    """Mean sigmoid binary cross-entropy (labels in {0,1})."""
    logits = logits.astype(jnp.float32)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def make_wide_deep_loss_fn(model: WideDeep):
    """Composite-step loss closure for ps_tpu.train.make_composite_step:
    ``loss_fn(dense_params, rows, batch)`` with rows = {'deep', 'wide'}."""

    def loss_fn(params, rows, batch):
        logits = model.apply(
            {"params": params}, batch["dense"], rows["deep"], rows["wide"]
        )
        return bce_loss(logits, batch["label"])

    return loss_fn


def make_ids_fn(cfg: WideDeepConfig):
    def ids_fn(batch):
        gids = cfg.global_ids(batch["sparse"])
        return {"deep": gids, "wide": gids}

    return ids_fn
