"""Model zoo for the reference's trainer configs (BASELINE.json).

Implemented: MNIST MLP (`mlp`). Planned per SURVEY.md §8: ResNet-50 (P2),
BERT-base MLM (P3), Wide-&-Deep (P4)."""
