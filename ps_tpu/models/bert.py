"""BERT-base MLM — reference workload config 3 (BASELINE.json: "BERT-base MLM
(dense grads + server-side LAMB optimizer)"; SURVEY.md §3 row 15). The
reference was unreadable (SURVEY.md §0), so this is a standard BERT encoder
written TPU-first:

- bfloat16 compute / float32 params: attention and FFN matmuls are
  MXU-shaped ([B*S, H] x [H, 4H] etc.); LayerNorm and the softmax run in
  float32 for numerics.
- Attention is explicit einsum (no dynamic shapes, no python control flow) —
  XLA fuses scale+mask+softmax into the matmul pipeline.
- The MLM decoder ties to the token embedding (standard BERT weight tying),
  which also keeps the dominant [V, H] matrix a single sharded tensor.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_len: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.bfloat16
    # 'full' = explicit einsum attention; 'flash' = the Pallas fused
    # kernel (ps_tpu/ops/flash_attention.py) — O(S) attention memory, the
    # seq-512 MFU lever measured in BASELINE.md r5. Sequence length must
    # be a multiple of 128 for 'flash'.
    attn: str = "full"

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        """Test-sized config (2 layers, 64 wide)."""
        defaults = dict(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, intermediate_size=128, max_len=64,
                        dtype=jnp.float32)
        defaults.update(kw)
        return BertConfig(**defaults)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.num_heads, head_dim), dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name,
        )
        q = dense("query")(x)  # [B, S, h, d]
        k = dense("key")(x)
        v = dense("value")(x)
        if cfg.attn == "flash":
            from ps_tpu.ops import flash_attention

            out = flash_attention(q, k, v, mask=mask)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
            # mask: [B, S] with 1 = attend; softmax in f32
            bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e9)
            probs = nn.softmax(
                scores.astype(jnp.float32) + bias
            ).astype(cfg.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
            param_dtype=jnp.float32, name="out",
        )(out)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=1e-12, dtype=jnp.float32, param_dtype=jnp.float32, name=name
        )
        # post-LN (original BERT): sublayer -> residual -> LayerNorm
        a = SelfAttention(cfg, name="attention")(x, mask)
        x = ln("ln_attention")(x + a).astype(cfg.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="intermediate")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="output")(h)
        return ln("ln_output")(x + h).astype(cfg.dtype)


class BertMLM(nn.Module):
    """BERT encoder + tied-embedding MLM head.

    ``__call__(input_ids, attention_mask, token_type_ids=None) -> logits
    [B, S, V] (float32)``.
    """

    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, token_type_ids=None):
        cfg = self.cfg
        if input_ids.shape[1] > cfg.max_len:
            raise ValueError(
                f"sequence length {input_ids.shape[1]} exceeds max_len "
                f"{cfg.max_len}; position ids would silently clamp"
            )
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, name="token_embed")
        x = embed(input_ids)
        pos = jnp.arange(input_ids.shape[1])[None, :]
        x = x + nn.Embed(cfg.max_len, cfg.hidden_size, param_dtype=jnp.float32,
                         name="position_embed")(pos)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                         param_dtype=jnp.float32, name="type_embed")(token_type_ids)
        x = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="ln_embed")(x)
        x = x.astype(cfg.dtype)

        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(x, attention_mask)

        # MLM head: transform + tied decoder
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlm_transform")(x)
        x = nn.gelu(x, approximate=True)
        x = nn.LayerNorm(epsilon=1e-12, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="ln_mlm")(x).astype(cfg.dtype)
        logits = embed.attend(x)  # tied weights: [B, S, V]
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros_init(), (cfg.vocab_size,), jnp.float32
        )
        return logits.astype(jnp.float32)


def mlm_loss(logits, labels, ignore_index: int = -100):
    """Mean cross-entropy over masked positions only (labels == ignore_index
    elsewhere, matching the data generator's contract).

    Logsumexp form: ``ce = lse(logits) - logits[label]`` instead of
    gathering from a materialized log_softmax — the [B, S, V] f32
    log-probability tensor (2 GB at bench shapes) never exists; the
    vocab axis is consumed by a fused reduction. Same math to fp
    tolerance (tests/test_bert.py pins it)."""
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tok = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)
    ce = lse - tok[..., 0].astype(jnp.float32)
    n = jnp.maximum(valid.sum(), 1)
    return (ce * valid).sum() / n


def make_mlm_loss_fn(model):
    """PS-step loss closure: ``loss_fn(params, batch) -> loss`` over the
    data generator's {input_ids, labels, attention_mask} dict batches."""

    def loss_fn(params, batch):
        logits = model.apply(
            {"params": params}, batch["input_ids"], batch["attention_mask"]
        )
        return mlm_loss(logits, batch["labels"])

    return loss_fn


def bert_partition_rules():
    """Megatron tensor-parallel placement for :class:`BertMLM` params
    (pass to ``KVStore(partition_rules=...)`` on a mesh with a 'model'
    axis): Q/K/V shard the HEADS dim (column-parallel with their biases),
    the attention out-projection and the FFN output are row-parallel
    (biases replicate — they add after the contraction's psum), the FFN
    intermediate is column-parallel. Embeddings/LayerNorms are left to the
    default heuristic. Parity vs pure data parallelism is asserted in
    tests/test_bert.py."""
    return [
        (r"attention/(query|key|value)/kernel$", (None, "model", None)),
        (r"attention/(query|key|value)/bias$", ("model", None)),
        (r"attention/out/kernel$", ("model", None, None)),
        (r"attention/out/bias$", (None,)),
        (r"/intermediate/kernel$", (None, "model")),
        (r"/intermediate/bias$", ("model",)),
        (r"/output/kernel$", ("model", None)),
        (r"/output/bias$", (None,)),
    ]
