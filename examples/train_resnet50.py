"""ResNet-50 / ImageNet — the reference's headline benchmark workload.

Reference workload config 2 (BASELINE.json): "ResNet-50 / ImageNet (dense
allreduce path, sync data-parallel)". The GPU reference reduces grads over
NCCL intra-node, pushes them over ZMQ to sharded servers, applies momentum
SGD server-side, and pulls updated params. Here the whole protocol is ONE
jitted SPMD step over the device mesh: the batch is sharded on the 'data'
axis, XLA inserts the gradient psum, and the server apply is a sharded optax
update (``placement='sharded'`` partitions params + momentum like ZeRO-1).

Run (any JAX devices; on CPU use XLA_FLAGS=--xla_force_host_platform_device_count=8):
    python examples/train_resnet50.py --steps 30 --batch-size 256 --image-size 64
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import ps_tpu as ps
from ps_tpu.data.prefetch import device_prefetch, threaded_source
from ps_tpu.data.synthetic import imagenet_batches
from ps_tpu.models.resnet import ResNet50, make_loss_fn
from ps_tpu.parallel.sharding import replicated
from ps_tpu.utils import StepLogger, TrainMetrics, trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=256, help="global batch")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--label-smoothing", type=float, default=0.1)
    ap.add_argument("--placement", default="sharded", choices=["replicated", "sharded"])
    ap.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data", default=None, metavar="DIR",
                    help="column-npy dataset directory (fields images, "
                         "labels — see ps_tpu.data.files.write_dataset); "
                         "default: synthetic generator")
    ap.add_argument("--jsonl", default=None, help="append per-step records here")
    ap.add_argument("--profile-dir", default=None, help="jax.profiler trace dir")
    args = ap.parse_args()

    if args.steps < 2:
        raise SystemExit("--steps must be >= 2 (step 0 is compile/warmup)")
    ctx = ps.init(backend="tpu")
    ndev = len(jax.devices())
    if args.batch_size % ndev:
        raise SystemExit(f"--batch-size must be divisible by the device count ({ndev})")

    model = ResNet50(dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32)
    variables = model.init(
        jax.random.key(args.seed),
        jnp.zeros((2, args.image_size, args.image_size, 3)),
        train=False,
    )
    params, model_state = variables["params"], variables["batch_stats"]
    # BN statistics are not optimizer state: keep them replicated on the mesh
    model_state = jax.device_put(model_state, replicated(ctx.mesh))

    store = ps.KVStore(
        optimizer="momentum", learning_rate=args.lr, momentum=args.momentum,
        placement=args.placement,
    )
    store.init(params)
    nparams = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    print(f"ResNet-50: {nparams/1e6:.1f}M params, {ndev} devices, "
          f"global batch {args.batch_size}, placement={args.placement}")

    run = store.make_step(
        make_loss_fn(model, label_smoothing=args.label_smoothing), has_aux=True
    )
    # input path overlap (VERDICT r2 item 7): generation (or the mmap file
    # read) runs in a producer thread, placement double-buffers onto the
    # mesh — per-iteration cost is max(input, step) instead of input + step
    if args.data:
        from ps_tpu.data.files import file_batches

        source = file_batches(args.data, args.batch_size, steps=args.steps,
                              shuffle=True, seed=args.seed,
                              as_tuple=("images", "labels"))
    else:
        source = imagenet_batches(args.batch_size, image_size=args.image_size,
                                  seed=args.seed, steps=args.steps)
    stream = device_prefetch(threaded_source(source),
                             place=store.shard_batch)

    metrics = TrainMetrics(store, batch_size=args.batch_size, num_chips=ndev)
    log = StepLogger(every=10, jsonl=args.jsonl)
    with trace(args.profile_dir):
        for step, batch in enumerate(stream):
            loss, _, model_state = run(batch, model_state)
            if step == 0:
                loss.block_until_ready()
                metrics.mark_compiled()  # exclude compile/warmup from rates
            else:
                metrics.step(loss)
            if log.wants(step):
                log.log(step, loss=float(loss))
        jax.block_until_ready(store.params())
    s = metrics.summary()
    print(f"done: {s['examples_per_sec']:.1f} imgs/s total, "
          f"{s['examples_per_sec_per_chip']:.1f} imgs/s/chip, "
          f"analytic ICI traffic {s['ici_gb_per_device']:.2f} GB "
          f"({s['ici_gbps_per_device']:.2f} GB/s/device)")
    log.close()


if __name__ == "__main__":
    main()
