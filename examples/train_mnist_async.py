"""Async-SGD MNIST — workload config 5 in its REAL deployment shape.

The reference's async mode runs the server and each worker as separate,
deliberately unsynchronized nodes (SURVEY.md §4d): the server applies every
arriving gradient immediately with the DC-ASGD correction; workers compute
against whatever (stale) parameters they last pulled. This trainer exposes
both the single-process form (threads as workers — quick start) and the
cross-process form over the native van's TCP layer.

Single process (threads drive the workers round-robin):
    python examples/train_mnist_async.py --steps 60 --num-workers 3

Cross-process (one terminal per node; server first):
    python examples/train_mnist_async.py --role server --port 7077 \
        --num-workers 2 --steps 60
    python examples/train_mnist_async.py --role worker --server localhost:7077 \
        --worker-id 0 --steps 30
    python examples/train_mnist_async.py --role worker --server localhost:7077 \
        --worker-id 1 --steps 30

Multi-server key partition (the reference's N-server topology, SURVEY.md §3
row 4 — each server owns the key range shard_for_key assigns it; workers
route per-subtree pushes/pulls to the owners):
    python examples/train_mnist_async.py --role server --port 7077 \
        --shard 0 --num-shards 2 --num-workers 2 --steps 60
    python examples/train_mnist_async.py --role server --port 7078 \
        --shard 1 --num-shards 2 --num-workers 2 --steps 60
    python examples/train_mnist_async.py --role worker \
        --server localhost:7077,localhost:7078 --worker-id 0 --steps 30

Replicated shard with live failover (README "Replication & failover" —
kill the primary mid-run; the backup promotes on the heartbeat timeout and
workers ride straight through):
    python examples/train_mnist_async.py --role server --port 7078 \
        --backup --watch-port 7979 --num-workers 1
    python examples/train_mnist_async.py --role server --port 7077 \
        --replicate-to localhost:7078 --beat localhost:7979 --num-workers 1
    python examples/train_mnist_async.py --role worker \
        --server "localhost:7077|localhost:7078" --worker-id 0 --steps 60
"""

from __future__ import annotations

import argparse
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    # some images preload jax with a pinned platform; the env var wins here
    # (the async nodes of one job may deliberately run on different backends)
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

import ps_tpu as ps
from ps_tpu.data.synthetic import mnist_batches
from ps_tpu.models.mlp import MLP, cross_entropy_loss
from ps_tpu.utils import StepLogger


def build(seed: int):
    model = MLP(hidden=32)
    params = model.init(jax.random.key(seed), jnp.zeros((1, 28, 28, 1)))["params"]

    def loss_fn(p, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": p}, images), labels)

    return params, loss_fn


def main():
    # env-var topology (PS_ROLE / DMLC_ROLE launcher style, config.py) is
    # the flag default; explicit flags override
    cfg = ps.Config.from_env()
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", default=cfg.role or "single",
                    choices=["single", "server", "worker"])
    ap.add_argument("--steps", type=int, default=60,
                    help="single/worker: this node's cycles (the server "
                         "drains after every worker disconnects)")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-workers", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--dc-lambda", type=float, default=0.04)
    ap.add_argument("--seed", type=int, default=0)
    # cross-process wiring
    ap.add_argument("--port", type=int, default=0, help="server listen port")
    ap.add_argument("--bind", default="127.0.0.1",
                    help="server listen address (pass 0.0.0.0 explicitly "
                         "for a multi-host job; the endpoint is "
                         "unauthenticated)")
    ap.add_argument("--server", default=cfg.server_uris,
                    help="worker: host:port, comma-separated for an "
                         "N-server partition (or env PS_SERVER_URIS / "
                         "PS_ASYNC_SERVER_URI)")
    ap.add_argument("--worker-id", type=int, default=cfg.worker_id)
    ap.add_argument("--bucket-bytes", type=int,
                    default=cfg.bucket_bytes or 0,
                    help="worker: fusion-bucket size for the bucketed/"
                         "pipelined transport (0 = serial transport; or "
                         "env PS_BUCKET_BYTES)")
    ap.add_argument("--pool", type=int, default=cfg.transport_pool,
                    help="worker: striped connections per server for the "
                         "bucketed transport (env PS_TRANSPORT_POOL)")
    ap.add_argument("--overlap", action="store_true",
                    help="worker: run each push/pull cycle in the "
                         "background (requires --bucket-bytes); gradients "
                         "are still computed against exactly the serial "
                         "step's params")
    ap.add_argument("--compress", default=cfg.compress or "none",
                    choices=["none", "cast16", "int8", "topk"],
                    help="worker: gradient codec for the wire "
                         "(ps_tpu/compress; env PS_COMPRESS). topk keeps "
                         "--compress-topk of each tensor with error-"
                         "feedback residuals")
    ap.add_argument("--compress-topk", type=float, default=cfg.compress_topk,
                    help="worker: kept fraction for --compress topk "
                         "(env PS_COMPRESS_TOPK)")
    ap.add_argument("--compress-min-bytes", type=int,
                    default=cfg.compress_min_bytes,
                    help="worker: tensors under this size always travel "
                         "raw (env PS_COMPRESS_MIN_BYTES)")
    ap.add_argument("--shard", type=int, default=cfg.shard,
                    help="server: this server's index in an N-server key "
                         "partition (or env PS_SHARD)")
    ap.add_argument("--num-shards", type=int, default=cfg.num_shards,
                    help="server: total servers in the key partition "
                         "(or env PS_NUM_SHARDS / DMLC_NUM_SERVER)")
    # shard replication & live failover (README "Replication & failover"):
    # run a second server with --backup --watch-port W; start the primary
    # with --replicate-to backup:port --beat backup:W; point workers at
    # the replica set "primary:port|backup:port" — killing the primary
    # mid-run promotes the backup and the workers ride straight through
    ap.add_argument("--backup", action="store_true",
                    help="server: start in backup role — follow a "
                         "primary's replication stream, refuse worker "
                         "traffic until promoted")
    ap.add_argument("--watch-port", type=int, default=0,
                    help="backup: heartbeat port the PRIMARY must beat "
                         "(--beat); the backup promotes itself when the "
                         "beats stop (0 = no promotion watch)")
    ap.add_argument("--replicate-to", default=None,
                    help="primary: host:port of this shard's backup "
                         "server (attached before workers are admitted)")
    ap.add_argument("--replica-ack", default=cfg.replica_ack,
                    choices=["sync", "async"],
                    help="primary: sync = replies wait for the backup's "
                         "ack (bitwise promotion); async = bounded lag "
                         "(env PS_REPLICA_ACK)")
    ap.add_argument("--replica-window", type=int, default=cfg.replica_window,
                    help="primary: max commits the backup may trail "
                         "(env PS_REPLICA_WINDOW)")
    ap.add_argument("--beat", default=None,
                    help="primary: host:port of the backup's promotion "
                         "watch to heartbeat")
    args = ap.parse_args()
    params, loss_fn = build(args.seed)

    if args.role == "worker":
        uri = args.server or os.environ.get("PS_ASYNC_SERVER_URI")
        if not uri:
            raise SystemExit("worker needs --server host:port "
                             "(or PS_ASYNC_SERVER_URI)")
        from ps_tpu.utils import TrainMetrics

        compress = None
        if args.compress != "none":
            compress = {"codec": args.compress,
                        "topk": args.compress_topk,
                        "min_bytes": args.compress_min_bytes,
                        "pull": cfg.compress_pull}
        w = ps.connect_async(
            uri, args.worker_id, params,
            bucket_bytes=args.bucket_bytes or None,
            pool_size=args.pool if args.bucket_bytes else None,
            compress=compress,
        )
        run = w.make_async_step(loss_fn, overlap=args.overlap)
        log = StepLogger(every=10)
        # the remote worker carries the same byte-counter surface as
        # KVStore, so TrainMetrics reports push/pull GB/s — here those are
        # REAL wire bytes on the van's TCP sockets, the reference's metric
        # in its physical form
        metrics = TrainMetrics(w, batch_size=args.batch_size, num_chips=1)
        # shard the stream by the JOB's worker count (the server's truth)
        stream = mnist_batches(args.batch_size, seed=args.seed,
                               worker=args.worker_id,
                               num_workers=w.num_workers)
        for step in range(args.steps):
            loss = run(next(stream))
            if step == 0:
                metrics.mark_compiled()
            else:
                metrics.step(loss)
            if log.wants(step):
                log.log(step, loss=float(loss), version=w.version)
        if args.overlap:
            w.flush()  # land the final background cycle before reporting
        s = metrics.summary()
        print(f"worker {args.worker_id}: done at server version {w.version}; "
              f"wire push {s['push_gb']:.4f} GB / pull {s['pull_gb']:.4f} GB "
              f"({s['push_pull_gbps']:.3f} GB/s)")
        if "overlap_efficiency" in s:
            print(f"worker {args.worker_id}: overlap efficiency "
                  f"{s['overlap_efficiency']:.2f} "
                  f"({s['transport_hidden_s']:.2f}s of transport hidden "
                  f"under compute)")
        if "compress_ratio" in s:
            extra = (f", residual norm {s['residual_norm']:.4f}"
                     if "residual_norm" in s else "")
            print(f"worker {args.worker_id}: compression "
                  f"{s['compress_ratio']:.2f}x raw/wire "
                  f"({s['codec_s']:.2f}s in codecs{extra})")
        w.close()
        return

    ps.init(backend="tpu", mode="async", num_workers=args.num_workers,
            dc_lambda=args.dc_lambda)
    store = ps.KVStore(optimizer="sgd", learning_rate=args.lr, mode="async")
    if args.role == "server" and args.num_shards is not None:
        # own only this server's key range of the partition
        store.init(ps.shard_tree(params, args.shard, args.num_shards))
    else:
        store.init(params)

    if args.role == "server":
        import time

        svc = ps.serve_async(store, port=args.port, bind=args.bind,
                             shard=args.shard, num_shards=args.num_shards,
                             backup=args.backup)
        shard_note = ("" if args.num_shards is None else
                      f", shard {args.shard}/{args.num_shards}")
        watch = hb = None
        if args.backup:
            if args.watch_port:
                watch = ps.PromotionWatch(svc, primary_id=1,
                                          port=args.watch_port,
                                          bind=args.bind)
            print(f"async PS BACKUP on port {svc.port}{shard_note} — "
                  f"following the primary"
                  + (f", promotion watch on :{watch.port}" if watch else ""),
                  flush=True)
            while svc.role == "backup":  # until promoted (or Ctrl-C)
                time.sleep(0.1)
            print(f"promoted to primary (reason={svc.promote_reason}, "
                  f"epoch {svc.epoch}) — now serving workers", flush=True)
        else:
            if args.replicate_to:
                host, port = args.replicate_to.rsplit(":", 1)
                svc.attach_backup(host, int(port), ack=args.replica_ack,
                                  window=args.replica_window)
            if args.beat:
                from ps_tpu.control.heartbeat import HeartbeatClient

                host, port = args.beat.rsplit(":", 1)
                hb = HeartbeatClient(host, int(port), node_id=1)
            print(f"async PS server on port {svc.port} "
                  f"({args.num_workers} workers expected{shard_note})"
                  + (f", replicating to {args.replicate_to} "
                     f"[{args.replica_ack}]" if args.replicate_to else ""),
                  flush=True)
        # quiesce on worker goodbyes, not push counts: a worker SHUTDOWNs
        # only after its last reply arrived, so stop() cannot race a reply
        # (the r4 flake — see backends/van_service.py)
        svc.wait_for_goodbyes(args.num_workers)
        hist = dict(store._engine.staleness_hist)
        print(f"served {svc.apply_log.total} pushes, "
              f"final version {store._engine.version}, "
              f"staleness histogram {dict(sorted(hist.items()))}")
        if watch is not None:
            watch.close()
        if hb is not None:
            hb.close(goodbye=True)  # planned leave: peers see 'left'
        svc.stop()
        ps.shutdown()
        return

    # single process: drive workers round-robin (staleness accrues because
    # each worker re-pulls only on its own turn)
    run = store.make_async_step(loss_fn)
    log = StepLogger(every=10)
    streams = [
        mnist_batches(args.batch_size, seed=args.seed, worker=w,
                      num_workers=args.num_workers)
        for w in range(args.num_workers)
    ]
    for step in range(args.steps):
        w = step % args.num_workers
        loss = run(next(streams[w]), worker=w)
        if log.wants(step):
            log.log(step, loss=float(loss), worker=w,
                    staleness=store._engine.staleness(w))
    hist = dict(store._engine.staleness_hist)
    print(f"done: version {store._engine.version}, "
          f"staleness histogram {dict(sorted(hist.items()))}")
    ps.shutdown()


if __name__ == "__main__":
    main()
