"""BERT-base MLM with server-side LAMB — reference workload config 3.

Reference workload (BASELINE.json): "BERT-base MLM (dense grads + server-side
LAMB optimizer)". The GPU reference pushes dense grads to PS servers that
apply LAMB; here LAMB runs as a sharded optax update inside the fused SPMD
step — the layerwise trust-ratio norms are per parameter tensor, so with
ZeRO-1 'sharded' placement XLA inserts the per-tensor norm reduces
(SURVEY.md §8 hard part (b); the parity test in tests/test_bert.py asserts
shard-exact numerics).

Run (CPU: XLA_FLAGS=--xla_force_host_platform_device_count=8):
    python examples/train_bert_mlm.py --steps 20 --batch-size 32 --seq-len 128
"""

from __future__ import annotations

import argparse
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    # some images preload jax with a pinned platform; the env var wins here
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

import ps_tpu as ps
from ps_tpu.data.synthetic import mlm_batches
from ps_tpu.models.bert import (BertConfig, BertMLM,
                                bert_partition_rules, make_mlm_loss_fn)
from ps_tpu.utils import StepLogger, TrainMetrics, trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32, help="global batch")
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--weight-decay", type=float, default=0.01)
    ap.add_argument("--size", default="base", choices=["base", "tiny"])
    ap.add_argument("--placement", default="sharded", choices=["replicated", "sharded"])
    ap.add_argument("--model-axis", type=int, default=1,
                    help="tensor-parallel width: Megatron placement via "
                         "bert_partition_rules over a 'model' mesh axis")
    ap.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--jsonl", default=None)
    ap.add_argument("--profile-dir", default=None)
    args = ap.parse_args()

    if args.steps < 2:
        raise SystemExit("--steps must be >= 2 (step 0 is compile/warmup)")
    ndev_all = len(jax.devices())
    tp = args.model_axis
    if tp > 1:
        if ndev_all % tp:
            raise SystemExit(f"--model-axis {tp} must divide the device "
                             f"count ({ndev_all})")
        ps.init(backend="tpu",
                mesh_shape={"data": ndev_all // tp, "model": tp})
    else:
        ps.init(backend="tpu")
    dp = ndev_all // tp if tp > 1 else ndev_all  # data-axis size
    if args.batch_size % dp:
        raise SystemExit(f"--batch-size must be divisible by the data-axis size ({dp})")

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    cfg = BertConfig(dtype=dtype) if args.size == "base" else BertConfig.tiny(dtype=dtype)
    model = BertMLM(cfg)
    shape = (2, args.seq_len)
    params = model.init(
        jax.random.key(args.seed),
        jnp.zeros(shape, jnp.int32), jnp.ones(shape, jnp.int32),
    )["params"]

    store = ps.KVStore(optimizer="lamb", learning_rate=args.lr,
                       weight_decay=args.weight_decay, placement=args.placement,
                       partition_rules=bert_partition_rules() if tp > 1 else None)
    store.init(params)
    nparams = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    print(f"BERT-{args.size} MLM: {nparams/1e6:.1f}M params, {ndev_all} "
          f"devices (data={dp}, model={tp}), "
          f"global batch {args.batch_size} x seq {args.seq_len}, "
          f"LAMB placement={args.placement}")

    run = store.make_step(make_mlm_loss_fn(model))
    stream = mlm_batches(args.batch_size, args.seq_len,
                         vocab_size=cfg.vocab_size, seed=args.seed,
                         steps=args.steps)

    # all chips participate in every step (dp AND tp): per-chip
    # rates divide by the full device count, not the data-axis size
    metrics = TrainMetrics(store, batch_size=args.batch_size,
                           num_chips=ndev_all)
    log = StepLogger(every=10, jsonl=args.jsonl)
    with trace(args.profile_dir):
        for step, batch in enumerate(stream):
            batch = store.shard_batch(
                {k: jnp.asarray(v) for k, v in batch.items()}
            )
            loss, _ = run(batch)
            if step == 0:
                loss.block_until_ready()
                metrics.mark_compiled()
            else:
                metrics.step(loss)
            if log.wants(step):
                log.log(step, loss=float(loss))
        jax.block_until_ready(store.params())
    s = metrics.summary()
    print(f"done: {s['examples_per_sec']:.1f} seq/s total, "
          f"{s['examples_per_sec_per_chip']:.1f} seq/s/chip, "
          f"analytic ICI traffic {s['ici_gb_per_device']:.2f} GB "
          f"({s['ici_gbps_per_device']:.2f} GB/s/device)")
    log.close()


if __name__ == "__main__":
    main()
