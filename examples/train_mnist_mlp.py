"""MNIST 2-layer MLP via the local parameter server.

Reference workload config 1 (BASELINE.json): "dense push/pull: 2-layer MLP on
MNIST (single-process local PS, CPU)". Exercises the full per-key
push/aggregate/apply/pull protocol in one process.

Run:  python examples/train_mnist_mlp.py --steps 200 --num-workers 2
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

import ps_tpu as ps
from ps_tpu.data.synthetic import mnist_batches
from ps_tpu.models.mlp import MLP, cross_entropy_loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--num-workers", type=int, default=1)
    ap.add_argument("--optimizer", default="sgd", choices=["sgd", "momentum", "adam", "lamb"])
    ap.add_argument("--mode", default="sync", choices=["sync", "async"])
    ap.add_argument("--backend", default="local", choices=["local", "tpu"],
                    help="'tpu' runs the same protocol on the device mesh "
                         "(async, or sync with one logical worker)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.backend == "tpu" and args.mode == "sync" and args.num_workers > 1:
        raise SystemExit(
            "on the tpu backend the sync worker set IS the mesh's data axis; "
            "use --num-workers 1 (shard the batch) or --mode async"
        )
    ps.init(backend=args.backend, num_workers=args.num_workers, mode=args.mode,
            seed=args.seed)
    model = MLP(hidden=args.hidden)
    params = model.init(jax.random.key(args.seed), jnp.zeros((1, 28, 28, 1)))["params"]

    store = ps.KVStore(optimizer=args.optimizer, learning_rate=args.lr, mode=args.mode)
    store.init(params)

    @jax.jit
    def grad_fn(params, images, labels):
        def loss_fn(p):
            return cross_entropy_loss(model.apply({"params": p}, images), labels)
        return jax.value_and_grad(loss_fn)(params)

    streams = [
        mnist_batches(args.batch_size, seed=args.seed, worker=w,
                      num_workers=args.num_workers, steps=args.steps)
        for w in range(args.num_workers)
    ]

    t0 = time.time()
    params = store.pull_all()
    for step in range(args.steps):
        losses = []
        # PS flow: every worker computes grads against the same pulled
        # version, pushes; the server applies once all pushes arrive.
        for w, stream in enumerate(streams):
            images, labels = next(stream)
            loss, grads = grad_fn(params, jnp.asarray(images), jnp.asarray(labels))
            losses.append(float(loss))
            store.push_all(grads, worker=w)
        params = store.pull_all()
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {np.mean(losses):.4f}")
    dt = max(time.time() - t0, 1e-9)
    gb = (store.bytes_pushed + store.bytes_pulled) / 1e9
    rate = f"{args.steps/dt:.1f} steps/s, push+pull {gb:.3f} GB, {gb/dt:.3f} GB/s" if args.steps else "no steps"
    print(f"done: {args.steps} steps in {dt:.1f}s  ({rate})")


if __name__ == "__main__":
    main()
