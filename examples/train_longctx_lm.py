"""Long-context causal LM over a dp×sp (×tp) mesh.

The long-context workload: a causal transformer whose ACTIVATIONS are
sharded along a 'seq' mesh axis, with ring (or Ulysses) attention doing the
cross-shard mixing — per-device attention memory is O((T/s)²) per block pair
instead of O(T²) — while the PS protocol around it is unchanged: fused
grad + psum + sharded server apply per step. Optional 'model' axis adds
Megatron tensor parallelism via partition rules.

Run on any devices (CPU: JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8):
    python examples/train_longctx_lm.py --steps 20 --seq-len 256 \
        --mesh data=2,seq=4 --attn ring
"""

from __future__ import annotations

import argparse
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import ps_tpu as ps
from ps_tpu.models import lm
from ps_tpu.parallel.mesh import parse_mesh
from ps_tpu.utils import StepLogger, TrainMetrics


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=8, help="global batch")
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=8)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--mesh", default="data=2,seq=4",
                    help="e.g. data=2,seq=4, data=2,model=2,seq=2, or "
                         "data=2,pipe=4 with --microbatches")
    ap.add_argument("--attn", default="ring",
                    choices=["full", "ring", "ulysses"])
    ap.add_argument("--microbatches", type=int, default=0,
                    help="> 0 with a 'pipe' mesh axis: GPipe the "
                         "transformer trunk over it (heterogeneous "
                         "stages: embed/readout stay data-parallel); "
                         "n-layers must divide by the pipe size")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    mesh_shape = parse_mesh(args.mesh)
    if "data" not in mesh_shape:
        raise SystemExit("--mesh needs a 'data' axis (the PS worker/server "
                         "axis), e.g. data=1,seq=8 for pure sequence "
                         "parallelism")
    ctx = ps.init(backend="tpu", mesh_shape=mesh_shape)
    sp = mesh_shape.get("seq", 1)
    pp = mesh_shape.get("pipe", 1)
    if args.attn != "full" and sp <= 1:
        raise SystemExit("--attn ring/ulysses needs a seq axis > 1")
    if args.seq_len % max(sp, 1):
        raise SystemExit("--seq-len must be divisible by the seq axis")
    if (pp > 1) != (args.microbatches > 0):
        raise SystemExit("pipelining needs BOTH a pipe mesh axis and "
                         "--microbatches > 0")
    if pp > 1 and args.attn != "full":
        raise SystemExit("--microbatches composes with full attention "
                         "(ring/ulysses shard the sequence axis the "
                         "pipeline microbatches would re-shard)")
    if pp > 1 and mesh_shape.get("model", 1) > 1:
        raise SystemExit("pipe + model axes do not compose yet: the GPipe "
                         "shard_map replicates stage params over 'model', "
                         "so TP would be silently dropped — use one or "
                         "the other")
    if args.microbatches > 0 and args.batch_size % args.microbatches:
        raise SystemExit("--batch-size must be divisible by --microbatches")
    if pp > 1 and args.n_layers % pp:
        raise SystemExit(f"--n-layers {args.n_layers} must divide into "
                         f"{pp} pipeline stages")

    params = lm.init_params(
        np.random.default_rng(args.seed), vocab=args.vocab,
        d_model=args.d_model, n_heads=args.n_heads, n_layers=args.n_layers,
        max_len=args.seq_len + 1,
    )
    nparams = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    print(f"causal LM: {nparams/1e6:.2f}M params, mesh {mesh_shape}, "
          f"attn={args.attn}, T={args.seq_len}")

    rules = lm.lm_partition_rules() if mesh_shape.get("model", 1) > 1 else None
    attn_fn = lm.make_attn_fn(args.attn, mesh=ctx.mesh)
    if pp > 1:
        # heterogeneous dp x pp: blocks stack on 'pipe', embed/readout
        # stay dense (ps_tpu/models/lm.py) — parity vs non-pipelined is
        # asserted in tests/test_pipeline.py. (No extra Megatron rules:
        # model+pipe is rejected above — the stacked trunk leaves could
        # not match the rank-2 TP rules anyway.)
        params = lm.split_pipeline_params(params, num_stages=pp)
        rules = lm.pipeline_lm_partition_rules()
        loss_fn = lm.make_pipelined_loss_fn(
            n_heads=args.n_heads, num_stages=pp,
            microbatches=args.microbatches, attn_fn=attn_fn,
        )
    else:
        loss_fn = lm.make_loss_fn(n_heads=args.n_heads, attn_fn=attn_fn)
    store = ps.KVStore(optimizer="adam", learning_rate=args.lr,
                       placement="sharded", partition_rules=rules)
    store.init(params)
    run = store.make_step(loss_fn)

    # activations shard batch over 'data' AND sequence over 'seq'
    tok_sharding = NamedSharding(
        ctx.mesh, P("data", "seq" if sp > 1 else None)
    )
    # same input pipeline as the other trainers: generation in a producer
    # thread, 2-deep double-buffered placement overlapping the step
    from ps_tpu.data.prefetch import device_prefetch, threaded_source

    def place(batch):
        return {k: jax.device_put(jnp.asarray(v), tok_sharding)
                for k, v in batch.items()}

    stream = device_prefetch(
        threaded_source(lm.lm_batches(args.batch_size, args.seq_len,
                                      vocab=args.vocab, seed=args.seed,
                                      steps=args.steps)),
        place=place,
    )
    metrics = TrainMetrics(store, batch_size=args.batch_size,
                           num_chips=len(jax.devices()))
    log = StepLogger(every=5)
    for step, placed in enumerate(stream):
        loss, _ = run(placed)
        if step == 0:
            loss.block_until_ready()
            metrics.mark_compiled()
        else:
            metrics.step(loss)
        if log.wants(step):
            log.log(step, loss=float(loss))
    jax.block_until_ready(store.params())
    s = metrics.summary()
    print(f"done: {s['steps_per_sec']:.2f} steps/s, final loss {s['loss']:.4f}")


if __name__ == "__main__":
    main()
