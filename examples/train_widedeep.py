"""Wide-&-Deep on Criteo-like data — the sparse push/pull workload.

Reference workload config 4 (BASELINE.json): "sparse push/pull: Wide-&-Deep
on Criteo (row-sparse embedding tables)". The GPU reference pushes (row_ids,
row_grads) to range-sharded servers that scatter-apply with per-row state;
here the whole composite step — sharded-table row gather, dense grads +
psum, row-grad exchange (all_gather or capacity-bounded all_to_all) +
scatter-apply — is ONE jitted SPMD program (ps_tpu/train.py).

Run (CPU: XLA_FLAGS=--xla_force_host_platform_device_count=8):
    python examples/train_widedeep.py --steps 50 --batch-size 512
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import ps_tpu as ps
from ps_tpu.data.synthetic import criteo_batches
from ps_tpu.kv.sparse import SparseEmbedding
from ps_tpu.models.wide_deep import (
    WideDeep, WideDeepConfig, make_ids_fn, make_wide_deep_loss_fn,
)
from ps_tpu.train import make_composite_step
from ps_tpu.utils import StepLogger, TrainMetrics, trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=512, help="global batch")
    ap.add_argument("--vocab", type=int, default=100_000, help="rows per feature")
    ap.add_argument("--embed-dim", type=int, default=16)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--embed-lr", type=float, default=0.05)
    ap.add_argument("--embed-optimizer", default="adagrad",
                    choices=["sgd", "adagrad", "adam"])
    ap.add_argument("--exchange", default="gather", choices=["gather", "a2a"])
    ap.add_argument("--capacity-factor", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--data", default=None, metavar="DIR",
                    help="column-npy dataset directory (fields dense, "
                         "sparse, label — see ps_tpu.data.files."
                         "write_dataset); default: synthetic generator")
    ap.add_argument("--jsonl", default=None)
    ap.add_argument("--profile-dir", default=None)
    args = ap.parse_args()

    if args.steps < 2:
        raise SystemExit("--steps must be >= 2 (step 0 is compile/warmup)")
    ps.init(backend="tpu")
    ndev = len(jax.devices())
    if args.batch_size % ndev:
        raise SystemExit(f"--batch-size must be divisible by the device count ({ndev})")

    cfg = WideDeepConfig(per_feature_vocab=args.vocab, embed_dim=args.embed_dim)
    model = WideDeep(cfg)
    batch0 = next(criteo_batches(2, vocab_size=cfg.per_feature_vocab, seed=args.seed))
    rows_shape = (2, cfg.num_sparse, cfg.embed_dim)
    params = model.init(
        jax.random.key(args.seed), jnp.asarray(batch0["dense"]),
        jnp.zeros(rows_shape), jnp.zeros(rows_shape[:2] + (1,)),
    )["params"]

    dense = ps.KVStore(optimizer="adam", learning_rate=args.lr, placement="sharded")
    dense.init(params)
    deep = SparseEmbedding(cfg.total_rows, cfg.embed_dim,
                           optimizer=args.embed_optimizer,
                           learning_rate=args.embed_lr,
                           exchange=args.exchange,
                           capacity_factor=args.capacity_factor)
    deep.init(jax.random.key(args.seed + 1), scale=0.01)
    wide = SparseEmbedding(cfg.total_rows, 1, optimizer="sgd",
                           learning_rate=args.embed_lr,
                           exchange=args.exchange,
                           capacity_factor=args.capacity_factor)
    wide.init(jax.random.key(args.seed + 2), scale=0.01)

    ndense = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
    print(f"Wide&Deep: {ndense/1e6:.2f}M dense params + "
          f"{deep.padded_rows * (cfg.embed_dim + 1) / 1e6:.1f}M embedding rows x dims, "
          f"{ndev} devices, global batch {args.batch_size}, "
          f"exchange={args.exchange}")

    run = make_composite_step(
        dense, {"deep": deep, "wide": wide},
        make_wide_deep_loss_fn(model), make_ids_fn(cfg),
    )

    metrics = TrainMetrics(dense, batch_size=args.batch_size, num_chips=ndev)
    log = StepLogger(every=10, jsonl=args.jsonl)
    if args.data:
        from ps_tpu.data.files import file_batches

        stream = file_batches(args.data, args.batch_size, steps=args.steps,
                              shuffle=True, seed=args.seed,
                              fields=("dense", "sparse", "label"))
    else:
        stream = criteo_batches(args.batch_size,
                                vocab_size=cfg.per_feature_vocab,
                                seed=args.seed, steps=args.steps)
    with trace(args.profile_dir):
        for step, batch in enumerate(stream):
            loss, _ = run(dense.shard_batch(
                {k: jnp.asarray(v) for k, v in batch.items()}
            ))
            if step == 0:
                loss.block_until_ready()
                metrics.mark_compiled()
            else:
                metrics.step(loss)
            if log.wants(step):
                log.log(step, loss=float(loss))
        jax.block_until_ready(dense.params())
    s = metrics.summary()
    emb_gb = (deep.bytes_pushed + deep.bytes_pulled
              + wide.bytes_pushed + wide.bytes_pulled) / 1e9
    print(f"done: {s['examples_per_sec']:.1f} ex/s total, "
          f"{s['examples_per_sec_per_chip']:.1f} ex/s/chip, "
          f"dense ICI {s['ici_gb_per_device']:.3f} GB, "
          f"sparse row traffic {emb_gb:.3f} GB "
          f"(+{(deep.collective_bytes + wide.collective_bytes)/1e9:.3f} GB/device collective)")
    for name, emb in (("deep", deep), ("wide", wide)):
        if emb.exchange == "a2a":
            print(f"  {name}: a2a dropped {emb.dropped_rows} of "
                  f"{emb.rows_pushed} rows "
                  f"({100 * emb.dropped_fraction:.3f}%) — raise "
                  f"--capacity-factor if this is not ~0")
    log.close()


if __name__ == "__main__":
    main()
