#!/usr/bin/env python
"""ps_doctor — one-shot fleet health report from the coordinator.

The "where did the millisecond go" answer without ssh-ing into N
processes: one COORD_TELEMETRY round trip (plus the membership view)
rendered as a readable report —

- membership + liveness (who serves, who beats, who left);
- fleet latency quantiles over the telemetry window, computed from
  MERGED raw log2 histogram buckets (README "Fleet telemetry" — a true
  fleet p99, never an average of per-member percentiles);
- the per-step critical-path breakdown (total / flush-wait / wire /
  server-apply / ack-wait, with each phase's share of the step);
- straggler suspects (windowed leave-one-out z-score) and rebalance
  hints, next to the byte-skew trigger;
- SLO rule states (breached / ok / no data);
- the freshness plane (README "Online serving & freshness"): one STATS
  round trip per data-plane member names the **stalest serving tier per
  shard** — which of pump / replica / cache / wire / nm / agg handed out
  the oldest bytes — next to the shard's push→servable lag p99 and the
  share of aged serves inside the PS_FRESHNESS_SLO bound.

Usage::

    python tools/ps_doctor.py --coord host:port [--window 30]
    python tools/ps_doctor.py --coord host:port --json     # machine form
    python tools/ps_doctor.py --coord host:port --strict   # exit 1 on
                                                 # breaches/stragglers

Exit codes: 0 = report produced; 1 = ``--strict`` and the fleet has an
active SLO breach or straggler suspect; 2 = coordinator unreachable
(the fleet then still has PR 5-style per-process observability — this
tool just has nothing fleet-wide to read).
"""

from __future__ import annotations

import argparse
import json
import sys

# tools/ run from the repo root; make that explicit for direct execution
sys.path.insert(0, ".")

from ps_tpu.control import tensor_van as tv  # noqa: E402
from ps_tpu.elastic.member import fetch_telemetry, fetch_view  # noqa: E402


def _ms(v) -> str:
    return "-" if v is None else f"{v:8.3f}"


def freshness_section(view: dict) -> list:
    """Per-shard freshness from one STATS round trip per data-plane
    member: each row carries the shard's merged push→servable lag p99,
    the share of aged serves within the PS_FRESHNESS_SLO bound, and the
    STALEST tier — the serving path (pump / replica / cache / wire / nm /
    agg) whose oldest handed-out bytes had the largest age. Members whose
    STATS fail (or that have no aged serves yet) are skipped; an empty
    list means no member has freshness samples."""
    shards: dict = {}
    for m in view.get("members") or []:
        uri = m.get("uri") or ""
        if ":" not in uri:
            continue
        host, _, port = uri.rpartition(":")
        try:
            ch = tv.Channel.connect(host, int(port), timeout_ms=2000,
                                    retries=1, max_wait_s=0.5)
        except (tv.VanError, OSError, ValueError):
            continue
        try:
            kind, _, _, extra = tv.decode(
                ch.request(tv.encode(tv.STATS, 0, None)))
        except (tv.VanError, OSError):
            continue
        finally:
            ch.close()
        fresh = extra.get("fresh") if kind == tv.OK else None
        if not isinstance(fresh, dict):
            continue
        row = shards.setdefault(m.get("shard"), {
            "shard": m.get("shard"), "aged": 0, "within": 0,
            "lag_p99_ms": None, "clamped": 0, "tiers": {}})
        row["aged"] += int(fresh.get("aged", 0))
        row["within"] += int(fresh.get("within", 0))
        row["clamped"] += int(fresh.get("clamped", 0))
        lag = fresh.get("lag_p99_ms")
        if lag is not None and lag > (row["lag_p99_ms"] or 0):
            row["lag_p99_ms"] = lag  # primaries stamp; backups don't
        for tier, t in (fresh.get("tiers") or {}).items():
            cur = row["tiers"].setdefault(tier, {"n": 0, "max_ms": 0.0})
            cur["n"] += int(t.get("n", 0))
            cur["max_ms"] = max(cur["max_ms"], float(t.get("max_ms", 0)))
    out = []
    for shard in sorted(shards, key=lambda s: (s is None, s)):
        row = shards[shard]
        if not row["aged"]:
            continue
        row["fresh_share"] = round(row["within"] / row["aged"], 4)
        stalest = max(row["tiers"].items(),
                      key=lambda kv: kv[1]["max_ms"], default=None)
        if stalest:
            row["stalest_tier"] = stalest[0]
            row["stalest_age_ms"] = round(stalest[1]["max_ms"], 3)
        out.append(row)
    return out


def native_section(tel: dict) -> dict:
    """The native event loop's fleet view (README "Native
    observability"): the in-loop p99s ps_top's nlp99/qw99 columns show,
    from the same merged fleet quantiles — plus the windowed slow-frame
    count. Empty dict when no member serves through the loop (nothing
    reported the ps_nl_* families)."""
    fleet = tel.get("fleet") or {}
    counters = tel.get("counters") or {}
    out: dict = {}
    rh = fleet.get("ps_nl_read_hit_seconds")
    if rh:
        out["read_hit_p99_ms"] = round(rh["p99"] * 1e3, 3)
        out["read_hits"] = int(rh["count"])
    qw = fleet.get("ps_nl_queue_wait_seconds")
    if qw:
        out["queue_wait_p99_ms"] = round(qw["p99"] * 1e3, 3)
    if out:
        sf = counters.get("ps_nl_slow_frames_total") or {}
        out["slow_frames"] = int(sf.get("delta", 0))
    return out


def render(view: dict, tel: dict, stream=sys.stdout) -> None:
    table = view.get("table") or {}
    print(f"== ps_doctor: fleet of {len(table.get('shards') or [])} "
          f"shard(s), table epoch {table.get('epoch', '?')}, "
          f"telemetry window {tel.get('window_s')}s ==", file=stream)

    print("\n-- members --", file=stream)
    for m in view.get("members") or []:
        rep = m.get("report") or {}
        print(f"  shard {m.get('shard')}  {m.get('uri'):21s} "
              f"{m.get('kind'):6s} hb={m.get('hb_state'):6s} "
              f"keys={m.get('keys')} "
              f"push_qps={rep.get('push_qps')}", file=stream)
    extra = [u for u in tel.get("members") or []
             if u not in {m.get("uri") for m in view.get("members") or []}]
    for u in extra:
        print(f"  (telemetry-only) {u}", file=stream)

    print("\n-- fleet quantiles (merged raw buckets) --", file=stream)
    fleet = tel.get("fleet") or {}
    if not fleet:
        print("  (no histogram telemetry in the window)", file=stream)
    for metric in sorted(fleet):
        s = fleet[metric]
        print(f"  {metric:32s} count={s['count']:>8d}  "
              f"p50={_ms(s['p50'] * 1e3)}ms  p99={_ms(s['p99'] * 1e3)}ms"
              f"  p999={_ms(s['p999'] * 1e3)}ms", file=stream)

    print("\n-- per-member window --", file=stream)
    per = tel.get("per_member") or {}
    if not per:
        print("  (no per-member telemetry in the window)", file=stream)
    for uri in sorted(per):
        row = per[uri]
        cells = []
        for metric in sorted(row):
            short = metric[3:-len("_seconds")] \
                if metric.startswith("ps_") \
                and metric.endswith("_seconds") else metric
            cells.append(f"{short} p99={row[metric]['p99'] * 1e3:.2f}ms")
        print(f"  {uri:21s} " + "  ".join(cells), file=stream)
    counters = tel.get("counters") or {}
    if counters:
        print("  fleet counters (window): "
              + "  ".join(f"{name}=+{int(c['delta'])}"
                          for name, c in sorted(counters.items())),
              file=stream)

    native = native_section(tel)
    if native:
        print("\n-- native loop (in-loop telemetry) --", file=stream)
        if "read_hit_p99_ms" in native:
            print(f"  read-hit serve p99 {native['read_hit_p99_ms']:8.3f}"
                  f"ms over {native.get('read_hits', 0)} hit(s) "
                  f"(zero upcalls)", file=stream)
        if "queue_wait_p99_ms" in native:
            print(f"  ready-queue wait p99 "
                  f"{native['queue_wait_p99_ms']:8.3f}ms", file=stream)
        print(f"  slow frames (window): {native.get('slow_frames', 0)}",
              file=stream)

    print("\n-- per-step breakdown --", file=stream)
    bd = tel.get("breakdown") or {}
    if not bd:
        print("  (no step telemetry yet)", file=stream)
    order = ("total", "flush_wait", "wire_round", "wire", "server_apply",
             "ack_wait", "agg_hold", "native_serve", "client")
    for phase in order:
        row = bd.get(phase)
        if not row:
            continue
        share = row.get("share")
        print(f"  {phase:13s} mean={_ms(row.get('mean_ms'))}ms  "
              f"p99={_ms(row.get('p99_ms'))}ms  "
              f"seconds={row.get('seconds'):10.3f}"
              + (f"  share={share * 100:5.1f}%" if share is not None
                 else ""), file=stream)

    stragglers = tel.get("stragglers") or []
    print("\n-- stragglers --", file=stream)
    if not stragglers:
        print("  none suspected", file=stream)
    for s in stragglers:
        print(f"  shard {s.get('shard')} {s.get('uri')}: "
              f"{s.get('metric')} z={s.get('z')} "
              f"({s.get('mean_ms')}ms vs peers {s.get('others_mean_ms')}"
              f"ms over {s.get('window_count')} sample(s))", file=stream)

    print("\n-- SLO --", file=stream)
    slo = tel.get("slo") or []
    if not slo:
        print("  no rules configured (PS_SLO_RULES)", file=stream)
    for r in slo:
        mark = "BREACH" if r.get("breached") else (
            "no-data" if r.get("value_ms") is None else "ok")
        print(f"  [{mark:7s}] {r.get('rule')}  value={r.get('value_ms')}"
              f"ms threshold={r.get('threshold_ms')}ms", file=stream)

    print("\n-- freshness --", file=stream)
    fresh = freshness_section(view)
    if not fresh:
        print("  (no aged serves yet — no member reported a fresh dict)",
              file=stream)
    for row in fresh:
        lag = row.get("lag_p99_ms")
        print(f"  shard {row['shard']}: "
              f"lag p99={'-' if lag is None else f'{lag:.3f}'}ms  "
              f"fresh={row['fresh_share'] * 100:.1f}% of "
              f"{row['aged']} aged serve(s)  "
              f"stalest tier={row.get('stalest_tier', '-')} "
              f"(oldest {row.get('stalest_age_ms', 0)}ms)"
              + (f"  clock_clamped={row['clamped']}" if row["clamped"]
                 else ""), file=stream)

    hints = tel.get("hints") or []
    if hints:
        print("\n-- rebalance hints --", file=stream)
        for h in hints:
            print(f"  [{h.get('kind')}] {h.get('action')}", file=stream)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--coord", required=True,
                    help="coordinator host:port")
    ap.add_argument("--window", type=float, default=None,
                    help="telemetry window in seconds (default: the "
                         "coordinator's telemetry_window_s)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of the report")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any SLO is breached or a straggler "
                         "is suspected")
    args = ap.parse_args(argv)
    try:
        view = fetch_view(args.coord)
        tel = fetch_telemetry(args.coord, window_s=args.window)
    except Exception as e:
        print(f"ps_doctor: coordinator {args.coord} unreachable: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"view": view, "telemetry": tel,
                          "native": native_section(tel),
                          "freshness": freshness_section(view)},
                         default=str))
    else:
        render(view, tel)
    unhealthy = bool(tel.get("stragglers")) or any(
        r.get("breached") for r in tel.get("slo") or [])
    return 1 if (args.strict and unhealthy) else 0


if __name__ == "__main__":
    sys.exit(main())
