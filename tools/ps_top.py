#!/usr/bin/env python
"""ps_top — live cluster table for the PS data plane.

Polls STATS across every shard's replica set (the same ``|``/``,`` URI
grammar workers use) and renders one line per endpoint: role, epoch,
version, applies, replication lag/degradation, dedup/stale counters, and
the latency p99s the new histogram layer exports (README
"Observability"). Backups answer STATS too (the one data-plane kind a
backup serves), so the table shows the WHOLE fleet, not just primaries.

Usage::

    python tools/ps_top.py --servers "h0:p0|b0:q0,h1:p1" [--interval 2]
    python tools/ps_top.py --servers ... --once          # one table, exit
    python tools/ps_top.py --servers ... --once --json   # machine-readable
    python tools/ps_top.py --coord host:port [--once] [--json]
    python tools/ps_top.py --fleet --coord host:port [--servers fallback]

``--once --json`` prints one JSON object per endpoint (a list), for CI
smoke checks and scripting (tools/ci_bench_smoke.sh's obs leg).

``--fleet`` discovers the member list FROM the coordinator (no more
hand-listing every endpoint on the CLI) and renders the same per-endpoint
STATS table, headed by the coordinator's fleet telemetry: windowed fleet
p99s computed from merged raw histogram buckets (README "Fleet
telemetry"), current straggler suspects, SLO breaches, and rebalance
hints. A ``--servers`` URI passed alongside is the FALLBACK when the
coordinator is down — the old path keeps working, just without the fleet
header.

``--coord`` renders the coordinator's membership view instead (README
"Elastic membership"): the live shard table (epoch, per-shard key count
and byte load, the reported push/pull QPS), each member's liveness from
the PR-4 heartbeat detector — state AND per-peer last-beat age — and the
progress of any in-flight rebalance (moves done/planned, keys moving).
Elastic data-plane members also grow a ``moved`` column in the
``--servers`` table: ``<keys moved away>@e<table epoch>``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# tools/ run from the repo root; make that explicit for direct execution
sys.path.insert(0, ".")

from ps_tpu.backends.common import parse_replica_uri  # noqa: E402
from ps_tpu.control import tensor_van as tv  # noqa: E402

COLS = [
    ("shard", 5), ("addr", 21), ("role", 8), ("promoted", 14),
    ("epoch", 5), ("version", 9),
    ("applies", 9), ("lag", 5), ("repl", 14), ("dedup", 6), ("stale", 6),
    ("moved", 8), ("gbps", 7), ("ack_p99_ms", 10), ("bkt_p99_ms", 10),
    ("loop", 10), ("nlp99", 8), ("qw99", 8), ("padm%", 6), ("reads", 8),
    ("nhit%", 6),
    ("chit%", 6), ("nm%", 6),
    ("rshare%", 7), ("fresh", 7), ("age%", 6),
    ("tier", 6), ("rows", 9), ("sap99", 8),
    ("hot%", 6), ("evict", 7),
]

COORD_COLS = [
    ("shard", 5), ("uri", 21), ("kind", 6), ("node", 4), ("hb", 6),
    ("age_ms", 6), ("keys", 5), ("mbytes", 8), ("push_qps", 8),
    ("pull_qps", 8), ("repl", 9),
]


def poll_endpoint(host: str, port: int, timeout_ms: int = 2000) -> dict:
    """One STATS round trip; errors come back as ``{"error": ...}`` so a
    dead member renders as a row, not a crash."""
    try:
        ch = tv.Channel.connect(host, port, timeout_ms=timeout_ms,
                                retries=1, max_wait_s=0.5)
    except (tv.VanError, OSError) as e:
        return {"error": str(e)}
    try:
        kind, _, _, extra = tv.decode(
            ch.request(tv.encode(tv.STATS, 0, None)))
        if kind != tv.OK:
            return {"error": extra.get("error", "STATS refused")}
        return extra
    except (tv.VanError, OSError) as e:
        return {"error": str(e)}
    finally:
        ch.close()


def poll_fleet(uri: str) -> list:
    """STATS for every member of every shard's replica set, flattened to
    ``[{shard, addr, ...stats}]`` in URI order. Each shard's rows are
    annotated with the set-wide read-replica share (backup-role rows'
    answered reads over the whole set's)."""
    _, sets = parse_replica_uri(uri)
    rows = []
    for shard, members in enumerate(sets):
        shard_rows = []
        for host, port in members:
            st = poll_endpoint(host, port)
            st["shard"] = shard
            st["addr"] = f"{host}:{port}"
            shard_rows.append(st)
        totals = [(_reads_total(st), st.get("role")) for st in shard_rows]
        total = sum(t for t, _ in totals if isinstance(t, int))
        if total:
            backup = sum(t for t, role in totals
                         if isinstance(t, int) and role == "backup")
            for st in shard_rows:
                st["_rshare"] = round(100.0 * backup / total, 1)
        rows.extend(shard_rows)
    return rows


def _p99_ms(st: dict, which: str):
    lat = (st.get("metrics") or {}).get("lat") or {}
    q = lat.get(which)
    return round(q["p99"] * 1e3, 2) if q else None


def _version_of(st: dict):
    v = st.get("version")
    if v is None and isinstance(st.get("versions"), dict):
        v = sum(st["versions"].values())  # sparse: per-table versions
    return v


def render_row(st: dict) -> dict:
    """The table's view of one endpoint's STATS extra."""
    if "error" in st:
        return {"shard": st.get("shard"), "addr": st.get("addr"),
                "role": "DOWN", "promoted": "-", "epoch": "-",
                "version": "-",
                "applies": "-", "lag": "-", "repl": st["error"][:24],
                "dedup": "-", "stale": "-", "moved": "-", "gbps": "-",
                "ack_p99_ms": "-", "bkt_p99_ms": "-", "loop": "-",
                "nlp99": "-", "qw99": "-", "padm%": "-",
                "reads": "-", "nhit%": "-", "chit%": "-", "nm%": "-",
                "rshare%": "-", "fresh": "-", "age%": "-",
                "tier": "-", "rows": "-", "sap99": "-",
                "hot%": "-", "evict": "-"}
    repl = st.get("repl") or {}
    # a live session renders "<ack mode>@<acked seq>" so an operator sees
    # the stream advancing between refreshes; degraded wins the cell
    repl_state = ("degraded" if repl.get("degraded")
                  else f"{repl.get('ack', '?')}@{repl.get('acked_seq', 0)}"
                  if repl else "-")
    # a promoted ex-backup names why (goodbye = planned handoff, timeout
    # = death horizon) and how long the flip took
    promoted = "-"
    if st.get("promote_reason"):
        ms = st.get("promotion_s")
        promoted = st["promote_reason"] + (
            f"/{ms * 1e3:.0f}ms" if isinstance(ms, (int, float)) else "")
    metrics = st.get("metrics") or {}
    return {
        "shard": st["shard"],
        "addr": st["addr"],
        "role": st.get("role", "?"),
        "promoted": promoted,
        "epoch": st.get("epoch", 0),
        "version": _version_of(st),
        "applies": st.get("apply_log_total", "-"),
        "lag": repl.get("lag", st.get("replica_applied_seq", "-")),
        "repl": repl_state,
        "dedup": st.get("dedup_hits", 0),
        "stale": st.get("stale_epochs", 0),
        # elastic members: how many keys a rebalance moved off this shard,
        # at which shard-table epoch (static services have no table_epoch)
        "moved": (f"{st.get('keys_moved', 0)}@e{st['table_epoch']}"
                  if st.get("table_epoch") is not None else "-"),
        "gbps": metrics.get("bucket_gbps", 0.0),
        # `or "-"` would eat a legitimate 0.0 ms p99 (sub-5µs acks round
        # to zero); only a MISSING histogram renders as no-data
        "ack_p99_ms": _opt(_p99_ms(st, "repl_ack_wait_s")),
        "bkt_p99_ms": _opt(_p99_ms(st, "bucket_s")),
        # native event-loop serve path: live conns + frames the loop has
        # read ("-" = classic thread-per-connection serving)
        "loop": (f"{st['loop'].get('conns', 0)}c/"
                 f"{st['loop'].get('requests', 0)}r"
                 if isinstance(st.get("loop"), dict) else "-"),
        # in-loop native p99s (µs, from the STATS loop dict — README
        # "Native observability"): zero-upcall READ-hit serve time and
        # the ready-queue wait pump-bound frames pay before dispatch
        "nlp99": _loop_us(st, "nlp99_us"),
        "qw99": _loop_us(st, "qw99_us"),
        # zero-upcall push plane (README "Push path"): the share of
        # classified push frames the native admission mirror settled
        # without an upcall (replay acks + role refusals)
        "padm%": _admit_pct(st),
        # serve-path read columns (README "Read path"): total READs this
        # endpoint answered (native hits + Python-served) and the
        # native-cache hit share. Backups answering reads show up as
        # their own rows, so the read-replica share of a shard is its
        # backup rows' reads over the set's total.
        "reads": _reads_total(st),
        "nhit%": _native_hit_pct(st),
        "chit%": _cached_read_pct(st),
        # conditional serving (README "Read path"): share of answered
        # reads settled as NOT_MODIFIED handshakes — Python-served NMs
        # plus version-floor native cache hits. A warm steady-state
        # fleet should sit near 100 here; near 0 with conditional reads
        # on means readers never revalidate (cold sets or cache off)
        "nm%": _not_modified_pct(st),
        # computed across the shard's replica set by poll_fleet: the
        # backup rows' reads over the whole set's (same value on every
        # row of a shard — the read-replica share of its traffic)
        "rshare%": _opt(st.get("_rshare")),
        # freshness plane (README "Online serving & freshness"): the
        # push->first-servable lag p99 (ms, primaries only — backups
        # serve but never stamp) and the share of this endpoint's aged
        # serves that landed within the PS_FRESHNESS_SLO bound
        "fresh": _fresh_lag(st),
        "age%": _fresh_share_pct(st),
        # sparse fused apply (README "Sparse apply"): the shard's apply
        # tier, raw row updates applied, and the per-push row-apply p99
        # (ms) — a shard falling off the fused tier shows 'off' here and
        # its sap99 jumps from batch-sized to table-sized
        "tier": _fused_tier(st),
        "rows": (st["fused"].get("rows_applied", "-")
                 if isinstance(st.get("fused"), dict) else "-"),
        "sap99": _opt(_p99_ms(st, "sparse_apply_s")),
        # tiered embedding storage (README "Tiered embedding storage"):
        # hot-set hit share across the shard's tiered tables and its
        # promotion/eviction churn ("-" = every table fully on device)
        "hot%": _hot_pct(st),
        "evict": _tier_churn(st),
    }


def _fresh_lag(st: dict):
    """Push→first-servable lag p99 in ms from the STATS ``fresh`` dict
    ("-" = no freshness samples yet, or a tier that never applies)."""
    f = st.get("fresh")
    if not isinstance(f, dict) or f.get("lag_p99_ms") is None:
        return "-"
    return f["lag_p99_ms"]


def _fresh_share_pct(st: dict):
    """Share of this endpoint's age-stamped serves within the freshness
    bound (PS_FRESHNESS_SLO) — the fleet's at-a-glance age% column."""
    f = st.get("fresh")
    if not isinstance(f, dict) or f.get("fresh_share") is None:
        return "-"
    return round(100.0 * f["fresh_share"], 1)


def _hot_pct(st: dict):
    """Aggregate hot-hit share over the shard's tiered tables ("-" = no
    tiered tables, or nothing pushed/read yet)."""
    tier = st.get("tier")
    if not isinstance(tier, dict) or not tier:
        return "-"
    hits = sum(t.get("hot_hits", 0) for t in tier.values())
    total = hits + sum(t.get("misses", 0) for t in tier.values())
    if not total:
        return "-"
    return f"{100.0 * hits / total:.1f}"


def _tier_churn(st: dict):
    """Promotion/eviction totals as ``<p>/<e>`` — the operator's glance
    at admission churn (a figure climbing every refresh means the hot
    set is thrashing and the budget or admit threshold is wrong)."""
    tier = st.get("tier")
    if not isinstance(tier, dict) or not tier:
        return "-"
    p = sum(t.get("promotions", 0) for t in tier.values())
    e = sum(t.get("evictions", 0) for t in tier.values())
    return f"{p}/{e}"


def _fused_tier(st: dict):
    """One cell for the shard's fused-apply tiers: the common tier, or
    'mixed' when its tables resolved differently ("-" = dense shard)."""
    fused = st.get("fused")
    if not isinstance(fused, dict):
        return "-"
    tiers = set((fused.get("tiers") or {}).values())
    if not tiers:
        return "-"
    return tiers.pop() if len(tiers) == 1 else "mixed"


def _loop_us(st: dict, key: str):
    """One native in-loop p99 cell, rendered as ``<µs>u`` ("-" when the
    endpoint serves threaded, or the histogram is still empty)."""
    loop = st.get("loop")
    if not isinstance(loop, dict) or loop.get(key) is None:
        return "-"
    return f"{loop[key]:.0f}u"


def _admit_pct(st: dict):
    """Native push-admission share: frames the loop's ledger mirror
    settled with zero upcalls (replay acks + role refusals) over every
    push frame it classified ("-" = admission off / no pushes yet)."""
    loop = st.get("loop")
    padm = loop.get("padm") if isinstance(loop, dict) else None
    if not isinstance(padm, dict):
        return "-"
    native = int(padm.get("acks", 0)) + int(padm.get("refusals", 0))
    total = native + int(padm.get("fresh", 0)) + int(padm.get("punts", 0))
    return round(100.0 * native / total, 1) if total else "-"


def _reads_total(st: dict):
    rd = st.get("read")
    if not isinstance(rd, dict):
        return "-"
    return int(rd.get("native_hits", 0)) + int(rd.get("served", 0))


def _cached_read_pct(st: dict):
    """Share of ALL answered reads that came from the native cache
    (hits over hits + Python-served) — the zero-upcall fraction of the
    endpoint's total read traffic."""
    rd = st.get("read")
    if not isinstance(rd, dict):
        return "-"
    hits = int(rd.get("native_hits", 0))
    total = hits + int(rd.get("served", 0))
    return round(100.0 * hits / total, 1) if total else "-"


def _native_hit_pct(st: dict):
    """Native-cache hit share over CACHEABLE frames (hits vs pump-path
    misses) — the zero-upcall fraction of the endpoint's read serving."""
    rd = st.get("read")
    if not isinstance(rd, dict):
        return "-"
    hits = int(rd.get("native_hits", 0))
    total = hits + int(rd.get("native_misses", 0))
    return round(100.0 * hits / total, 1) if total else "-"


def _not_modified_pct(st: dict):
    """Share of ALL answered reads settled as NOT_MODIFIED handshakes
    (stamp-only replies) — Python-served NMs plus the native cache's
    version-floor hits, over the endpoint's total answered reads."""
    rd = st.get("read")
    if not isinstance(rd, dict):
        return "-"
    nm = int(rd.get("nm", 0)) + int(rd.get("native_cond_hits", 0))
    total = int(rd.get("native_hits", 0)) + int(rd.get("served", 0))
    return round(100.0 * nm / total, 1) if total else "-"


def _opt(v):
    return "-" if v is None else v


def _cell(v, w: int) -> str:
    """Over-wide cells keep their TAIL: the low-order digits of
    `async@<acked_seq>` / the ms of `timeout/<ms>` are the part that
    moves between refreshes — truncating the head keeps the table
    showing advancement instead of a frozen prefix."""
    s = str(v)
    return s if len(s) <= w else "…" + s[-(w - 1):]


def print_table(rows: list, stream=sys.stdout) -> None:
    hdr = "  ".join(f"{name:>{w}}" for name, w in COLS)
    print(hdr, file=stream)
    print("-" * len(hdr), file=stream)
    for st in rows:
        r = render_row(st)
        print("  ".join(f"{_cell(r[name], w):>{w}}" for name, w in COLS),
              file=stream)


def render_coord_row(m: dict) -> dict:
    """One membership row of the coordinator view: identity, the PR-4
    heartbeat detector's state + per-peer last-beat age, and the latest
    load report."""
    report = m.get("report") or {}
    nbytes = m.get("nbytes")
    return {
        "shard": m.get("shard"),
        "uri": m.get("uri", "?"),
        "kind": m.get("kind", "?"),
        "node": m.get("node", "-"),
        "hb": m.get("hb_state", "?"),
        "age_ms": _opt(m.get("hb_age_ms")),
        "keys": _opt(m.get("keys")),
        "mbytes": (round(nbytes / 1e6, 1)
                   if isinstance(nbytes, (int, float)) else "-"),
        "push_qps": _opt(report.get("push_qps")),
        "pull_qps": _opt(report.get("pull_qps")),
        "repl": _repl_cell(report.get("repl")),
    }


def _repl_cell(repl) -> str:
    """Replica-pair health at a glance — the same states the autopilot's
    re-seed rule keys on: PROMOTED (backup consumed, no downstream yet)
    is the one that pages."""
    if not isinstance(repl, dict):
        return "-"
    if repl.get("promoted") and not repl.get("attached"):
        return "PROMOTED"
    if repl.get("degraded"):
        return "degraded"
    if repl.get("attached"):
        return "sync"
    return "detached"


def print_coord_view(view: dict, stream=sys.stdout) -> None:
    table = view.get("table") or {}
    mig = view.get("migration")
    head = (f"shard table epoch {table.get('epoch', '?')}  "
            f"shards {len(table.get('shards') or [])}  "
            f"keys {len(table.get('assign') or {})}")
    if mig:
        head += (f"  |  REBALANCING: {mig.get('done', 0)}/"
                 f"{mig.get('moves', 0)} moves, "
                 f"{mig.get('keys', 0)} key(s) in motion")
    print(head, file=stream)
    pol = view.get("policy")
    if pol:
        # the autopilot line: mode, storm-brake state, the last decision
        cool = ",".join(f"{a}:{s}s" for a, s in
                        sorted((pol.get("cooldown") or {}).items()))
        acted = ",".join(f"{k}={n}" for k, n in
                         sorted((pol.get("actions_total") or {}).items()))
        last = pol.get("last_action") or {}
        line = (f"AUTOPILOT mode={pol.get('mode')}  "
                f"spares={len(view.get('spares') or [])}  "
                f"inflight={pol.get('inflight') or '-'}  "
                f"cooldown=[{cool or '-'}]  actions=[{acted or '-'}]")
        if last:
            line += (f"  last={last.get('rule')}/{last.get('action')}"
                     f"->{last.get('outcome')}")
        print(line, file=stream)
        for e in (pol.get("actions") or [])[-3:]:
            # the decision ring's tail: what fired (or was suppressed,
            # and why) — the audit trail COORD_POLICY serves in full
            print(f"  policy {e.get('rule')}/{e.get('action')} "
                  f"-> {e.get('outcome')} {e.get('detail')}", file=stream)
    for h in view.get("hints") or []:
        # the byte-skew trigger and straggler suspects, side by side —
        # the two reasons an operator rebalances
        print(f"HINT [{h.get('kind')}] {h.get('action')}", file=stream)
    hdr = "  ".join(f"{name:>{w}}" for name, w in COORD_COLS)
    print(hdr, file=stream)
    print("-" * len(hdr), file=stream)
    for m in view.get("members") or []:
        r = render_coord_row(m)
        print("  ".join(f"{_cell(r[name], w):>{w}}"
                        for name, w in COORD_COLS), file=stream)


def poll_coord(addr: str) -> dict:
    from ps_tpu.elastic.member import fetch_policy, fetch_view

    try:
        view = fetch_view(addr)
    except Exception as e:  # render, don't crash — same policy as STATS
        return {"error": str(e)}
    if view.get("policy"):
        # the autopilot is on: one extra round trip for the decision
        # ring (COORD_POLICY carries the full audit; the table reply
        # only summarizes)
        try:
            view["policy"]["actions"] = fetch_policy(addr, n=8).get(
                "actions") or []
        except Exception:
            pass  # header still renders without the ring
    return view


def poll_fleet_via_coord(coord: str, fallback_servers=None) -> dict:
    """--fleet: member URIs come from the coordinator's table, telemetry
    from COORD_TELEMETRY; a dead coordinator falls back to the CLI
    ``--servers`` list (old behavior) when one was given."""
    from ps_tpu.elastic.member import fetch_telemetry, fetch_view

    view = poll_coord(coord)
    if "error" in view:
        if fallback_servers:
            return {"fallback": view["error"],
                    "rows": poll_fleet(fallback_servers)}
        return {"error": view["error"]}
    shards = (view.get("table") or {}).get("shards") or []
    rows = poll_fleet(",".join(shards)) if shards else []
    out = {"rows": rows, "view": view}
    try:
        out["telemetry"] = fetch_telemetry(coord)
    except Exception as e:
        out["telemetry_error"] = str(e)
    return out


def print_fleet_header(tel: dict, stream=sys.stdout) -> None:
    """Fleet p99 line + stragglers/SLO/hints above the endpoint table."""
    fleet = tel.get("fleet") or {}
    parts = []
    for metric in sorted(fleet):
        s = fleet[metric]
        short = metric[3:-len("_seconds")] if metric.startswith("ps_") \
            and metric.endswith("_seconds") else metric
        parts.append(f"{short} p99={s['p99'] * 1e3:.2f}ms")
    print(f"fleet window {tel.get('window_s')}s  "
          + ("  ".join(parts) if parts else "(no telemetry yet)"),
          file=stream)
    for s in tel.get("stragglers") or []:
        print(f"  STRAGGLER shard {s.get('shard')} {s.get('uri')}: "
              f"{s.get('metric')} z={s.get('z')} "
              f"({s.get('mean_ms')}ms vs {s.get('others_mean_ms')}ms)",
              file=stream)
    for r in tel.get("slo") or []:
        mark = "BREACH" if r.get("breached") else "ok"
        print(f"  SLO [{mark}] {r.get('rule')}: value "
              f"{r.get('value_ms')}ms / threshold "
              f"{r.get('threshold_ms')}ms", file=stream)
    for h in tel.get("hints") or []:
        if h.get("kind") != "straggler":  # stragglers already rendered
            print(f"  HINT [{h.get('kind')}] {h.get('action')}",
                  file=stream)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--servers",
                    help="replica-set URI, as workers take it: "
                         '"h0:p0|b0:q0,h1:p1"')
    ap.add_argument("--coord",
                    help="coordinator host:port — render the membership/"
                         "shard-table view instead of per-endpoint STATS")
    ap.add_argument("--fleet", action="store_true",
                    help="with --coord: discover the member list from the"
                         " coordinator and render the per-endpoint table "
                         "headed by fleet telemetry (p99s, stragglers, "
                         "SLO); --servers becomes the fallback path")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh cadence in seconds (live mode)")
    ap.add_argument("--once", action="store_true",
                    help="print one table (or --json blob) and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: raw per-endpoint STATS as JSON")
    args = ap.parse_args(argv)
    if args.fleet:
        if args.coord is None:
            ap.error("--fleet discovers members from the coordinator: "
                     "pass --coord host:port (--servers is the fallback)")
    elif (args.servers is None) == (args.coord is None):
        ap.error("pass exactly one of --servers or --coord")

    def snapshot():
        if args.fleet:
            return poll_fleet_via_coord(args.coord, args.servers)
        return poll_coord(args.coord) if args.coord \
            else poll_fleet(args.servers)

    def render(data):
        if args.fleet:
            if "error" in data:
                print(f"coordinator {args.coord}: DOWN ({data['error']}) "
                      f"and no --servers fallback given")
                return
            if "fallback" in data:
                print(f"coordinator {args.coord}: DOWN "
                      f"({data['fallback']}) — falling back to --servers")
            elif "telemetry" in data:
                print_fleet_header(data["telemetry"])
            print_table(data["rows"])
        elif args.coord:
            if "error" in data:
                print(f"coordinator {args.coord}: DOWN ({data['error']})")
            else:
                print_coord_view(data)
        else:
            print_table(data)

    if args.once:
        data = snapshot()
        if args.json:
            print(json.dumps(data, default=str))
        else:
            render(data)
        return 0
    try:
        while True:
            data = snapshot()
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(f"ps_top  {time.strftime('%H:%M:%S')}  "
                  f"({args.coord or args.servers})")
            render(data)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
