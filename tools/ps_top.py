#!/usr/bin/env python
"""ps_top — live cluster table for the PS data plane.

Polls STATS across every shard's replica set (the same ``|``/``,`` URI
grammar workers use) and renders one line per endpoint: role, epoch,
version, applies, replication lag/degradation, dedup/stale counters, and
the latency p99s the new histogram layer exports (README
"Observability"). Backups answer STATS too (the one data-plane kind a
backup serves), so the table shows the WHOLE fleet, not just primaries.

Usage::

    python tools/ps_top.py --servers "h0:p0|b0:q0,h1:p1" [--interval 2]
    python tools/ps_top.py --servers ... --once          # one table, exit
    python tools/ps_top.py --servers ... --once --json   # machine-readable

``--once --json`` prints one JSON object per endpoint (a list), for CI
smoke checks and scripting (tools/ci_bench_smoke.sh's obs leg).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# tools/ run from the repo root; make that explicit for direct execution
sys.path.insert(0, ".")

from ps_tpu.backends.common import parse_replica_uri  # noqa: E402
from ps_tpu.control import tensor_van as tv  # noqa: E402

COLS = [
    ("shard", 5), ("addr", 21), ("role", 8), ("promoted", 14),
    ("epoch", 5), ("version", 9),
    ("applies", 9), ("lag", 5), ("repl", 14), ("dedup", 6), ("stale", 6),
    ("gbps", 7), ("ack_p99_ms", 10), ("bkt_p99_ms", 10),
]


def poll_endpoint(host: str, port: int, timeout_ms: int = 2000) -> dict:
    """One STATS round trip; errors come back as ``{"error": ...}`` so a
    dead member renders as a row, not a crash."""
    try:
        ch = tv.Channel.connect(host, port, timeout_ms=timeout_ms,
                                retries=1, max_wait_s=0.5)
    except (tv.VanError, OSError) as e:
        return {"error": str(e)}
    try:
        kind, _, _, extra = tv.decode(
            ch.request(tv.encode(tv.STATS, 0, None)))
        if kind != tv.OK:
            return {"error": extra.get("error", "STATS refused")}
        return extra
    except (tv.VanError, OSError) as e:
        return {"error": str(e)}
    finally:
        ch.close()


def poll_fleet(uri: str) -> list:
    """STATS for every member of every shard's replica set, flattened to
    ``[{shard, addr, ...stats}]`` in URI order."""
    _, sets = parse_replica_uri(uri)
    rows = []
    for shard, members in enumerate(sets):
        for host, port in members:
            st = poll_endpoint(host, port)
            st["shard"] = shard
            st["addr"] = f"{host}:{port}"
            rows.append(st)
    return rows


def _p99_ms(st: dict, which: str):
    lat = (st.get("metrics") or {}).get("lat") or {}
    q = lat.get(which)
    return round(q["p99"] * 1e3, 2) if q else None


def _version_of(st: dict):
    v = st.get("version")
    if v is None and isinstance(st.get("versions"), dict):
        v = sum(st["versions"].values())  # sparse: per-table versions
    return v


def render_row(st: dict) -> dict:
    """The table's view of one endpoint's STATS extra."""
    if "error" in st:
        return {"shard": st.get("shard"), "addr": st.get("addr"),
                "role": "DOWN", "promoted": "-", "epoch": "-",
                "version": "-",
                "applies": "-", "lag": "-", "repl": st["error"][:24],
                "dedup": "-", "stale": "-", "gbps": "-",
                "ack_p99_ms": "-", "bkt_p99_ms": "-"}
    repl = st.get("repl") or {}
    # a live session renders "<ack mode>@<acked seq>" so an operator sees
    # the stream advancing between refreshes; degraded wins the cell
    repl_state = ("degraded" if repl.get("degraded")
                  else f"{repl.get('ack', '?')}@{repl.get('acked_seq', 0)}"
                  if repl else "-")
    # a promoted ex-backup names why (goodbye = planned handoff, timeout
    # = death horizon) and how long the flip took
    promoted = "-"
    if st.get("promote_reason"):
        ms = st.get("promotion_s")
        promoted = st["promote_reason"] + (
            f"/{ms * 1e3:.0f}ms" if isinstance(ms, (int, float)) else "")
    metrics = st.get("metrics") or {}
    return {
        "shard": st["shard"],
        "addr": st["addr"],
        "role": st.get("role", "?"),
        "promoted": promoted,
        "epoch": st.get("epoch", 0),
        "version": _version_of(st),
        "applies": st.get("apply_log_total", "-"),
        "lag": repl.get("lag", st.get("replica_applied_seq", "-")),
        "repl": repl_state,
        "dedup": st.get("dedup_hits", 0),
        "stale": st.get("stale_epochs", 0),
        "gbps": metrics.get("bucket_gbps", 0.0),
        # `or "-"` would eat a legitimate 0.0 ms p99 (sub-5µs acks round
        # to zero); only a MISSING histogram renders as no-data
        "ack_p99_ms": _opt(_p99_ms(st, "repl_ack_wait_s")),
        "bkt_p99_ms": _opt(_p99_ms(st, "bucket_s")),
    }


def _opt(v):
    return "-" if v is None else v


def _cell(v, w: int) -> str:
    """Over-wide cells keep their TAIL: the low-order digits of
    `async@<acked_seq>` / the ms of `timeout/<ms>` are the part that
    moves between refreshes — truncating the head keeps the table
    showing advancement instead of a frozen prefix."""
    s = str(v)
    return s if len(s) <= w else "…" + s[-(w - 1):]


def print_table(rows: list, stream=sys.stdout) -> None:
    hdr = "  ".join(f"{name:>{w}}" for name, w in COLS)
    print(hdr, file=stream)
    print("-" * len(hdr), file=stream)
    for st in rows:
        r = render_row(st)
        print("  ".join(f"{_cell(r[name], w):>{w}}" for name, w in COLS),
              file=stream)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--servers", required=True,
                    help="replica-set URI, as workers take it: "
                         '"h0:p0|b0:q0,h1:p1"')
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh cadence in seconds (live mode)")
    ap.add_argument("--once", action="store_true",
                    help="print one table (or --json blob) and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: raw per-endpoint STATS as JSON")
    args = ap.parse_args(argv)

    if args.once:
        rows = poll_fleet(args.servers)
        if args.json:
            print(json.dumps(rows, default=str))
        else:
            print_table(rows)
        return 0
    try:
        while True:
            rows = poll_fleet(args.servers)
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(f"ps_top  {time.strftime('%H:%M:%S')}  "
                  f"({args.servers})")
            print_table(rows)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
