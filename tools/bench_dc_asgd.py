"""DC-ASGD efficacy measurement — VERDICT r4 item 3, SURVEY.md §4d.

Does the delay compensation actually help convergence, or is it only
unit-tested math? Protocol: MNIST-grating MLP, async SGD, W round-robin
workers (round-robin makes every push stale by exactly τ = W-1), fixed
total number of server applies, fixed LR — sweep τ ∈ {1, 4, 8} ×
dc_lambda ∈ {0, 0.04} and record the held-out eval-loss curve per config,
plus the τ=0 sync-SGD reference (the curve async is trying not to lose).
Results → BASELINE.md.

Run:  python tools/bench_dc_asgd.py [--applies 240] [--lr 0.1]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--applies", type=int, default=240,
                    help="total server applies per config (fair budget)")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--eval-every", type=int, default=24)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import ps_tpu as ps
    from ps_tpu.data.synthetic import mnist_batches
    from ps_tpu.models.mlp import MLP, cross_entropy_loss

    model = MLP(hidden=args.hidden)
    init_params = model.init(jax.random.key(args.seed),
                             jnp.zeros((1, 28, 28, 1)))["params"]

    # held-out eval batch: SAME task (the class prototypes are a function
    # of the seed) but a step index far beyond any config's training
    # budget, so the draws are disjoint from every training stream
    ev_stream = mnist_batches(512, seed=args.seed)
    for _ in range(300):
        next(ev_stream)
    ev_images, ev_labels = next(ev_stream)
    ev_images, ev_labels = jnp.asarray(ev_images), jnp.asarray(ev_labels)

    @jax.jit
    def eval_loss(p):
        return cross_entropy_loss(
            model.apply({"params": p}, ev_images), ev_labels
        )

    def loss_fn(p, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": p}, images), labels)

    def run_async(workers: int, lam: float):
        """Round-robin async: every push stale by workers-1."""
        ps.init(backend="tpu", mode="async", num_workers=workers,
                dc_lambda=lam)
        store = ps.KVStore(optimizer="sgd", learning_rate=args.lr,
                           mode="async")
        store.init(init_params)
        run = store.make_async_step(loss_fn)
        streams = [mnist_batches(args.batch, seed=args.seed, worker=w,
                                 num_workers=workers)
                   for w in range(workers)]
        curve = []
        applies = 0
        while applies < args.applies:
            w = applies % workers
            images, labels = next(streams[w])
            run((jnp.asarray(images), jnp.asarray(labels)), worker=w)
            applies += 1
            if applies % args.eval_every == 0:
                # params() is the side-effect-free read: pull_all would
                # record a protocol pull for worker 0, resetting its stale
                # snapshot/version and biasing the very DC correction this
                # tool measures
                curve.append(round(float(eval_loss(store.params())), 4))
        hist = dict(store._engine.staleness_hist)
        ps.shutdown()
        return curve, {str(t): n for t, n in sorted(hist.items())}

    def run_sync():
        """τ=0 reference: plain sync SGD, same apply budget, same stream."""
        ps.init(backend="tpu")
        store = ps.KVStore(optimizer="sgd", learning_rate=args.lr)
        store.init(init_params)
        run = store.make_step(loss_fn)
        stream = mnist_batches(args.batch, seed=args.seed)
        curve = []
        for step in range(args.applies):
            images, labels = next(stream)
            run(store.shard_batch((jnp.asarray(images), jnp.asarray(labels))))
            if (step + 1) % args.eval_every == 0:
                curve.append(round(float(eval_loss(store.params())), 4))
        ps.shutdown()
        return curve

    out = {"applies": args.applies, "lr": args.lr, "batch": args.batch,
           "eval_every": args.eval_every, "configs": []}
    out["sync_curve"] = run_sync()
    print(f"sync: {out['sync_curve']}", file=sys.stderr)
    for workers in (2, 5, 9):  # τ = 1, 4, 8
        for lam in (0.0, 0.04):
            curve, hist = run_async(workers, lam)
            cfg = {"tau": workers - 1, "dc_lambda": lam,
                   "curve": curve, "staleness_hist": hist}
            out["configs"].append(cfg)
            print(f"tau={workers-1} lam={lam}: {curve}", file=sys.stderr)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
