"""Measure exact per-step HLO FLOPs of the fused bench steps on the CPU
backend (where pre-compile cost analysis exists — the axon TPU plugin
returns none), at two batch sizes to separate the per-example slope from
the per-step constant. Feeds the `_FLOPS_*` fallbacks in bench.py; the
derivations are recorded in BASELINE.md.

Run:  python tools/measure_flops.py bert|widedeep|resnet
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(model: str, batch_sizes=(8, 16)) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import ps_tpu as ps

    out = {}
    for bs in batch_sizes:
        if ps.is_initialized():
            ps.shutdown()
        ps.init(backend="tpu")
        if model == "bert":
            from ps_tpu.data.synthetic import mlm_batches
            from ps_tpu.models.bert import BertConfig, BertMLM, make_mlm_loss_fn

            cfg = BertConfig(dtype=jnp.bfloat16)  # the TPU bench dtype
            m = BertMLM(cfg)
            params = m.init(jax.random.key(0), jnp.zeros((2, 128), jnp.int32),
                            jnp.ones((2, 128), jnp.int32))["params"]
            store = ps.KVStore(optimizer="lamb", learning_rate=1e-3,
                               weight_decay=0.01, placement="replicated")
            store.init(params)
            run = store.make_step(make_mlm_loss_fn(m))
            batch = next(mlm_batches(bs, 128, vocab_size=cfg.vocab_size))
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            ca = run.cost_analysis(batch)
        elif model == "widedeep":
            from ps_tpu.data.synthetic import criteo_batches
            from ps_tpu.kv.sparse import SparseEmbedding
            from ps_tpu.models.wide_deep import (
                WideDeep, WideDeepConfig, make_ids_fn, make_wide_deep_loss_fn,
            )
            from ps_tpu.train import make_composite_step

            cfg = WideDeepConfig(per_feature_vocab=100_000, embed_dim=16)
            m = WideDeep(cfg)
            b0 = next(criteo_batches(2, vocab_size=cfg.per_feature_vocab))
            rows = (2, cfg.num_sparse, cfg.embed_dim)
            params = m.init(jax.random.key(0), jnp.asarray(b0["dense"]),
                            jnp.zeros(rows), jnp.zeros(rows[:2] + (1,)))["params"]
            dense = ps.KVStore(optimizer="adam", learning_rate=1e-3,
                               placement="replicated")
            dense.init(params)
            deep = SparseEmbedding(cfg.total_rows, cfg.embed_dim,
                                   optimizer="adagrad", learning_rate=0.05)
            deep.init(jax.random.key(1), scale=0.01)
            wide = SparseEmbedding(cfg.total_rows, 1, optimizer="sgd",
                                   learning_rate=0.05)
            wide.init(jax.random.key(2), scale=0.01)
            run = make_composite_step(dense, {"deep": deep, "wide": wide},
                                      make_wide_deep_loss_fn(m),
                                      make_ids_fn(cfg))
            batch = next(criteo_batches(bs, vocab_size=cfg.per_feature_vocab))
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            ca = run.cost_analysis(batch)
        elif model == "resnet":
            # reproduces the r3 derivation behind bench.py's
            # _FLOPS_RESNET_* constants (BASELINE.md)
            from ps_tpu.data.synthetic import imagenet_batches
            from ps_tpu.models.resnet import ResNet50, make_loss_fn
            from ps_tpu.parallel.sharding import replicated

            ctx = ps.current_context()
            m = ResNet50(dtype=jnp.bfloat16)
            v = m.init(jax.random.key(0), jnp.zeros((2, 224, 224, 3)),
                       train=False)
            mstate = jax.device_put(v["batch_stats"], replicated(ctx.mesh))
            store = ps.KVStore(optimizer="momentum", learning_rate=0.1,
                               momentum=0.9, placement="replicated")
            store.init(v["params"])
            run = store.make_step(make_loss_fn(m, label_smoothing=0.1),
                                  has_aux=True)
            images, labels = next(imagenet_batches(bs))
            ca = run.cost_analysis(
                (jnp.asarray(images), jnp.asarray(labels)), mstate
            )
        else:
            raise SystemExit(f"unknown model {model}")
        out[bs] = float(ca["flops"])
        ps.shutdown()
    b1, b2 = batch_sizes
    slope = (out[b2] - out[b1]) / (b2 - b1)
    const = out[b1] - slope * b1
    return {"model": model, "flops_by_batch": out,
            "slope_per_example": slope, "const_per_step": const}


if __name__ == "__main__":
    print(json.dumps(measure(sys.argv[1] if len(sys.argv) > 1 else "bert")))
