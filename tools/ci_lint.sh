#!/usr/bin/env bash
# Static-analysis + native-sanitizer CI leg (total budget < 120 s):
#   1. pslint  — repo-aware lint of ps_tpu/ (README "Static analysis"):
#      the Python families (concurrency, wire protocol, resource
#      safety, knob drift) AND the cross-language ones (PSL5xx native
#      C++ concurrency/ownership, PSL6xx ctypes<->C ABI drift) run by
#      default; --timings prints per-family wall time so a family that
#      starts eating the budget is visible in the log, not a mystery.
#      Exit nonzero on any unsuppressed finding.
#   2. TSan    — the native van's full concurrent surface (heartbeat,
#      TCP echo, tv_send_vec, shm-ring primitives, cross-thread sever)
#      under ThreadSanitizer.
#   3. ASan+UBSan — the same driver under AddressSanitizer (leak
#      detection on) + UndefinedBehaviorSanitizer.
#
# Usage: tools/ci_lint.sh   (from the repo root; first leg of
# tools/ci_bench_smoke.sh)
set -euo pipefail
cd "$(dirname "$0")/.."

t0=$SECONDS
echo "== pslint (PSL1xx-PSL6xx) =="
timeout -k 10 60 python tools/pslint.py ps_tpu/ --timings

echo "== tsan van =="
timeout -k 10 60 bash tools/tsan_van.sh

echo "== asan+ubsan van =="
timeout -k 10 60 bash tools/asan_van.sh

echo "ci_lint: all legs clean in $((SECONDS - t0))s"
