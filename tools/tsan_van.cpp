// ThreadSanitizer / ASan+UBSan driver for the native van (SURVEY.md §6:
// "any C++ control-plane code gets TSAN/ASAN"). Exercises every public ABI
// function from multiple threads concurrently — monitor rx thread, client tx
// threads, host poll threads, goodbye-while-beating, start/stop churn, the
// vectored tv_send_vec data path, the shm-ring primitives (tv_memcpy +
// release/acquire cursors + tv_wait_u64) under a real two-thread SPSC ring
// workload mirroring ps_tpu/control/shm_lane.py, and the cross-thread
// tv_shutdown sever Channel.close() relies on — so the sanitizers can
// observe any race/UB in van.cpp's threading model.
//
// Build + run: tools/tsan_van.sh (TSan) / tools/asan_van.sh (ASan+UBSan);
// clean exit + no sanitizer report = pass. Both run in tools/ci_lint.sh.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* hb_server_start(const char* bind_addr, int port, int timeout_ms);
int hb_server_port(void* h);
int hb_server_poll(void* h, int state, uint32_t* out, int cap);
uint64_t hb_server_seq(void* h, uint32_t node_id);
void hb_server_stop(void* h);
void* hb_client_start(const char* host, int port, uint32_t node_id,
                      int interval_ms);
void hb_client_goodbye(void* h);
void hb_client_stop(void* h);
void* tv_listen(const char* bind_addr, int port, int backlog);
int tv_listener_port(void* h);
void* tv_accept(void* h, int timeout_ms);
void tv_listener_close(void* h);
void* tv_connect(const char* host, int port, int timeout_ms);
int tv_send(void* h, const void* buf, uint64_t n);
int tv_send_vec(void* h, const void** bufs, const uint64_t* lens, int n);
int tv_poll_readable(void* h, int timeout_ms);
void tv_memcpy(void* dst, const void* src, uint64_t n);
void tv_prefault(void* addr, uint64_t n, int mode);
uint64_t tv_load_u64(const void* addr);
void tv_store_u64(void* addr, uint64_t v);
int tv_wait_u64(const void* addr, uint64_t last, int timeout_us,
                int skip_spin);
int64_t tv_recv_size(void* h);
int tv_recv_into(void* h, void* buf, uint64_t n);
void tv_shutdown(void* h);
void tv_close(void* h);
void* tv_adopt_fd(int fd);
void* nl_start(void* listener, int nthreads);
int nl_poll(void* h, uint64_t* conn_ids, void** bodies, uint64_t* lens,
            int cap, int timeout_ms);
int nl_reply_vec(void* h, uint64_t conn_id, const void** bufs,
                 const uint64_t* lens, int n, int close_after, int prio);
void nl_body_free(void* h, void* body);
int nl_detach(void* h, uint64_t conn_id);
void nl_stop_accept(void* h);
void nl_shutdown_conns(void* h);
uint64_t nl_pending(void* h);
int nl_conn_count(void* h);
void nl_stats(void* h, uint64_t* out);
void nl_begin_stop(void* h);
void nl_stop(void* h);
void nl_cache_config(void* h, int kind, uint64_t max_bytes);
int nl_cache_put(void* h, const void* key, uint64_t klen, const void* buf,
                 uint64_t len, uint64_t gen);
int nl_cache_put_tagged(void* h, const void* key, uint64_t klen,
                        const void* buf, uint64_t len, uint64_t gen,
                        const uint64_t* tags, int ntags);
int nl_cache_put_cond(void* h, const void* key, uint64_t klen,
                      const void* buf, uint64_t len, uint64_t gen,
                      const uint64_t* tags, int ntags, uint64_t vfloor);
void nl_cache_invalidate(void* h, uint64_t gen);
void nl_cache_invalidate_tags(void* h, uint64_t gen, const uint64_t* tags,
                              int ntags);
void nl_cache_stats(void* h, uint64_t* out);
int nl_poll2(void* h, uint64_t* conn_ids, void** bodies, uint64_t* lens,
             uint64_t* admits, int cap, int timeout_ms);
void nl_admit_config(void* h, int kind);
int nl_admit_put(void* h, uint32_t worker, const void* nonce,
                 uint64_t nonce_len, uint64_t lo, uint64_t hi,
                 uint64_t gen);
int nl_admit_set_ack(void* h, const void* buf, uint64_t len, uint64_t gen);
int nl_admit_set_refusal(void* h, const void* buf, uint64_t len);
void nl_admit_invalidate(void* h, uint64_t gen);
void nl_admit_reset(void* h, uint64_t gen);
void nl_admit_stats(void* h, uint64_t* out);
void nl_telemetry_config(void* h, int stats_on, uint64_t slow_frame_ns);
int nl_hist_snapshot(void* h, int which, uint64_t* out);
void nl_stats_snapshot(void* h, uint64_t* out);
int nl_slow_drain(void* h, uint64_t* vals, char* tids, int cap);
void nl_hist_record(void* h, int which, uint64_t ns);
}

static void sleep_ms(int ms) {
  std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

int main() {
  void* srv = hb_server_start("127.0.0.1", 0, 300);
  if (!srv) { std::fprintf(stderr, "server start failed\n"); return 1; }
  int port = hb_server_port(srv);

  // 4 clients beating fast
  std::vector<void*> clients;
  for (uint32_t id = 1; id <= 4; ++id) {
    void* c = hb_client_start("127.0.0.1", port, id, 5);
    if (!c) { std::fprintf(stderr, "client %u start failed\n", id); return 1; }
    clients.push_back(c);
  }

  // 3 poller threads hammering every read path while beats arrive
  std::atomic<bool> stop{false};
  std::vector<std::thread> pollers;
  for (int t = 0; t < 3; ++t) {
    pollers.emplace_back([&] {
      uint32_t buf[16];
      while (!stop.load()) {
        for (int state = 0; state <= 2; ++state)
          hb_server_poll(srv, state, buf, 16);
        for (uint32_t id = 1; id <= 4; ++id) hb_server_seq(srv, id);
      }
    });
  }

  sleep_ms(100);
  // goodbye from one thread while its tx thread still beats (the
  // concurrent-sendto path), then a hard stop of another client
  hb_client_goodbye(clients[0]);
  hb_client_stop(clients[0]);
  hb_client_stop(clients[1]);  // silent death
  // poll to a generous deadline instead of one fixed sleep past the
  // horizon: sanitizer overhead + sandboxed kernels stretch the beat
  // timeline, and a CI leg must not flake on scheduler jitter — the
  // states still move UNDER the concurrent poller threads either way
  uint32_t buf[16];
  int alive = 0, dead = 0, left = 0;
  for (int tries = 0; tries < 100; ++tries) {
    sleep_ms(50);
    alive = hb_server_poll(srv, 0, buf, 16);
    dead = hb_server_poll(srv, 1, buf, 16);
    left = hb_server_poll(srv, 2, buf, 16);
    if (alive == 2 && dead == 1 && left == 1) break;
  }
  stop.store(true);
  for (auto& t : pollers) t.join();
  hb_client_stop(clients[2]);
  hb_client_stop(clients[3]);
  hb_server_stop(srv);
  std::printf("alive=%d dead=%d left=%d\n", alive, dead, left);
  if (alive != 2 || dead != 1 || left != 1) {
    std::fprintf(stderr, "unexpected states\n");
    return 1;
  }
  // --- tensor van: a server echoing frames to 3 concurrent client threads
  void* lst = tv_listen("127.0.0.1", 0, 8);
  if (!lst) { std::fprintf(stderr, "tv_listen failed\n"); return 1; }
  int tport = tv_listener_port(lst);
  std::atomic<int> echoed{0};
  std::thread server([&] {
    std::vector<std::thread> handlers;
    for (int i = 0; i < 3; ++i) {
      void* conn = tv_accept(lst, 2000);
      if (!conn) break;
      handlers.emplace_back([conn, &echoed] {
        for (;;) {
          int64_t n = tv_recv_size(conn);
          if (n < 0) break;
          std::vector<char> buf(n);
          if (!tv_recv_into(conn, buf.data(), n)) break;
          if (!tv_send(conn, buf.data(), n)) break;
          echoed.fetch_add(1);
        }
        tv_close(conn);
      });
    }
    for (auto& h : handlers) h.join();
  });
  std::vector<std::thread> tx;
  std::atomic<int> ok_frames{0};
  for (int t = 0; t < 3; ++t) {
    tx.emplace_back([&, t] {
      void* c = tv_connect("127.0.0.1", tport, 2000);
      if (!c) return;
      std::vector<char> payload(1 << 16, (char)t);
      for (int i = 0; i < 20; ++i) {
        if (!tv_send(c, payload.data(), payload.size())) break;
        int64_t n = tv_recv_size(c);
        if (n != (int64_t)payload.size()) break;
        std::vector<char> back(n);
        if (!tv_recv_into(c, back.data(), n)) break;
        ok_frames.fetch_add(back == payload ? 1 : 0);
      }
      tv_close(c);
    });
  }
  for (auto& t : tx) t.join();
  server.join();
  tv_listener_close(lst);
  std::printf("tv echoed=%d ok=%d\n", echoed.load(), ok_frames.load());
  if (ok_frames.load() != 60) {
    std::fprintf(stderr, "tensor van frames lost/corrupted\n");
    return 1;
  }

  // --- vectored sends: tv_send_vec from 3 client threads, each frame
  // gathered from several live chunks (the zero-copy writev path), echoed
  // back whole by the same recv_size/recv_into framing
  void* vlst = tv_listen("127.0.0.1", 0, 8);
  if (!vlst) { std::fprintf(stderr, "tv_listen (vec) failed\n"); return 1; }
  int vport = tv_listener_port(vlst);
  std::thread vserver([&] {
    std::vector<std::thread> handlers;
    for (int i = 0; i < 3; ++i) {
      void* conn = tv_accept(vlst, 2000);
      if (!conn) break;
      handlers.emplace_back([conn] {
        for (;;) {
          int64_t n = tv_recv_size(conn);
          if (n < 0) break;
          std::vector<char> buf(n);
          if (!tv_recv_into(conn, buf.data(), n)) break;
          if (!tv_send(conn, buf.data(), n)) break;
        }
        tv_close(conn);
      });
    }
    for (auto& h : handlers) h.join();
  });
  std::atomic<int> vec_ok{0};
  std::vector<std::thread> vtx;
  for (int t = 0; t < 3; ++t) {
    vtx.emplace_back([&, t] {
      void* c = tv_connect("127.0.0.1", vport, 2000);
      if (!c) return;
      // chunks of uneven sizes, including an empty one (iovec is skipped)
      std::vector<char> a(7 + t, (char)('a' + t));
      std::vector<char> b(1 << 14, (char)('A' + t));
      std::vector<char> d(333, (char)t);
      for (int i = 0; i < 12; ++i) {
        const void* bufs[4] = {a.data(), b.data(), nullptr, d.data()};
        uint64_t lens[4] = {a.size(), b.size(), 0, d.size()};
        if (!tv_send_vec(c, bufs, lens, 4)) break;
        uint64_t total = a.size() + b.size() + d.size();
        int64_t n = tv_recv_size(c);
        if (n != (int64_t)total) break;
        std::vector<char> back(n);
        if (!tv_recv_into(c, back.data(), n)) break;
        bool match = std::memcmp(back.data(), a.data(), a.size()) == 0 &&
                     std::memcmp(back.data() + a.size(), b.data(),
                                 b.size()) == 0 &&
                     std::memcmp(back.data() + a.size() + b.size(),
                                 d.data(), d.size()) == 0;
        vec_ok.fetch_add(match ? 1 : 0);
      }
      tv_close(c);
    });
  }
  for (auto& t : vtx) t.join();
  vserver.join();
  tv_listener_close(vlst);
  std::printf("tv_send_vec ok=%d\n", vec_ok.load());
  if (vec_ok.load() != 36) {
    std::fprintf(stderr, "vectored frames lost/corrupted\n");
    return 1;
  }

  // --- shm-ring primitives: one SPSC byte ring (the shm_lane.py layout:
  // [0:8) tail, [8:16) head, data after a 64-byte header; frames are
  // [u64 len][bytes] and never wrap — a wrap sentinel restarts at 0),
  // producer and consumer on separate threads moving bytes through
  // tv_memcpy with cursors published/read through the release/acquire
  // atomics and blocking through tv_wait_u64. TSAN validates that the
  // cursor ordering contract alone makes the payload bytes safe.
  {
    constexpr uint64_t kCap = 1 << 16;
    constexpr uint64_t kWrap = ~0ull;
    constexpr int kFrames = 4000;
    std::vector<unsigned char> seg(64 + kCap);
    unsigned char* base = seg.data();
    tv_prefault(base, seg.size(), 1);  // creator zero-fill
    tv_prefault(base, seg.size(), 2);  // attacher rewrite
    tv_prefault(base, seg.size(), 0);  // read-touch
    unsigned char* data = base + 64;
    void* tail_addr = base + 0;
    void* head_addr = base + 8;
    std::atomic<uint64_t> produced_sum{0};
    std::thread producer([&] {
      uint64_t tail = 0;
      std::vector<unsigned char> payload(4096);
      for (int i = 0; i < kFrames; ++i) {
        uint64_t n = (uint64_t)((i % 37) * 73 + 9);
        for (uint64_t j = 0; j < n; ++j)
          payload[j] = (unsigned char)((i + j) & 0xff);
        uint64_t need = 8 + n;
        for (;;) {
          uint64_t pos = tail % kCap;
          uint64_t contig = kCap - pos;
          uint64_t skip = contig < need ? contig : 0;
          uint64_t head = tv_load_u64(head_addr);
          if (kCap - (tail - head) >= skip + need) {
            if (skip) {
              if (contig >= 8) std::memcpy(data + pos, &kWrap, 8);
              tail += skip;
              pos = 0;
            }
            std::memcpy(data + pos, &n, 8);
            tv_memcpy(data + pos + 8, payload.data(), n);
            tail += need;
            tv_store_u64(tail_addr, tail);
            break;
          }
          tv_wait_u64(head_addr, head, 1000, i % 2);
        }
        uint64_t s = 0;
        for (uint64_t j = 0; j < n; ++j) s += payload[j];
        produced_sum.fetch_add(s);
      }
    });
    uint64_t consumed_sum = 0;
    int got = 0;
    uint64_t head = 0;
    std::vector<unsigned char> out(4096);
    while (got < kFrames) {
      uint64_t tail = tv_load_u64(tail_addr);
      if (head == tail) {
        tv_wait_u64(tail_addr, tail, 1000, got % 2);
        continue;
      }
      uint64_t pos = head % kCap;
      uint64_t contig = kCap - pos;
      if (contig < 8) {
        head += contig;
        tv_store_u64(head_addr, head);
        continue;
      }
      uint64_t n;
      std::memcpy(&n, data + pos, 8);
      if (n == kWrap) {
        head += contig;
        tv_store_u64(head_addr, head);
        continue;
      }
      tv_memcpy(out.data(), data + pos + 8, n);
      for (uint64_t j = 0; j < n; ++j) consumed_sum += out[j];
      head += 8 + n;
      tv_store_u64(head_addr, head);
      ++got;
    }
    producer.join();
    std::printf("ring frames=%d sum=%llu\n", got,
                (unsigned long long)consumed_sum);
    if (consumed_sum != produced_sum.load()) {
      std::fprintf(stderr, "ring payload corrupted across threads\n");
      return 1;
    }
  }

  // --- cross-thread sever: a reader blocked in tv_recv_size is woken by
  // tv_shutdown from another thread (Channel.close()'s contract), then
  // the fd is freed by the reader's own tv_close; tv_poll_readable sees
  // the EOF as "readable"
  {
    void* slst = tv_listen("127.0.0.1", 0, 4);
    if (!slst) { std::fprintf(stderr, "tv_listen (sever) failed\n"); return 1; }
    int sport = tv_listener_port(slst);
    void* cli = tv_connect("127.0.0.1", sport, 2000);
    void* srvconn = tv_accept(slst, 2000);
    if (!cli || !srvconn) {
      std::fprintf(stderr, "sever setup failed\n");
      return 1;
    }
    if (tv_poll_readable(cli, 0) != 0) {
      std::fprintf(stderr, "poll_readable: idle socket reported readable\n");
      return 1;
    }
    std::atomic<int> woke{0};
    std::thread reader([&] {
      int64_t n = tv_recv_size(cli);  // blocks until the sever
      woke.store(n < 0 ? 1 : 2);
    });
    sleep_ms(50);
    tv_shutdown(cli);  // cross-thread, non-freeing: reader wakes with EOF
    reader.join();
    // the free happens only after every other user is provably out of
    // the handle — the deferred-close contract Channel._hlock enforces
    // in Python (shutdown may race reads; tv_close may not race anything)
    tv_close(cli);
    if (woke.load() != 1) {
      std::fprintf(stderr, "severed reader did not wake with EOF\n");
      return 1;
    }
    if (tv_poll_readable(srvconn, 100) != 1) {
      std::fprintf(stderr, "peer death not visible as readable/EOF\n");
      return 1;
    }
    tv_close(srvconn);
    tv_listener_close(slst);
    std::printf("cross-thread sever: OK\n");
  }

  // --- native epoll event loop (nl_*): a 2-thread loop + echo pump under
  // churning clients — concurrent connect/close racing replies, a multi-MB
  // frame whose echo outgrows the socket buffer (stage-while-writev: the
  // pump's nl_reply_vec stages the tail while the loop thread flushes it
  // on EPOLLOUT), the introspection calls hammered from a third thread,
  // the detach handoff (SHM_SETUP's path), and begin_stop/stop while
  // connections are live. Then start/stop churn on fresh loops.
  {
    void* nlst = tv_listen("127.0.0.1", 0, 64);
    if (!nlst) { std::fprintf(stderr, "nl listen failed\n"); return 1; }
    void* loop = nl_start(nlst, 2);
    if (!loop) { std::fprintf(stderr, "nl_start failed\n"); return 1; }
    int nport = tv_listener_port(nlst);
    std::atomic<bool> nstop{false};
    std::atomic<bool> detach_mode{false};
    std::atomic<int> served{0}, detached{0};
    std::thread statst([&] {  // concurrent introspection reads
      uint64_t out[6];
      while (!nstop.load()) {
        nl_stats(loop, out);
        nl_pending(loop);
        nl_conn_count(loop);
        sleep_ms(1);
      }
    });
    std::thread pump([&] {  // the Python pump's shape: poll/reply/free
      uint64_t ids[16];
      void* bodies[16];
      uint64_t lens[16];
      while (true) {
        int n = nl_poll(loop, ids, bodies, lens, 16, 50);
        if (n < 0) break;
        for (int i = 0; i < n; ++i) {
          if (detach_mode.load()) {
            int fd = nl_detach(loop, ids[i]);
            if (fd >= 0) {
              void* conn = tv_adopt_fd(fd);
              tv_send(conn, bodies[i], lens[i]);
              tv_close(conn);
              detached.fetch_add(1);
            }
            nl_body_free(loop, bodies[i]);
            continue;
          }
          const void* bufs[1] = {bodies[i]};  // reply ALIASES the request
          uint64_t ls[1] = {lens[i]};
          // alternate priorities so the driver exercises the priority
          // writev drain's sort under TSan, not just the default path
          nl_reply_vec(loop, ids[i], bufs, ls, 1, 0, (int)(i % 3));
          nl_body_free(loop, bodies[i]);
          served.fetch_add(1);
        }
      }
    });
    std::vector<std::thread> ncls;
    std::atomic<int> ok{0};
    for (int c = 0; c < 6; ++c) {
      ncls.emplace_back([&, c] {
        for (int r = 0; r < 5; ++r) {
          void* ch = tv_connect("127.0.0.1", nport, 2000);
          if (!ch) continue;
          uint64_t sz = (c == 0 && r == 0) ? (3u << 20) : 4096;
          std::vector<char> payload(sz, (char)(c + 1));
          if (tv_send(ch, payload.data(), payload.size())) {
            if (c % 3 == 2 && r % 2 == 1) {
              tv_close(ch);  // abrupt close: the echo races the sever
              continue;
            }
            int64_t n = tv_recv_size(ch);
            if (n == (int64_t)payload.size()) {
              std::vector<char> back(n);
              if (tv_recv_into(ch, back.data(), n) && back == payload)
                ok.fetch_add(1);
            }
          }
          tv_close(ch);
        }
      });
    }
    for (auto& t : ncls) t.join();
    if (ok.load() < 20) {
      std::fprintf(stderr, "nl echo: only %d/26 round trips\n", ok.load());
      return 1;
    }
    // detach handoff: the pump pulls the next conn out of the loop and
    // answers over a blocking adopted Conn (how SHM_SETUP leaves the loop)
    detach_mode.store(true);
    {
      void* ch = tv_connect("127.0.0.1", nport, 2000);
      char ping[32] = {7};
      if (!ch || !tv_send(ch, ping, sizeof(ping))) {
        std::fprintf(stderr, "nl detach client failed\n");
        return 1;
      }
      int64_t n = tv_recv_size(ch);
      std::vector<char> back(n > 0 ? n : 0);
      if (n != sizeof(ping) || !tv_recv_into(ch, back.data(), n)) {
        std::fprintf(stderr, "nl detach echo failed (n=%lld)\n",
                     (long long)n);
        return 1;
      }
      tv_close(ch);
    }
    // live-connection sever + shutdown while a client is mid-dial
    void* lingering = tv_connect("127.0.0.1", nport, 2000);
    nl_stop_accept(loop);
    nl_shutdown_conns(loop);
    nl_begin_stop(loop);
    pump.join();
    nstop.store(true);
    statst.join();
    nl_stop(loop);
    if (lingering) tv_close(lingering);
    tv_listener_close(nlst);
    if (detached.load() != 1) {
      std::fprintf(stderr, "nl detach count %d\n", detached.load());
      return 1;
    }
    std::printf("nl echo/detach/sever: OK (%d served)\n", served.load());
    // start/stop churn: fresh loop per round, one touch-and-go client
    for (int i = 0; i < 3; ++i) {
      void* lst2 = tv_listen("127.0.0.1", 0, 8);
      void* lp = nl_start(lst2, 1);
      if (!lp) { std::fprintf(stderr, "nl churn start failed\n"); return 1; }
      void* ch = tv_connect("127.0.0.1", tv_listener_port(lst2), 2000);
      if (ch) tv_close(ch);
      nl_stop(lp);
      tv_listener_close(lst2);
    }
    std::printf("nl start/stop churn: OK\n");
  }

  // --- native read cache (nl_cache_*): publish-while-serve churn — the
  // read path's three concurrent parties all live at once: loop threads
  // answering cache hits (nl_cache_serve under cachemu then wmu), the
  // pump publishing replies on misses (nl_cache_put / nl_cache_put_tagged
  // — TAGGED on alternate keys, exercising the per-key entry metadata),
  // and an "applier" thread bumping the invalidation floor on a tight
  // cadence (alternating full nl_cache_invalidate with per-key
  // nl_cache_invalidate_tags — the invalidation-on-apply race, both
  // flavors), while a stats thread hammers nl_cache_stats PLUS the whole
  // in-loop telemetry surface (nl_stats_snapshot, every nl_hist_snapshot,
  // nl_slow_drain) with the slow-frame watchdog armed at 1 ns so EVERY
  // served frame also contends the slow ring. Clients verify every reply
  // — hit or miss — echoes their request bytes exactly.
  {
    void* clst = tv_listen("127.0.0.1", 0, 64);
    if (!clst) { std::fprintf(stderr, "cache listen failed\n"); return 1; }
    void* loop = nl_start(clst, 2);
    if (!loop) { std::fprintf(stderr, "cache nl_start failed\n"); return 1; }
    const char kCacheKind = 0x42;
    nl_cache_config(loop, kCacheKind, 1u << 20);
    nl_telemetry_config(loop, 1, 1);  // stats on; everything is "slow"
    int cport = tv_listener_port(clst);
    std::atomic<bool> cstop{false};
    std::atomic<uint64_t> genctr{0};
    std::atomic<int> cserved{0};
    std::thread applier([&] {  // invalidation-on-apply churn, both flavors
      uint64_t round = 0;
      while (!cstop.load()) {
        uint64_t g = genctr.fetch_add(1) + 1;
        if (++round % 2 == 0) {
          nl_cache_invalidate(loop, g);
        } else {
          // tag 0 matches half the tagged entries; untagged entries
          // drop too (the conservative contract under TSan churn)
          uint64_t tags[2] = {0, round};
          nl_cache_invalidate_tags(loop, g, tags, 2);
        }
        sleep_ms(1);
      }
    });
    std::thread cstats([&] {  // stats-while-serve: the whole read surface
      uint64_t out[9];
      uint64_t hist[4 + 160];
      uint64_t svals[7 * 8];
      char stids[2 * 20 * 8];
      while (!cstop.load()) {
        nl_cache_stats(loop, out);
        nl_stats_snapshot(loop, out);
        for (int w = 0; w < 4; ++w) nl_hist_snapshot(loop, w, hist);
        nl_slow_drain(loop, svals, stids, 8);
        sleep_ms(1);
      }
    });
    std::thread cpump([&] {  // echo + publish-on-miss (the pump's shape)
      uint64_t ids[16];
      void* bodies[16];
      uint64_t lens[16];
      while (true) {
        int n = nl_poll(loop, ids, bodies, lens, 16, 50);
        if (n < 0) break;
        for (int i = 0; i < n; ++i) {
          const void* bufs[1] = {bodies[i]};
          uint64_t ls[1] = {lens[i]};
          uint64_t g = genctr.load();
          nl_reply_vec(loop, ids[i], bufs, ls, 1, 0, 0);
          if (lens[i] >= 1 && ((char*)bodies[i])[0] == kCacheKind) {
            // publish the echo under the request's own bytes — some of
            // these race the applier and are refused at the floor;
            // alternate tagged and untagged entries by the key selector
            char sel = lens[i] >= 2 ? ((char*)bodies[i])[1] : 0;
            if (sel % 2 == 0) {
              uint64_t tags[1] = {(uint64_t)sel};
              nl_cache_put_tagged(loop, bodies[i], lens[i], bodies[i],
                                  lens[i], g, tags, 1);
            } else {
              nl_cache_put(loop, bodies[i], lens[i], bodies[i], lens[i],
                           g);
            }
          }
          nl_body_free(loop, bodies[i]);
          cserved.fetch_add(1);
        }
      }
    });
    std::vector<std::thread> ccls;
    std::atomic<int> cok{0};
    for (int c = 0; c < 4; ++c) {
      ccls.emplace_back([&, c] {
        void* ch = tv_connect("127.0.0.1", cport, 2000);
        if (!ch) return;
        for (int r = 0; r < 120; ++r) {
          // two hot cacheable keys shared ACROSS clients (hits), plus
          // every 7th request non-cacheable (always takes the pump)
          std::vector<char> req(64, (char)((r % 7 == 6) ? 0x11
                                           : kCacheKind));
          req[1] = (char)(r % 2);  // key selector
          if (!tv_send(ch, req.data(), req.size())) break;
          int64_t n = tv_recv_size(ch);
          if (n != (int64_t)req.size()) break;
          std::vector<char> back(n);
          if (!tv_recv_into(ch, back.data(), n) || back != req) break;
          cok.fetch_add(1);
        }
        tv_close(ch);
      });
    }
    for (auto& t : ccls) t.join();
    // an entry alone over the budget must be refused, not crash
    std::vector<char> big((1u << 20) + 64, kCacheKind);
    if (nl_cache_put(loop, big.data(), 64, big.data(), big.size(),
                     genctr.load() + 1) != 0) {
      std::fprintf(stderr, "oversize cache_put accepted\n");
      return 1;
    }
    cstop.store(true);
    applier.join();
    cstats.join();
    nl_stop_accept(loop);
    nl_shutdown_conns(loop);
    nl_begin_stop(loop);
    cpump.join();
    uint64_t cs[9];
    nl_cache_stats(loop, cs);
    // in-loop telemetry landed: read latency + read-hit serve histograms
    // counted, and the 1 ns watchdog filled the slow ring (drain sanity:
    // every entry names a conn and a stage time)
    uint64_t hist[4 + 160];
    int nb = nl_hist_snapshot(loop, 0, hist);
    uint64_t frames_counted = hist[0];
    if (nb <= 0 || nl_hist_snapshot(loop, 2, hist) != nb) {
      std::fprintf(stderr, "nl_hist_snapshot bucket counts drifted\n");
      return 1;
    }
    uint64_t hits_counted = hist[0];
    uint64_t nlst[8];
    nl_stats_snapshot(loop, nlst);
    uint64_t svals[7 * 8];
    char stids[2 * 20 * 8];
    int drained = nl_slow_drain(loop, svals, stids, 8);
    for (int i = 0; i < drained; ++i) {
      if (svals[i * 7 + 0] == 0) {
        std::fprintf(stderr, "slow-frame entry names no conn\n");
        return 1;
      }
    }
    nl_stop(loop);
    tv_listener_close(clst);
    if (cok.load() < 400) {
      std::fprintf(stderr, "cache echo: only %d/480 round trips\n",
                   cok.load());
      return 1;
    }
    if (cs[0] == 0 || cs[2] == 0 || cs[4] == 0) {
      std::fprintf(stderr,
                   "cache churn never exercised hits/puts/invals: "
                   "h=%llu p=%llu i=%llu\n", (unsigned long long)cs[0],
                   (unsigned long long)cs[2], (unsigned long long)cs[4]);
      return 1;
    }
    if (frames_counted == 0 || hits_counted == 0 || nlst[3] == 0) {
      std::fprintf(stderr,
                   "in-loop telemetry never counted under churn: "
                   "frames=%llu hits=%llu slow=%llu\n",
                   (unsigned long long)frames_counted,
                   (unsigned long long)hits_counted,
                   (unsigned long long)nlst[3]);
      return 1;
    }
    std::printf("nl read-cache churn: OK (%d ok, %llu hits, %llu puts, "
                "%llu invals, %llu rejects; telemetry frames=%llu "
                "hit-samples=%llu slow=%llu drained=%d)\n", cok.load(),
                (unsigned long long)cs[0], (unsigned long long)cs[2],
                (unsigned long long)cs[4], (unsigned long long)cs[3],
                (unsigned long long)frames_counted,
                (unsigned long long)hits_counted,
                (unsigned long long)nlst[3], drained);
  }

  // --- conditional serving (nl_cache_put_cond + the version-floor
  // lookup): revalidation churn — reader threads hammer conditional
  // requests whose "cond" version climbs, the pump answers every miss
  // with the spliced NOT_MODIFIED-shaped reply and publishes it under a
  // version floor, while a "pusher" thread bumps the version and the
  // invalidation floor on a tight cadence (an apply IS an invalidation).
  // Every reply — version-floor hit or pump miss — must be byte-identical
  // to the splice of the reader's own request, whatever cond digits it
  // carried: the by-construction parity contract of NOT_MODIFIED serving.
  {
    void* clst = tv_listen("127.0.0.1", 0, 64);
    if (!clst) { std::fprintf(stderr, "cond listen failed\n"); return 1; }
    void* loop = nl_start(clst, 2);
    if (!loop) { std::fprintf(stderr, "cond nl_start failed\n"); return 1; }
    const char kCacheKind = 0x42;
    nl_cache_config(loop, kCacheKind, 1u << 20);
    int cport = tv_listener_port(clst);
    std::atomic<bool> cstop{false};
    std::atomic<uint64_t> version{1};
    std::atomic<uint64_t> genctr{0};
    // request layout (the wire frame's shape): kind byte, 4-byte worker,
    // 8-byte meta length, then meta {"k":K,"cond":DDDDDDDD} — fixed
    // width so the digit run sits at body offsets [27, 35)
    auto mkreq = [&](char kind, char key, uint64_t v) {
      std::vector<char> b(36, 0);
      b[0] = kind;
      uint64_t mlen = 23;
      std::memcpy(b.data() + 5, &mlen, 8);
      char meta[24];
      std::snprintf(meta, sizeof(meta), "{\"k\":%c,\"cond\":%08llu}",
                    key, (unsigned long long)(v % 100000000ull));
      std::memcpy(b.data() + 13, meta, 23);
      return b;
    };
    auto splice = [](const std::vector<char>& b) {  // drop the digits
      std::vector<char> out(b.begin(), b.begin() + 27);
      out.insert(out.end(), b.begin() + 35, b.end());
      return out;
    };
    std::thread pusher([&] {  // version bump + floor bump, push cadence
      while (!cstop.load()) {
        version.fetch_add(1);
        nl_cache_invalidate(loop, genctr.fetch_add(1) + 1);
        sleep_ms(1);
      }
    });
    std::thread condstats([&] {  // widened stats surface under churn
      uint64_t out[9];
      while (!cstop.load()) {
        nl_cache_stats(loop, out);
        sleep_ms(1);
      }
    });
    std::thread cpump([&] {  // miss path: spliced reply, cond publish
      uint64_t ids[16];
      void* bodies[16];
      uint64_t lens[16];
      while (true) {
        int n = nl_poll(loop, ids, bodies, lens, 16, 50);
        if (n < 0) break;
        for (int i = 0; i < n; ++i) {
          std::vector<char> body((char*)bodies[i],
                                 (char*)bodies[i] + lens[i]);
          uint64_t g = genctr.load();
          uint64_t vf = version.load();
          if (body.size() == 36 && body[0] == kCacheKind) {
            std::vector<char> rep = splice(body);
            const void* bufs[1] = {rep.data()};
            uint64_t ls[1] = {rep.size()};
            nl_reply_vec(loop, ids[i], bufs, ls, 1, 0, 0);
            // the reply is valid for ANY cond >= the version it was
            // computed at: publish under that floor (some of these
            // race the pusher and are refused at the gen floor)
            nl_cache_put_cond(loop, body.data(), body.size(), rep.data(),
                              rep.size(), g, nullptr, 0, vf);
          } else {  // non-cacheable: plain echo
            const void* bufs[1] = {body.data()};
            uint64_t ls[1] = {body.size()};
            nl_reply_vec(loop, ids[i], bufs, ls, 1, 0, 0);
          }
          nl_body_free(loop, bodies[i]);
        }
      }
    });
    std::vector<std::thread> ccls;
    std::atomic<int> cok{0};
    for (int c = 0; c < 4; ++c) {
      ccls.emplace_back([&, c] {
        void* ch = tv_connect("127.0.0.1", cport, 2000);
        if (!ch) return;
        for (int r = 0; r < 120; ++r) {
          // revalidate at or past the live version (hits whenever an
          // entry survives the pusher's floor), two hot keys across
          // clients, every 7th request non-cacheable
          bool cold = (r % 7 == 6);
          std::vector<char> req =
              mkreq(cold ? (char)0x11 : kCacheKind, (char)('0' + r % 2),
                    version.load() + 1);
          std::vector<char> want = cold ? req : splice(req);
          if (!tv_send(ch, req.data(), req.size())) break;
          int64_t n = tv_recv_size(ch);
          if (n != (int64_t)want.size()) break;
          std::vector<char> back(n);
          if (!tv_recv_into(ch, back.data(), n) || back != want) break;
          cok.fetch_add(1);
        }
        tv_close(ch);
      });
    }
    for (auto& t : ccls) t.join();
    cstop.store(true);
    pusher.join();
    condstats.join();
    // deterministic tail (no pusher racing): a publish at a known floor
    // must serve BOTH the exact cond it was built from and any higher
    // one (the splice), and refuse a lower one back to the pump
    uint64_t vf = version.load();
    uint64_t g = genctr.load();
    std::vector<char> base = mkreq(kCacheKind, '9', vf);
    std::vector<char> rep = splice(base);
    if (nl_cache_put_cond(loop, base.data(), base.size(), rep.data(),
                          rep.size(), g, nullptr, 0, vf) != 1) {
      std::fprintf(stderr, "cond publish refused at a live floor\n");
      return 1;
    }
    uint64_t cs0[9], cs1[9];
    nl_cache_stats(loop, cs0);
    void* ch = tv_connect("127.0.0.1", cport, 2000);
    if (!ch) { std::fprintf(stderr, "cond tail connect failed\n"); return 1; }
    for (uint64_t dv : {0ull, 3ull}) {  // exact floor, then above it
      std::vector<char> req = mkreq(kCacheKind, '9', vf + dv);
      std::vector<char> want = splice(req);
      if (!tv_send(ch, req.data(), req.size())) return 1;
      int64_t n = tv_recv_size(ch);
      std::vector<char> back(n > 0 ? n : 0);
      if (n != (int64_t)want.size() ||
          !tv_recv_into(ch, back.data(), n) || back != want) {
        std::fprintf(stderr, "cond tail parity broke at +%llu\n",
                     (unsigned long long)dv);
        return 1;
      }
    }
    tv_close(ch);
    nl_cache_stats(loop, cs1);
    if (cs1[8] < cs0[8] + 2) {
      std::fprintf(stderr, "cond tail not served from the version floor: "
                   "cond_hits %llu -> %llu\n", (unsigned long long)cs0[8],
                   (unsigned long long)cs1[8]);
      return 1;
    }
    if (cs1[0] < cs1[8]) {
      std::fprintf(stderr, "cond hits not a subset of hits\n");
      return 1;
    }
    nl_stop_accept(loop);
    nl_shutdown_conns(loop);
    nl_begin_stop(loop);
    cpump.join();
    nl_stop(loop);
    tv_listener_close(clst);
    if (cok.load() < 400) {
      std::fprintf(stderr, "cond churn: only %d/480 round trips\n",
                   cok.load());
      return 1;
    }
    std::printf("nl conditional-serve churn: OK (%d ok, %llu hits of "
                "which %llu cond, %llu puts, %llu invals)\n", cok.load(),
                (unsigned long long)cs1[0], (unsigned long long)cs1[8],
                (unsigned long long)cs1[2], (unsigned long long)cs1[4]);
  }

  // --- native push admission (nl_admit_*): admission churn — loop
  // threads classifying concurrent replays + fresh pushes (nl_poll2
  // stamping), a promoter thread re-seeding the ledger wholesale
  // (nl_admit_reset + republish + re-arm, with refusal-armed windows —
  // the backup/fenced phases), an applier raising the invalidation
  // floor and re-arming the ack template on a tight cadence (the
  // per-apply publish shape), and a stats thread hammering
  // nl_admit_stats — while clients verify every reply is either the
  // pump echo (punt/fresh) or an armed template with THEIR worker id
  // patched in.
  {
    void* alst = tv_listen("127.0.0.1", 0, 64);
    if (!alst) { std::fprintf(stderr, "admit listen failed\n"); return 1; }
    void* loop = nl_start(alst, 2);
    if (!loop) { std::fprintf(stderr, "admit nl_start failed\n"); return 1; }
    const uint8_t kPushKind = 0x02;
    nl_admit_config(loop, kPushKind);
    int aport = tv_listener_port(alst);

    // push frame: [kind u8][worker u32 le][meta_len u64 le][meta json];
    // the dedup token rides the meta TAIL, exactly where the encoder
    // puts `extra` (the last top-level key)
    auto mkpush = [&](uint32_t w, uint64_t seq, const char* nonce,
                      bool tokened) {
      char meta[160];
      int mlen = tokened
          ? std::snprintf(meta, sizeof(meta),
                          "{\"tensors\": [], \"extra\": {\"pseq\": %llu, "
                          "\"pnonce\": \"%s\"}}",
                          (unsigned long long)seq, nonce)
          : std::snprintf(meta, sizeof(meta),
                          "{\"tensors\": [], \"extra\": {}}");
      std::vector<char> f(13 + (size_t)mlen);
      f[0] = (char)kPushKind;
      std::memcpy(f.data() + 1, &w, 4);
      uint64_t ml = (uint64_t)mlen;
      std::memcpy(f.data() + 5, &ml, 8);
      std::memcpy(f.data() + 13, meta, (size_t)mlen);
      return f;
    };
    // reply templates (worker 0; the loop patches bytes 1..5 per serve)
    auto mktmpl = [&](uint8_t kind) {
      const char* meta = "{\"tensors\": [], \"extra\": {\"dedup\": true}}";
      uint64_t ml = std::strlen(meta);
      std::vector<char> f(13 + (size_t)ml);
      f[0] = (char)kind;
      uint32_t w0 = 0;
      std::memcpy(f.data() + 1, &w0, 4);
      std::memcpy(f.data() + 5, &ml, 8);
      std::memcpy(f.data() + 13, meta, (size_t)ml);
      return f;
    };
    std::vector<char> acktmpl = mktmpl(0x06);
    std::vector<char> reftmpl = mktmpl(0x07);

    std::atomic<bool> astop{false};
    std::atomic<uint64_t> agen{1};
    // seed: 4 workers settled at (nonce "n0", lo=hi=5), ack armed
    for (uint32_t w = 0; w < 4; ++w)
      nl_admit_put(loop, w, "n0", 2, 5, 5, 1);
    nl_admit_set_ack(loop, acktmpl.data(), acktmpl.size(), 1);

    std::thread promoter([&] {  // structural reseed churn + role flips
      int round = 0;
      while (!astop.load()) {
        uint64_t g = agen.fetch_add(1) + 1;
        nl_admit_reset(loop, g);
        if (++round % 4 == 0) {
          // a backup/fenced window: every admissible frame refused
          nl_admit_set_refusal(loop, reftmpl.data(), reftmpl.size());
          sleep_ms(1);
          uint64_t g2 = agen.fetch_add(1) + 1;
          nl_admit_reset(loop, g2);  // promotion clears the refusal
          g = g2;
        }
        for (uint32_t w = 0; w < 4; ++w)
          nl_admit_put(loop, w, "n0", 2, 5, 5, g);
        nl_admit_set_ack(loop, acktmpl.data(), acktmpl.size(), g);
        sleep_ms(2);
      }
    });
    std::thread applier([&] {  // invalidation-on-apply + republish
      while (!astop.load()) {
        uint64_t g = agen.fetch_add(1) + 1;
        nl_admit_invalidate(loop, g);
        nl_admit_put(loop, 0, "n0", 2, 5, 5, g);
        nl_admit_set_ack(loop, acktmpl.data(), acktmpl.size(), g);
        sleep_ms(1);
      }
    });
    std::thread astats([&] {
      uint64_t out[8];
      while (!astop.load()) {
        nl_admit_stats(loop, out);
        sleep_ms(1);
      }
    });
    std::atomic<uint64_t> stamped{0};
    std::thread apump([&] {  // echo everything the admission tier punts
      uint64_t ids[16];
      void* bodies[16];
      uint64_t lens[16];
      uint64_t admits[16];
      while (true) {
        int n = nl_poll2(loop, ids, bodies, lens, admits, 16, 50);
        if (n < 0) break;
        for (int i = 0; i < n; ++i) {
          if (admits[i] != 0) stamped.fetch_add(1);
          const void* bufs[1] = {bodies[i]};
          uint64_t ls[1] = {lens[i]};
          nl_reply_vec(loop, ids[i], bufs, ls, 1, 0, 0);
          nl_body_free(loop, bodies[i]);
        }
      }
    });
    std::vector<std::thread> acls;
    std::atomic<int> aok{0};
    std::atomic<int> anative{0};
    for (uint32_t w = 0; w < 4; ++w) {
      acls.emplace_back([&, w] {
        void* ch = tv_connect("127.0.0.1", aport, 2000);
        if (!ch) return;
        uint64_t fresh_seq = 10;
        for (int r = 0; r < 150; ++r) {
          // mix: pure replays (seq 3 <= lo), strictly-fresh seqs,
          // tokenless pushes (always punt), and an unknown nonce
          std::vector<char> req =
              r % 5 == 0 ? mkpush(w, ++fresh_seq, "n0", true)
              : r % 7 == 0 ? mkpush(w, 3, "zz", true)
              : r % 11 == 0 ? mkpush(w, 3, "n0", false)
              : mkpush(w, 3, "n0", true);
          if (!tv_send(ch, req.data(), req.size())) break;
          int64_t n = tv_recv_size(ch);
          if (n <= 0) break;
          std::vector<char> back((size_t)n);
          if (!tv_recv_into(ch, back.data(), (uint64_t)n)) break;
          if (back == req) {  // pump echo: punted or stamped-fresh
            aok.fetch_add(1);
            continue;
          }
          // native template: ack or refusal, worker id patched to OURS
          uint32_t rw = 0;
          if ((size_t)n >= 13) std::memcpy(&rw, back.data() + 1, 4);
          if ((back[0] == 0x06 || back[0] == 0x07) && rw == w) {
            aok.fetch_add(1);
            anative.fetch_add(1);
          }
        }
        tv_close(ch);
      });
    }
    for (auto& t : acls) t.join();
    astop.store(true);
    promoter.join();
    applier.join();
    astats.join();
    // ABI edge cases: malformed publishes refused, never crash
    uint64_t gnow = agen.load() + 100;
    if (nl_admit_put(loop, 9, "n0", 2, 7, 5, gnow) != 0) {  // lo > hi
      std::fprintf(stderr, "inverted admit window accepted\n");
      return 1;
    }
    if (nl_admit_put(loop, 9, "n0", 0, 5, 5, gnow) != 0) {  // empty nonce
      std::fprintf(stderr, "empty admit nonce accepted\n");
      return 1;
    }
    if (nl_admit_set_ack(loop, acktmpl.data(), 5, gnow) != 0) {
      std::fprintf(stderr, "short ack template accepted\n");
      return 1;
    }
    if (nl_admit_set_ack(loop, acktmpl.data(), acktmpl.size(), 0) != 0) {
      std::fprintf(stderr, "ack template below the floor accepted\n");
      return 1;
    }
    nl_admit_config(loop, -1);  // disable clears everything
    if (nl_admit_put(loop, 0, "n0", 2, 5, 5, gnow) != 0) {
      std::fprintf(stderr, "disabled admission accepted a put\n");
      return 1;
    }
    uint64_t as[8];
    nl_admit_stats(loop, as);
    nl_stop_accept(loop);
    nl_shutdown_conns(loop);
    nl_begin_stop(loop);
    apump.join();
    nl_stop(loop);
    tv_listener_close(alst);
    if (aok.load() < 400) {
      std::fprintf(stderr, "admit churn: only %d/600 round trips\n",
                   aok.load());
      return 1;
    }
    if (as[0] == 0 || as[2] == 0 || as[3] == 0) {
      std::fprintf(stderr,
                   "admit churn never exercised acks/fresh/punts: "
                   "a=%llu f=%llu p=%llu\n", (unsigned long long)as[0],
                   (unsigned long long)as[2], (unsigned long long)as[3]);
      return 1;
    }
    std::printf("nl admission churn: OK (%d ok, %d native, %llu stamped; "
                "acks=%llu refusals=%llu fresh=%llu punts=%llu)\n",
                aok.load(), anative.load(),
                (unsigned long long)stamped.load(),
                (unsigned long long)as[0], (unsigned long long)as[1],
                (unsigned long long)as[2], (unsigned long long)as[3]);
  }

  std::printf("tsan van driver: OK\n");
  return 0;
}
