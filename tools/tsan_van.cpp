// ThreadSanitizer driver for the native control-plane van (SURVEY.md §6:
// "any C++ control-plane code gets TSAN/ASAN"). Exercises every public ABI
// function from multiple threads concurrently — monitor rx thread, client tx
// threads, host poll threads, goodbye-while-beating, start/stop churn — so
// TSAN can observe any data race in van.cpp's threading model.
//
// Build + run: tools/tsan_van.sh (clean exit + no TSAN report = pass).

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <thread>
#include <vector>

extern "C" {
void* hb_server_start(const char* bind_addr, int port, int timeout_ms);
int hb_server_port(void* h);
int hb_server_poll(void* h, int state, uint32_t* out, int cap);
uint64_t hb_server_seq(void* h, uint32_t node_id);
void hb_server_stop(void* h);
void* hb_client_start(const char* host, int port, uint32_t node_id,
                      int interval_ms);
void hb_client_goodbye(void* h);
void hb_client_stop(void* h);
void* tv_listen(const char* bind_addr, int port, int backlog);
int tv_listener_port(void* h);
void* tv_accept(void* h, int timeout_ms);
void tv_listener_close(void* h);
void* tv_connect(const char* host, int port, int timeout_ms);
int tv_send(void* h, const void* buf, uint64_t n);
int64_t tv_recv_size(void* h);
int tv_recv_into(void* h, void* buf, uint64_t n);
void tv_close(void* h);
}

static void sleep_ms(int ms) {
  std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

int main() {
  void* srv = hb_server_start("127.0.0.1", 0, 300);
  if (!srv) { std::fprintf(stderr, "server start failed\n"); return 1; }
  int port = hb_server_port(srv);

  // 4 clients beating fast
  std::vector<void*> clients;
  for (uint32_t id = 1; id <= 4; ++id) {
    void* c = hb_client_start("127.0.0.1", port, id, 5);
    if (!c) { std::fprintf(stderr, "client %u start failed\n", id); return 1; }
    clients.push_back(c);
  }

  // 3 poller threads hammering every read path while beats arrive
  std::atomic<bool> stop{false};
  std::vector<std::thread> pollers;
  for (int t = 0; t < 3; ++t) {
    pollers.emplace_back([&] {
      uint32_t buf[16];
      while (!stop.load()) {
        for (int state = 0; state <= 2; ++state)
          hb_server_poll(srv, state, buf, 16);
        for (uint32_t id = 1; id <= 4; ++id) hb_server_seq(srv, id);
      }
    });
  }

  sleep_ms(100);
  // goodbye from one thread while its tx thread still beats (the
  // concurrent-sendto path), then a hard stop of another client
  hb_client_goodbye(clients[0]);
  hb_client_stop(clients[0]);
  hb_client_stop(clients[1]);  // silent death
  sleep_ms(400);               // past the horizon: states move under pollers

  uint32_t buf[16];
  int alive = hb_server_poll(srv, 0, buf, 16);
  int dead = hb_server_poll(srv, 1, buf, 16);
  int left = hb_server_poll(srv, 2, buf, 16);
  stop.store(true);
  for (auto& t : pollers) t.join();
  hb_client_stop(clients[2]);
  hb_client_stop(clients[3]);
  hb_server_stop(srv);
  std::printf("alive=%d dead=%d left=%d\n", alive, dead, left);
  if (alive != 2 || dead != 1 || left != 1) {
    std::fprintf(stderr, "unexpected states\n");
    return 1;
  }
  // --- tensor van: a server echoing frames to 3 concurrent client threads
  void* lst = tv_listen("127.0.0.1", 0, 8);
  if (!lst) { std::fprintf(stderr, "tv_listen failed\n"); return 1; }
  int tport = tv_listener_port(lst);
  std::atomic<int> echoed{0};
  std::thread server([&] {
    std::vector<std::thread> handlers;
    for (int i = 0; i < 3; ++i) {
      void* conn = tv_accept(lst, 2000);
      if (!conn) break;
      handlers.emplace_back([conn, &echoed] {
        for (;;) {
          int64_t n = tv_recv_size(conn);
          if (n < 0) break;
          std::vector<char> buf(n);
          if (!tv_recv_into(conn, buf.data(), n)) break;
          if (!tv_send(conn, buf.data(), n)) break;
          echoed.fetch_add(1);
        }
        tv_close(conn);
      });
    }
    for (auto& h : handlers) h.join();
  });
  std::vector<std::thread> tx;
  std::atomic<int> ok_frames{0};
  for (int t = 0; t < 3; ++t) {
    tx.emplace_back([&, t] {
      void* c = tv_connect("127.0.0.1", tport, 2000);
      if (!c) return;
      std::vector<char> payload(1 << 16, (char)t);
      for (int i = 0; i < 20; ++i) {
        if (!tv_send(c, payload.data(), payload.size())) break;
        int64_t n = tv_recv_size(c);
        if (n != (int64_t)payload.size()) break;
        std::vector<char> back(n);
        if (!tv_recv_into(c, back.data(), n)) break;
        ok_frames.fetch_add(back == payload ? 1 : 0);
      }
      tv_close(c);
    });
  }
  for (auto& t : tx) t.join();
  server.join();
  tv_listener_close(lst);
  std::printf("tv echoed=%d ok=%d\n", echoed.load(), ok_frames.load());
  if (ok_frames.load() != 60) {
    std::fprintf(stderr, "tensor van frames lost/corrupted\n");
    return 1;
  }
  std::printf("tsan van driver: OK\n");
  return 0;
}
