#!/usr/bin/env python
"""pslint — repo-aware static analysis for the ps-tpu data plane.

Usage::

    python tools/pslint.py ps_tpu/              # the CI/tier-1 gate
    python tools/pslint.py ps_tpu/ --json       # machine-readable
    python tools/pslint.py path/a.py path/b.py  # spot-check files
    python tools/pslint.py --list-rules

Exit status: 0 = clean (every finding fixed or suppressed-with-reason),
1 = findings, 2 = usage error.

By default, when the linted paths live inside this repository, the
repo's ``README.md`` joins as the doc side of the knob-drift rules and
``tools/*.py`` + ``bench.py`` join as *context* (consumers of STATS/
trace header keys live there; context files are read for evidence but
never reported on). ``--no-default-context`` disables that, ``--context``
adds more roots, ``--readme`` points elsewhere.

See ``ps_tpu/analysis/`` for the rule families and the README's
"Static analysis" section for the suppression syntax and how to add a
rule.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ps_tpu.analysis import all_rules, run_lint  # noqa: E402


def _default_context(paths, repo):
    """tools/ + bench.py as read-only evidence when linting repo code."""
    out = []
    tools = os.path.join(repo, "tools")
    if os.path.isdir(tools):
        out.append(tools)
    bench = os.path.join(repo, "bench.py")
    if os.path.isfile(bench):
        out.append(bench)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="pslint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--context", action="append", default=[],
                    help="extra read-only evidence roots (repeatable)")
    ap.add_argument("--readme", default=None,
                    help="README path for the knob-drift rules "
                         "(default: the repo's README.md)")
    ap.add_argument("--no-default-context", action="store_true",
                    help="do not auto-add tools/ + bench.py + README.md")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-family prefixes "
                         "(e.g. PSL1,PSL4); default: all")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for prefix, (doc, _fn) in sorted(all_rules().items()):
            print(f"{prefix}xx  {doc}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: python tools/pslint.py ps_tpu/)")

    context = list(args.context)
    readme = args.readme
    if not args.no_default_context:
        context += _default_context(args.paths, _REPO)
        if readme is None:
            cand = os.path.join(_REPO, "README.md")
            readme = cand if os.path.isfile(cand) else None
    # never lint what is also context; never let pslint lint itself into
    # its own evidence twice
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)

    try:
        findings = run_lint(args.paths, context=context, readme=readme,
                            rules=rules)
    except ValueError as e:
        ap.error(str(e))  # unknown --rules selection: exit 2, not 'clean'
    if args.as_json:
        print(json.dumps([vars(f) for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        sev = {}
        for f in findings:
            sev[f.severity] = sev.get(f.severity, 0) + 1
        if findings:
            counts = ", ".join(f"{k}: {v}" for k, v in sorted(sev.items()))
            print(f"pslint: {len(findings)} finding(s) ({counts})",
                  file=sys.stderr)
        else:
            print("pslint: clean", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
