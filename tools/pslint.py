#!/usr/bin/env python
"""pslint — repo-aware static analysis for the ps-tpu data plane.

Usage::

    python tools/pslint.py ps_tpu/              # the CI/tier-1 gate
    python tools/pslint.py ps_tpu/ --json       # machine-readable
    python tools/pslint.py path/a.py b.cpp      # spot-check files
    python tools/pslint.py ps_tpu/ --rules PSL5 PSL6   # native families
    python tools/pslint.py ps_tpu/ --native-only       # C++ + ABI only
    python tools/pslint.py ps_tpu/ --write-baseline lint.json
    python tools/pslint.py ps_tpu/ --baseline lint.json  # ratchet mode
    python tools/pslint.py --list-rules

Exit status: 0 = clean (every finding fixed or suppressed-with-reason;
with ``--baseline``, no finding OUTSIDE the snapshot), 1 = findings
(with ``--baseline``, NEW findings — the snapshot's are tolerated and
ones that disappeared are reported as fixed), 2 = usage error (unknown
--rules selection, missing baseline file, conflicting selectors).

By default, when the linted paths live inside this repository, the
repo's ``README.md`` joins as the doc side of the knob-drift rules and
``tools/*.py`` + ``bench.py`` join as *context* (consumers of STATS/
trace header keys live there; context files are read for evidence but
never reported on). C++ sources (``*.cpp`` — the native van and the
sanitizer driver) are collected from linted AND context roots and are
always linted: the native rule families (PSL5xx) and the ABI drift gate
(PSL6xx) bind them all. ``--no-default-context`` disables the
auto-context, ``--context`` adds more roots, ``--readme`` points
elsewhere.

``--baseline`` is the ratchet for future PRs: emit a snapshot once with
``--write-baseline``, then compare against it so new code cannot add
findings while the existing debt is burned down incrementally instead
of big-banged.

See ``ps_tpu/analysis/`` for the rule families and the README's
"Static analysis" section for the suppression/annotation syntax and how
to add a rule.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ps_tpu.analysis import all_rules, run_lint  # noqa: E402

#: the language split --native-only / --py-only select between
NATIVE_FAMILIES = ("PSL5", "PSL6")
PY_FAMILIES = ("PSL1", "PSL2", "PSL3", "PSL4")


def _default_context(paths, repo):
    """tools/ + bench.py as read-only evidence when linting repo code."""
    out = []
    tools = os.path.join(repo, "tools")
    if os.path.isdir(tools):
        out.append(tools)
    bench = os.path.join(repo, "bench.py")
    if os.path.isfile(bench):
        out.append(bench)
    return out


def _finding_key(f) -> dict:
    # line numbers shift with every edit, and several rules embed OTHER
    # locations' line numbers in their message ("at path:746", "line 52",
    # the C signature's site) — a ratchet baseline keys on (rule, path,
    # message with location digits normalized) so a refactor above a
    # finding (or above its cross-referenced site) does not thrash the
    # snapshot. Identical keys are counted, not deduped: a SECOND
    # occurrence of an already-baselined finding is still NEW (see
    # main()), so the ratchet's no-new-findings promise holds even for
    # rules whose messages carry no per-site detail.
    msg = re.sub(r"(?<=:)\d+", "_", f.message)
    msg = re.sub(r"\bline \d+", "line _", msg)
    return {"rule": f.rule, "path": f.path, "message": msg}


def _key_tuple(f):
    return tuple(sorted(_finding_key(f).items()))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="pslint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--context", action="append", default=[],
                    help="extra read-only evidence roots (repeatable)")
    ap.add_argument("--readme", default=None,
                    help="README path for the knob-drift rules "
                         "(default: the repo's README.md)")
    ap.add_argument("--no-default-context", action="store_true",
                    help="do not auto-add tools/ + bench.py + README.md")
    ap.add_argument("--rules", nargs="+", default=None, metavar="PSLn",
                    help="rule-family prefixes or concrete ids, space- "
                         "or comma-separated (e.g. --rules PSL5 PSL6)")
    ap.add_argument("--native-only", action="store_true",
                    help=f"only the native families "
                         f"{'/'.join(NATIVE_FAMILIES)} (C++ rules + the "
                         f"ctypes ABI drift gate)")
    ap.add_argument("--py-only", action="store_true",
                    help=f"only the Python families "
                         f"{'/'.join(PY_FAMILIES)}")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="compare against a findings snapshot: only "
                         "findings NOT in it fail the run (the ratchet)")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write the current findings as a snapshot for "
                         "--baseline and exit 0")
    ap.add_argument("--timings", action="store_true",
                    help="print per-family wall time to stderr (the CI "
                         "budget probe)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON array")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for prefix, (doc, _fn) in sorted(all_rules().items()):
            print(f"{prefix}xx  {doc}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: python tools/pslint.py ps_tpu/)")
    if sum([bool(args.rules), args.native_only, args.py_only]) > 1:
        ap.error("--rules, --native-only and --py-only are mutually "
                 "exclusive")
    if args.baseline and args.write_baseline:
        ap.error("--baseline and --write-baseline are mutually exclusive")

    context = list(args.context)
    readme = args.readme
    if not args.no_default_context:
        context += _default_context(args.paths, _REPO)
        if readme is None:
            cand = os.path.join(_REPO, "README.md")
            readme = cand if os.path.isfile(cand) else None
    # never lint what is also context; never let pslint lint itself into
    # its own evidence twice
    rules = None
    if args.rules:
        rules = [r.strip() for tok in args.rules
                 for r in tok.split(",") if r.strip()]
    elif args.native_only:
        rules = list(NATIVE_FAMILIES)
    elif args.py_only:
        rules = list(PY_FAMILIES)

    timings = {} if args.timings else None
    try:
        findings = run_lint(args.paths, context=context, readme=readme,
                            rules=rules, timings=timings)
    except ValueError as e:
        ap.error(str(e))  # unknown --rules selection: exit 2, not 'clean'
    if timings is not None:
        for prefix, secs in sorted(timings.items()):
            print(f"pslint: {prefix}xx {secs*1e3:7.1f} ms", file=sys.stderr)

    if args.write_baseline:
        snap = {"version": 1, "findings": [_finding_key(f)
                                           for f in findings]}
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(snap, f, indent=2)
        print(f"pslint: baseline with {len(findings)} finding(s) "
              f"written to {args.write_baseline}", file=sys.stderr)
        return 0

    fixed = 0
    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            ap.error(f"--baseline {args.baseline}: {e}")
        # multiset comparison: each key is tolerated only as many times
        # as the snapshot recorded it — a second wait_for in the same
        # file is NEW even though its key matches a baselined one
        old = collections.Counter(tuple(sorted(d.items()))
                                  for d in snap.get("findings", []))
        seen: collections.Counter = collections.Counter()
        new = []
        for f in findings:
            k = _key_tuple(f)
            seen[k] += 1
            if seen[k] > old.get(k, 0):
                new.append(f)
        fixed = sum((old - seen).values())
        findings = new

    if args.as_json:
        print(json.dumps([vars(f) for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        sev = {}
        for f in findings:
            sev[f.severity] = sev.get(f.severity, 0) + 1
        tag = "new " if args.baseline else ""
        if findings:
            counts = ", ".join(f"{k}: {v}" for k, v in sorted(sev.items()))
            print(f"pslint: {len(findings)} {tag}finding(s) ({counts})",
                  file=sys.stderr)
        else:
            print(f"pslint: clean{' vs baseline' if args.baseline else ''}",
                  file=sys.stderr)
        if args.baseline and fixed > 0:
            print(f"pslint: {fixed} baseline finding(s) no longer fire — "
                  f"regenerate with --write-baseline to ratchet down",
                  file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
