"""Single-chip characterization harness (VERDICT r2 item 1; r5: +BERT).

Runs the same fused PS step as bench.py on the real chip, and reports the
numbers the bench's one-line JSON cannot: XLA cost-analysis FLOPs/step, MFU
against the detected chip peak, a jax.profiler trace, and the top op-level
time sinks parsed from the trace (via xprof's xspace converter). Use this to
decide tuning, then fold the distilled metrics into bench.py.

Usage: python tools/characterize.py [--model resnet|bert] [--batch 256]
       [--steps 12] [--trace-dir /tmp/ps_trace]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the image preloads jax pinned to the TPU platform; the env var must
    # win here so CPU smoke runs (tests/test_tools.py) measure on CPU
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

import ps_tpu as ps
from ps_tpu.data.synthetic import imagenet_batches
from ps_tpu.models.resnet import ResNet50, make_loss_fn
from ps_tpu.parallel.sharding import replicated



def detect_peak_tflops(device):
    from ps_tpu.utils.chips import peak_bf16_tflops

    return peak_bf16_tflops(device)


def top_op_sinks(trace_dir: str, k: int = 10):
    """Parse the .xplane.pb under trace_dir; return top-k ops by self time."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        return None
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([paths[-1]], "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    rows = json.loads(data)
    # framework_op_stats JSON: list of tables; first is by-op records
    return rows, paths[-1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet", choices=["resnet", "bert"])
    ap.add_argument("--batch", type=int, default=None,
                    help="default: 256 (resnet) / 128 (bert)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--trace-dir", default="/tmp/ps_trace")
    ap.add_argument("--placement", default="replicated")
    ap.add_argument("--no-trace", action="store_true")
    args = ap.parse_args()
    if args.batch is None:
        args.batch = 256 if args.model == "resnet" else 128
    if args.model == "bert":
        return char_bert(args)

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    print(f"device: {dev.device_kind} ({dev.platform}) x{len(jax.devices())}")

    ctx = ps.init(backend="tpu")
    model = ResNet50(dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    variables = model.init(
        jax.random.key(0), jnp.zeros((2, args.image_size, args.image_size, 3)),
        train=False,
    )
    params, model_state = variables["params"], variables["batch_stats"]
    model_state = jax.device_put(model_state, replicated(ctx.mesh))

    store = ps.KVStore(optimizer="momentum", learning_rate=0.1, momentum=0.9,
                       placement=args.placement)
    store.init(params)
    run = store.make_step(make_loss_fn(model, label_smoothing=0.1), has_aux=True)

    batches = [
        store.shard_batch((jnp.asarray(images), jnp.asarray(labels)))
        for images, labels in imagenet_batches(
            args.batch, image_size=args.image_size, steps=3
        )
    ]
    jax.block_until_ready(batches)

    # Warmup (compile + relayout); timing below is steady state.
    for step in range(2):
        loss, _, model_state = run(batches[step % len(batches)], model_state)
    loss.block_until_ready()

    t0 = time.time()
    for step in range(args.steps):
        loss, _, model_state = run(batches[step % len(batches)], model_state)
    loss.block_until_ready()
    jax.block_until_ready(store.params())
    dt = time.time() - t0
    ips = args.steps * args.batch / dt
    print(f"throughput: {ips:.1f} imgs/sec  ({dt/args.steps*1e3:.2f} ms/step)"
          f"  loss={float(loss):.4f}")

    # HLO cost analysis of the exact fused step (the axon TPU plugin's
    # lowering returns None — the CPU backend measures the same program;
    # bench.py carries the resulting per-image constant)
    try:
        ca = run.cost_analysis(batches[0], model_state)
    except Exception:
        ca = None
    if ca and ca.get("flops"):
        flops = float(ca["flops"])
        print(f"flops/step (HLO): {flops:.3e}  "
              f"sustained: {flops * args.steps / dt / 1e12:.1f} TFLOPS")
    else:
        print("flops: live cost analysis unavailable on this platform "
              "(run on JAX_PLATFORMS=cpu for the HLO numbers)")

    peak = detect_peak_tflops(dev)
    if peak:
        print(f"chip peak (bf16): {peak} TFLOPS")

    if not args.no_trace and on_tpu:
        os.makedirs(args.trace_dir, exist_ok=True)
        with jax.profiler.trace(args.trace_dir):
            for step in range(4):
                loss, _, model_state = run(batches[step % len(batches)], model_state)
            loss.block_until_ready()
        print(f"trace written to {args.trace_dir}")
        try:
            rows, path = top_op_sinks(args.trace_dir)
            out = os.path.join(args.trace_dir, "op_stats.json")
            with open(out, "w") as f:
                json.dump(rows, f, indent=1)
            print(f"op stats -> {out}")
        except Exception as e:
            print("trace parse failed:", e)


def char_bert(args):
    """BERT-base MLM + LAMB: the bench_bert step, traced."""
    from ps_tpu.data.synthetic import mlm_batches
    from ps_tpu.models.bert import BertConfig, BertMLM, make_mlm_loss_fn

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    print(f"device: {dev.device_kind} ({dev.platform}) x{len(jax.devices())}")

    ps.init(backend="tpu")
    cfg = BertConfig(dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    model = BertMLM(cfg)
    shape = (2, args.seq_len)
    params = model.init(jax.random.key(0), jnp.zeros(shape, jnp.int32),
                        jnp.ones(shape, jnp.int32))["params"]
    store = ps.KVStore(optimizer="lamb", learning_rate=1e-3,
                       weight_decay=0.01, placement=args.placement)
    store.init(params)
    run = store.make_step(make_mlm_loss_fn(model))
    batches = [
        store.shard_batch({k: jnp.asarray(v) for k, v in b.items()})
        for b in mlm_batches(args.batch, args.seq_len,
                             vocab_size=cfg.vocab_size, steps=3)
    ]
    jax.block_until_ready(batches)
    for step in range(2):
        loss, _ = run(batches[step % 3])
    loss.block_until_ready()

    t0 = time.time()
    for step in range(args.steps):
        loss, _ = run(batches[step % 3])
    loss.block_until_ready()
    jax.block_until_ready(store.params())
    dt = time.time() - t0
    print(f"throughput: {args.steps * args.batch / dt:.1f} seqs/sec  "
          f"({dt/args.steps*1e3:.2f} ms/step)  loss={float(loss):.4f}")

    peak = detect_peak_tflops(dev)
    if peak:
        print(f"chip peak (bf16): {peak} TFLOPS")

    if not args.no_trace and on_tpu:
        os.makedirs(args.trace_dir, exist_ok=True)
        with jax.profiler.trace(args.trace_dir):
            for step in range(4):
                loss, _ = run(batches[step % 3])
            loss.block_until_ready()
        print(f"trace written to {args.trace_dir}")
        try:
            rows, path = top_op_sinks(args.trace_dir)
            out = os.path.join(args.trace_dir, "op_stats.json")
            with open(out, "w") as f:
                json.dump(rows, f, indent=1)
            print(f"op stats -> {out}")
        except Exception as e:
            print("trace parse failed:", e)


if __name__ == "__main__":
    main()
