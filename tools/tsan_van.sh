#!/usr/bin/env bash
# ThreadSanitizer run for the native control-plane van (SURVEY.md §6).
# Builds van.cpp + the concurrency driver with -fsanitize=thread and runs it;
# any data race aborts with a TSAN report and a non-zero exit.
#
# Usage: tools/tsan_van.sh   (from the repo root; also wired into
# tests/test_failure.py::test_tsan_van_clean)
set -euo pipefail
cd "$(dirname "$0")/.."
out=$(mktemp -d)
trap 'rm -rf "$out"' EXIT
g++ -std=c++17 -O1 -g -fsanitize=thread -fno-omit-frame-pointer \
    ps_tpu/native/van.cpp tools/tsan_van.cpp -o "$out/tsan_van" -lpthread
TSAN_OPTIONS="halt_on_error=1 exitcode=66" "$out/tsan_van"
echo "TSAN: clean"
