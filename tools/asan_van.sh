#!/usr/bin/env bash
# AddressSanitizer + UndefinedBehaviorSanitizer run for the native van —
# the memory-safety sibling of tools/tsan_van.sh (same driver, different
# sanitizers: TSan sees races, ASan sees heap/stack misuse and leaks in
# the handle lifecycle, UBSan sees signed overflow / bad casts in the
# framing math). Wired into tools/ci_lint.sh and
# tests/test_failure.py::test_asan_van_clean (slow-marked), runnable
# standalone from the repo root: tools/asan_van.sh
set -euo pipefail
cd "$(dirname "$0")/.."
out=$(mktemp -d)
trap 'rm -rf "$out"' EXIT
g++ -std=c++17 -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer \
    ps_tpu/native/van.cpp tools/tsan_van.cpp -o "$out/asan_van" -lpthread
# halt_on_error: any report fails the leg; detect_leaks catches lost
# Conn/Listener/Server handles (the drivers close everything they open)
ASAN_OPTIONS="halt_on_error=1 detect_leaks=1" \
UBSAN_OPTIONS="halt_on_error=1 print_stacktrace=1" \
    "$out/asan_van"
echo "ASAN/UBSAN: clean"
