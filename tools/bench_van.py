"""Measure the van data plane at realistic tree sizes — VERDICT r4 item 6.

Drives :func:`ps_tpu.backends.remote_async.serve_async` with a BERT-base-
shaped parameter tree (~0.44 GB f32) over loopback TCP and reports wall
time + GB/s for pulls and push_pull cycles, single- and multi-worker (the
multi-worker concurrent pull is what the r4 lock-held serialization
throttled: every worker's pull serialized behind every apply). Numbers go
to BASELINE.md.

Run:  python tools/bench_van.py [--mb 440] [--cycles 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bert_like_tree(target_mb: float) -> dict:
    """Flat {key: f32 array} tree shaped like BERT-base: one [30522,768]
    embedding + uniform ~[768,768]x4-ish blocks until target_mb."""
    tree = {"embed/word": np.zeros((30522, 768), np.float32)}
    total = tree["embed/word"].nbytes
    i = 0
    while total < target_mb * 1e6:
        a = np.zeros((768, 3072), np.float32)  # 9.4 MB, FFN-block-sized
        tree[f"layer{i//4}/block{i%4}"] = a
        total += a.nbytes
        i += 1
    return tree


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=440.0)
    ap.add_argument("--cycles", type=int, default=4)
    ap.add_argument("--workers", type=int, default=2)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import ps_tpu as ps
    from ps_tpu.backends.remote_async import connect_async, serve_async

    params = bert_like_tree(args.mb)
    nbytes = sum(a.nbytes for a in params.values())
    print(f"tree: {len(params)} tensors, {nbytes/1e6:.0f} MB", file=sys.stderr)

    ps.init(backend="tpu", mode="async", num_workers=args.workers)
    store = ps.KVStore(optimizer="sgd", learning_rate=0.01, mode="async")
    store.init(params)
    svc = serve_async(store, bind="127.0.0.1")
    uri = f"127.0.0.1:{svc.port}"

    out = {"tree_mb": round(nbytes / 1e6, 1), "tensors": len(params)}

    # single-worker pull latency/bandwidth
    w0 = connect_async(uri, 0, params)
    t0 = time.monotonic()
    for _ in range(args.cycles):
        w0.pull_all()
    dt = time.monotonic() - t0
    out["pull_s"] = round(dt / args.cycles, 3)
    out["pull_gbps"] = round(w0.bytes_pulled / dt / 1e9, 3)

    # single-worker push_pull (the async cycle: grads up, params down)
    grads = {k: np.zeros_like(v) for k, v in params.items()}
    b0 = w0.bytes_pushed + w0.bytes_pulled
    t0 = time.monotonic()
    for _ in range(args.cycles):
        w0.push_pull(grads)
    dt = time.monotonic() - t0
    moved = w0.bytes_pushed + w0.bytes_pulled - b0
    out["push_pull_s"] = round(dt / args.cycles, 3)
    out["push_pull_gbps"] = round(moved / dt / 1e9, 3)

    # N workers pulling CONCURRENTLY — the lock-held-serialization probe:
    # before the r5 fix every pull serialized behind the engine lock, so
    # aggregate GB/s could not exceed single-worker GB/s.
    ws = [w0] + [connect_async(uri, w, params)
                 for w in range(1, args.workers)]
    for w in ws:
        w.bytes_pulled = 0
    t0 = time.monotonic()

    def pull_loop(w):
        for _ in range(args.cycles):
            w.pull_all()

    ts = [threading.Thread(target=pull_loop, args=(w,)) for w in ws]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.monotonic() - t0
    total = sum(w.bytes_pulled for w in ws)
    out[f"concurrent_pull_{args.workers}w_gbps"] = round(total / dt / 1e9, 3)

    for w in ws:
        w.close()
    svc.stop()
    ps.shutdown()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
